"""Continuous-batching scheduler: concurrent serving on one persistent cache.

This is the TPU-native replacement for Ollama's request queue + llama.cpp's
slot scheduler (the reference serializes everything: one blocking
`ollama.generate` per HTTP handler, reference `Flask/app.py:102-107`,
`FastAPI/app.py:85-90`). Concurrent FastAPI requests here share ONE decode
batch on the device (BASELINE.json config 5: mixed NL→SQL + error-analysis
serving), instead of queueing behind a per-backend lock.

Design (slot-based continuous batching, TPU/XLA-shaped):

- A fixed pool of `num_slots` sequence slots backs a persistent KV cache
  [L, num_slots, S_max, K, H] that lives across jit calls. Both jitted
  programs donate the cache buffers, so XLA updates HBM in place — no
  per-request allocation, no growth, static shapes forever.
- **Prefill** is one jitted fn per prompt-length bucket: run the prompt
  through the stack against the slot's cache row (sliced out with
  `dynamic_slice`, written back with `dynamic_update_slice`) and sample the
  first token.
- **Decode** is one jitted fn total: a `lax.scan` of `decode_chunk` single
  token steps over the whole slot batch. Chunking amortizes the host↔device
  sync to 1/chunk per token; the host inspects tokens between chunks to
  retire finished sequences and admit pending ones into freed slots.
- Mixed sampling rides per-slot runtime arrays (ops/sampling.sample_runtime):
  greedy SQL generation and temperature/top-p/top-k error analysis share one
  compiled decode program.
- Per-request RNG streams: slot s samples token i with
  `fold_in(key(request_seed), i)` — each request owns an independent seeded
  stream, so resubmitting (prompt, seed, sampling) reproduces the same
  completion no matter what other traffic shares the batch (asserted in
  tests/test_scheduler.py).
- Free slots keep decoding garbage at a frozen position. That is safe by the
  cache-visibility invariant (engine/kvcache.py): admission prefill
  overwrites slots [0, T), and beyond T the new sequence's own decode writes
  position p before p ever becomes visible to attention.
- **Prefix caching** (block-chained, vLLM-style at block granularity): the
  NL→SQL workload repeats one system prefix — the table schema — across
  every request for a table (reference `Flask/app.py:102-106` rebuilds the
  same system prompt per query). K/V for completed prefix blocks of
  `_pblock` tokens is kept in an LRU keyed by the *token content* of the
  whole prefix up to that block (hash-chain semantics: a block is reusable
  only when everything before it matched too). Admission copies matching
  blocks into the slot's cache rows device-to-device and skips their
  prefill entirely. Content keys mean no invalidation is ever needed, and
  positions line up because a shared prefix occupies the same absolute
  positions [0, n) in every request. Memory: one block for a 7B bf16 model
  is ~17 MB (2·L·K·16·H·2B); `prefix_cache_blocks` caps the LRU (0
  disables).
- Tensor parallelism: pass a mesh with dp=1 — request parallelism comes from
  slots (the batch axis stays unsharded because slots are dynamically
  indexed), TP shards heads/MLP exactly as in engine/generate.py.
- Data parallelism (dp>1) is request-level BY DESIGN: the slot axis cannot
  shard (dynamic per-slot cache indexing), so dp means independent scheduler
  replicas — each with its own params copy and tp-submesh — behind one
  `SchedulerPool`, a supervised FLEET with least-loaded deadline-aware
  placement and per-replica lifecycle (targeted restart/drain — see the
  SchedulerPool docstring). That matches the workload: serving throughput
  scales with independent replicas; there is no gradient all-reduce to
  motivate a fused dp program (inference-only framework).
- **int8 KV cache** (`kv_quant="int8"`): the persistent window stores int8
  values + per-slot f32 scales (ops/quant.quantize_kv) — half the HBM
  footprint and decode streaming. Decode runs the int8-streaming einsum
  attention, or — past the cost crossover where a large mostly-dead
  window pays for per-row bounded streaming — the quantized flash kernel
  (ops.pallas.flash_gqa_attention_quantized: int8 bytes AND kv_lens
  bounding stacked). Chunked prefill dequantizes the gathered rows for
  the chunk forward and requantizes only its own window on scatter-back.
- **Streaming + cancellation**: `submit(on_token=...)` delivers accepted
  tokens in order from the worker thread (SchedulerBackend.complete_stream
  turns them into clean text deltas, byte-identical to the blocking path);
  `cancel(future)` retires an abandoned request at its next harvest so
  disconnected clients do not pin slots.
- **Speculative decoding** (`speculative_draft=D`): decode rounds become
  draft+verify rounds — each slot drafts D tokens by prompt lookup over an
  on-device token history (prompt tokens scattered in by the prefill fn,
  emits appended by the round itself) and one T=D+1 forward verifies the
  whole batch. Greedy slots emit their accepted chain (1..D+1 tokens per
  round, exactly vanilla-greedy output); temperature>0 slots emit their
  rejection-sampling chain (1..D+1 tokens per round: draft i accepted
  with min(1, p/q) under the target distribution — a delta q for these
  deterministic drafts — and the first rejection resampled from the
  normalized residual, engine/speculative.rejection_sample_chain), so
  sampled output is DISTRIBUTION-identical to vanilla sample_runtime
  decode and every request class gets the draft/verify speedup on ONE
  compiled program. The verify window runs the unrolled small-T einsum
  path, which also composes with the int8 KV cache. Prefix-cache reuse
  is disabled in this mode (reused tokens never reach the draft
  history). Grammar-constrained requests compose: the draft chain
  advances the slot's FSM per position (constrain.fsm_advance_chain),
  every verify logit row is masked with its own position's state BEFORE
  the accept test (so the sampled residual is grammar-renormalized and
  grammar-rejected drafts carry zero target mass), acceptance caps at
  the grammar-valid prefix, and the committed state rewinds past
  nothing — constrained+speculative greedy output is token-identical to
  constrained vanilla decode, and speculation_stats splits acceptance by
  constrained/unconstrained AND greedy/sampled class.

- **Async issue/harvest pipeline**: decode rounds, prompt chunks and
  admission scatters dispatch without waiting; per-slot state (cur/pos/
  sampling knobs/RNG counts) lives on device and chains between rounds.
  The host syncs exactly once per round — to harvest the OLDEST in-flight
  round's tokens, `_harvest_lag` rounds behind the issue frontier — so the
  transfer round-trip overlaps the next rounds' compute. This is what makes
  the loop fast over a high-latency device transport (the measured
  bottleneck was sync latency, not device FLOPs) and costs one chunk of
  retirement/admission latency.

Bounds: a request needs `bucket_len(prompt) + max_new + overshoot <= S_max`
(see the `overshoot` property: (harvest_lag+1) rounds of decode_chunk — or
of D+1 plus a verify window's write lookahead under speculation) — the
device can run past a budget or a stop token for up to that many positions
before the host notices (those tokens are discarded; their cache writes are
garbage covered by the invariant above).
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import queue
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..constrain.masks import CompiledMask, trivial_tables
from ..engine.kvcache import bucket_len, init_cache
from ..engine.paged_kv import (
    PageAllocator,
    default_page_size,
    export_pages,
    handoff_bytes,
    import_pages,
    init_page_pool,
    page_bytes,
    pages_for_budget,
    pages_for_tokens,
)
from ..models.configs import LlamaConfig
from ..models.llama import Params, forward, split_blocks
from ..ops.pallas import attention_impl, decode_attention_impl
from ..ops.sampling import (
    SamplingParams,
    apply_token_mask,
    filtered_runtime_logits,
    sample_runtime,
)
from ..parallel.sharding import shard_params, validate_tp
from ..utils import traceprof
from ..utils.faults import FAULTS, InjectedFault
from ..utils.observability import resilience
from ..utils.perfmodel import PerfModel
from .flightrecorder import FlightRecorder, merge_snapshots
from .resilience import (
    Deadline,
    DeadlineExceeded,
    Overloaded,
    SchedulerCrashed,
    SlotStalled,
)
from .watchdog import CombinedHeartbeat, Heartbeat

_log = logging.getLogger("lsot.scheduler")

#: Scheduler phase roles (ISSUE 13 — disaggregated prefill/decode
#: serving). "mixed" (the default) is today's behavior bit for bit; a
#: "prefill" replica runs chunked prefill to completion, packs the
#: request's KV pages into a portable handoff blob and retires it into a
#: handoff queue instead of entering its decode loop; a "decode" replica
#: is a routing preference — full mixed capability, but the pool's
#: phase-aware router sends it migrated requests and keeps fresh prompts
#: off it.
PHASE_ROLES = ("mixed", "prefill", "decode")


def parse_pool_phases(spec: str, replicas: int) -> List[str]:
    """Parse LSOT_POOL_PHASES ("prefill:1,decode:3") into a per-replica
    role list of length `replicas`. Empty/None spec means an all-"mixed"
    fleet (the pre-disaggregation behavior). Counts must sum to the
    replica count, and a fleet with any "prefill" replica must also have
    somewhere for its handoffs to decode ("decode" or "mixed") — a
    prefill-only fleet would silently fall back to decoding in place on
    every request, which is a misconfiguration, not a deployment."""
    if not spec:
        return ["mixed"] * replicas
    roles: List[str] = []
    for entry in filter(None, (s.strip() for s in spec.split(","))):
        parts = entry.split(":")
        if len(parts) != 2:
            raise ValueError(
                f"bad pool-phases entry {entry!r} (want role:count)"
            )
        role, n = parts[0].strip(), parts[1].strip()
        if role not in PHASE_ROLES:
            raise ValueError(
                f"bad phase role {role!r} (want one of {PHASE_ROLES})"
            )
        try:
            count = int(n)
        except ValueError:
            raise ValueError(f"bad replica count in {entry!r}") from None
        if count < 1:
            raise ValueError(f"replica count must be >= 1 in {entry!r}")
        roles.extend([role] * count)
    if len(roles) != replicas:
        raise ValueError(
            f"pool phases {spec!r} describe {len(roles)} replica(s) but "
            f"the pool has {replicas}"
        )
    if "prefill" in roles and not any(
            r in ("decode", "mixed") for r in roles):
        raise ValueError(
            f"pool phases {spec!r} have prefill replicas but no decode/"
            f"mixed replica to hand off to"
        )
    return roles


def normalize_replica_weights(values: Sequence[float], replicas: int,
                              label: str = "replica weights"
                              ) -> List[float]:
    """ONE pad/validate policy for replica capacity weights, shared by
    the LSOT_REPLICA_WEIGHTS spec parser and SchedulerPool's explicit
    `weights=` argument: positive floats, at most one per replica
    (more is a misconfigured fleet and raises — never a silent
    truncation), padded with 1.0."""
    out = [float(w) for w in values]
    for w in out:
        if w <= 0:
            raise ValueError(
                f"replica weights must be positive, got {w} in {label}")
    if len(out) > replicas:
        raise ValueError(
            f"{label} name {len(out)} replica(s) but the pool has "
            f"{replicas}"
        )
    return out + [1.0] * (replicas - len(out))


def parse_replica_weights(spec: str, replicas: int) -> List[float]:
    """Parse LSOT_REPLICA_WEIGHTS ("4,1,1" — one positive capacity
    multiplier per replica index) into a weight list of length
    `replicas`, padded with 1.0. A tp=4 replica weighted 4 takes
    proportionally more token mass than a tp=1 sibling: placement
    ORDERING compares backlog DIVIDED by weight (deadline feasibility
    stays wall-clock). Empty spec = all 1.0, which is bit-identical to
    the unweighted order."""
    if not spec:
        return [1.0] * replicas
    out: List[float] = []
    for entry in filter(None, (s.strip() for s in spec.split(","))):
        try:
            out.append(float(entry))
        except ValueError:
            raise ValueError(
                f"bad replica weight {entry!r} in {spec!r}") from None
    return normalize_replica_weights(out, replicas,
                                     label=f"replica weights {spec!r}")


#: Prefix-cache telemetry bounds (ISSUE 14): how many registry entries
#: /debug/prefixcache returns per replica (top-K by token mass) and how
#: many recent admissions the reuse-distance ring remembers. App-startup
#: overrides via `reconfigure_prefix_telemetry` (AppConfig.prefix_topk /
#: prefix_ring — the same wiring seam as flightrecorder.reconfigure);
#: None falls through to the LSOT_PREFIX_TOPK / LSOT_PREFIX_RING env
#: reads below.
_PREFIX_TOPK: Optional[int] = None
_PREFIX_RING: Optional[int] = None


def reconfigure_prefix_telemetry(top_k: Optional[int] = None,
                                 ring: Optional[int] = None) -> None:
    """Set the prefix-registry bounds schedulers constructed AFTER this
    call will use (app/__main__ wires AppConfig.prefix_topk/prefix_ring
    through here, so the knobs are documented config, not hidden env)."""
    global _PREFIX_TOPK, _PREFIX_RING
    _PREFIX_TOPK = int(top_k) if top_k else None
    _PREFIX_RING = int(ring) if ring else None


def _prefix_bound(configured: Optional[int], env: str, default: int) -> int:
    if configured is not None:
        return max(1, configured)
    try:
        n = int(os.environ.get(env, str(default)))
    except ValueError:
        n = default
    return max(1, n)


def prefix_digest(ids: Sequence[int]) -> str:
    """Stable content address of a token prefix: blake2b over the int32
    token ids, 16 hex chars. Deterministic across processes and replicas
    — the SAME schema prefix hashes to the SAME digest fleet-wide, which
    is what lets `SchedulerPool.prefix_affinity` compare a request's
    prefix against every replica's resident set without shipping token
    lists around (ISSUE 14)."""
    return hashlib.blake2b(
        np.asarray(ids, np.int32).tobytes(), digest_size=8
    ).hexdigest()


def prefix_chain_digests(ids: Sequence[int], block: int,
                         ns: Sequence[int] = ()) -> List[str]:
    """Digests of every whole-block prefix of a prompt (the hash-chain
    keys' content addresses): what a cache-aware router hands to
    `SchedulerPool.prefix_affinity` — a replica holding ANY chain prefix
    of the request saves that much re-prefill, so affinity matches on
    the whole chain, not just the longest prefix. `ns` is the tenant
    namespace salt (ISSUE 18): when per-tenant prefix namespacing is on,
    the router salts here exactly as admission salts its cache keys, so
    fleet-wide affinity still matches — within one tenant only."""
    base = tuple(ns)
    return [
        prefix_digest(base + tuple(ids[: (j + 1) * block]))
        for j in range(max(0, (len(ids) - 1) // block))
    ]


def _rd_buckets(ring_cap: int) -> Tuple[int, ...]:
    """Reuse-distance histogram buckets (admissions between consecutive
    sightings of the same prefix digest): le-style powers of two up to
    the ring cap, so a ring configured wider than the default still
    buckets its whole window instead of dumping the tail into "inf". A
    distance histogram bounded by the ring answers "would a cache of N
    entries have held this working set" — the capacity-planning
    readout."""
    b, buckets = 1, []
    while b < ring_cap:
        buckets.append(b)
        b *= 2
    buckets.append(ring_cap)
    return tuple(buckets)


def _first_token_timer(then: Optional[Callable[[int], None]] = None):
    """(on_token, first_at) pair for TTFT measurement: on_token records the
    worker-thread harvest time of the request's first ACCEPTED token — its
    true time-to-first-token origin (queueing + prefill + first harvest
    lag) — into the returned list, then forwards the token to `then`."""
    first_at: List[float] = []

    def on_tok(tok: int) -> None:
        if not first_at:
            first_at.append(time.perf_counter())
        if then is not None:
            then(tok)

    return on_tok, first_at


def _cache_dict(arrs: Sequence[jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Tuple-of-arrays cache -> the dict form models/llama.forward takes."""
    if len(arrs) == 2:
        return {"k": arrs[0], "v": arrs[1]}
    return {"k8": arrs[0], "ks": arrs[1], "v8": arrs[2], "vs": arrs[3]}


def _cache_tuple(d: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, ...]:
    if "k8" in d:
        return (d["k8"], d["ks"], d["v8"], d["vs"])
    return (d["k"], d["v"])


def _paged_cache_dict(
    arrs: Sequence[jnp.ndarray], ptab: jnp.ndarray
) -> Dict[str, jnp.ndarray]:
    """Paged cache tuple -> the dict form models/llama.forward takes:
    (kp, vp) for a compute-dtype pool, (kp, kps, vp, vps) for the int8
    pool (values + per-position scales, mirroring the contiguous
    (k8, ks, v8, vs) ordering)."""
    if len(arrs) == 2:
        return {"kp": arrs[0], "vp": arrs[1], "ptab": ptab}
    return {"kp": arrs[0], "kps": arrs[1], "vp": arrs[2], "vps": arrs[3],
            "ptab": ptab}


def _paged_cache_tuple(d: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, ...]:
    if "kps" in d:
        return (d["kp"], d["kps"], d["vp"], d["vps"])
    return (d["kp"], d["vp"])


@dataclasses.dataclass
class _Request:
    ids: List[int]
    max_new: int
    temperature: float
    top_p: float
    top_k: int
    seed: int
    future: Future
    # Streaming: called from the worker thread with each ACCEPTED token id,
    # in order, before the future resolves (overshoot/stop tokens never
    # reach it). Must be fast and non-blocking; exceptions are swallowed so
    # a broken consumer cannot kill the serving loop.
    on_token: Optional[Callable[[int], None]] = None
    # Cooperative cancellation (set via Scheduler.cancel): the worker
    # retires the request at its next harvest instead of decoding the rest
    # of the budget into an abandoned consumer (client disconnects must not
    # pin slots).
    cancelled: bool = False
    # Per-request deadline (serve/resilience.Deadline), threaded submit →
    # queue → decode: expired queued requests fail fast at admission
    # (never occupying a slot); expired in-flight requests are retired at
    # the next harvest through the same path cancellation uses — either
    # way the future fails with a typed DeadlineExceeded.
    deadline: Optional[Deadline] = None
    # Grammar-constrained decoding (constrain.CompiledMask): the slot's
    # on-device DFA state starts at constraint.init_state and every decode
    # step applies the state's precomputed vocabulary mask. None = free.
    constraint: Optional[CompiledMask] = None
    # live state (set at admission)
    generated: List[int] = dataclasses.field(default_factory=list)
    # chunked-prefill progress: prompt tokens already written to the cache.
    # A slot is decode-eligible only once the whole prompt is in (`ready`).
    prefilled: int = 0
    ready: bool = False
    # Submit wall-clock origin: feeds the per-request service-time EWMA
    # behind retry_after_hint() (queue-depth-aware Retry-After).
    submitted_at: float = 0.0
    # Per-slot stall retirement: consecutive harvest rounds in which this
    # request's slot appended nothing while OTHER slots advanced. At
    # `slot_stall_rounds` the slot is retired typed (SlotStalled/504)
    # instead of occupying a decode lane forever. `stall_inject` is the
    # chaos seam (`sched:slot_stall` site, set at admission): the harvest
    # treats the slot's round output as empty, simulating a lane the
    # device produces nothing useful for.
    stall_rounds: int = 0
    stall_inject: bool = False
    # Observability (ISSUE 6): a scheduler-scope monotonic request id
    # (flight-recorder attribution: which rids a round admitted/retired),
    # the request's RequestTrace when it was head-sampled
    # (utils/tracing.py — the worker thread records queue-wait / prefill /
    # per-round decode spans into it), and the wall stamps those spans
    # are cut from.
    rid: int = 0
    trace: Optional[object] = None
    admitted_at: float = 0.0
    ready_at: float = 0.0
    # Multi-model serving (ISSUE 16): the checkpoint this request's KV
    # was (or will be) written by — stamped at submit from the owning
    # scheduler, carried on requeue/extract wire frames so a migrated
    # request can only land on a same-model replica ("" = the
    # single-model fleet).
    model_id: str = ""
    # Paged KV (kv_layout="paged"): highest cache position (exclusive) this
    # request's prefill+decode can ever write — admission allocated pages
    # covering exactly [0, page_end), and the ready-time ensure-writable
    # sweep COWs any published page the decode range intersects.
    page_end: int = 0
    # Already counted in page_waits: the admission loop retries a starved
    # request every iteration, and the metric must count REQUESTS that
    # waited, not retry attempts.
    page_waited: bool = False
    # Victim preemption (ISSUE 10): `resume_pref` is how many COMMITTED
    # generated tokens are folded into the prefill prefix for the next
    # admission — resume re-runs prefill over prompt + generated-so-far
    # (recompute mode), and the continuation appends to the same
    # `generated` list, so clients never see a token twice. `rng_count`
    # mirrors the slot's on-device RNG stream index at the last harvest
    # (tokens sampled so far for vanilla decode, 1 + sampled rounds for
    # speculative) — restoring it at resume is what makes a preempted
    # SAMPLED request's continuation token-identical to an unpreempted
    # control (the fold_in(key(seed), count) contract). `spilled` holds
    # host-side page copies under LSOT_KV_SPILL=1 (restore mode skips the
    # re-prefill entirely).
    resume_pref: int = 0
    preempted: int = 0
    rng_count: int = 0
    # Parked intervals [t_preempt, t_resume-or-0.0] for the request trace
    # tree: a victim's Perfetto export shows WHERE its latency went while
    # it sat preempted off the device (flush_spans emits one
    # "sched.preempted" span per interval — ISSUE 12 satellite; PR 10
    # only emitted flight-recorder events, so a victim's timeline had an
    # unexplained hole exactly over the preemption).
    parked: List[List[float]] = dataclasses.field(default_factory=list)
    # Host page copies under LSOT_KV_SPILL=1: one array per cache array —
    # (k, v) for a compute-dtype pool, (k8, ks, v8, vs) for the int8 pool
    # (the quantization scales serialize beside the pages, so restore is
    # content-exact).
    spilled: Optional[Tuple[np.ndarray, ...]] = None
    # Prefill→decode handoff metadata (ISSUE 13): set when a prefill-role
    # replica packed this request's KV into `spilled` for migration —
    # {"t_pack", "export_s", "pages", "bytes", "src"} — and cleared by
    # the importing replica's resume, which turns it into the
    # `sched.handoff` trace span + the pages_migrated/handoff_wait_s
    # flight columns. None everywhere outside a live handoff, so the
    # spill-resume paths can tell a migrated blob from a preemption spill
    # (different counters, same restore machinery).
    handoff: Optional[Dict] = None
    # Prefix-cache reuse attribution (ISSUE 14), stamped at admission:
    # the request's schema-prefix content digest — the MATCHED chain
    # entry's digest on a hit (joinable against /debug/prefixcache and
    # the resident-digest routing feed), the longest block-aligned
    # prompt prefix on a miss (the best schema-identity guess when
    # nothing matched); same digest fleet-wide for the same token
    # prefix. Plus how many prompt tokens the hit let prefill SKIP and
    # the analytic prefill seconds that skip saved
    # (utils/perfmodel.prefill_saved). "" / 0 when the prompt is shorter
    # than one block or the cache is off.
    prefix_digest: str = ""
    tokens_reused: int = 0
    prefill_s_saved: float = 0.0
    # Multi-tenant QoS (ISSUE 18). `tenant`/`qos` ride the request from
    # the HTTP layer through pool/supervisor/remote-wire; "" = unlabeled
    # (the single-tenant shape, untouched by every QoS-off path). `vft`
    # is the WFQ virtual finish time stamped at submit; `ns` is the
    # tenant's prefix-cache namespace salt (two int32s prepended to
    # every cache key/digest — () for unlabeled traffic, so its keys
    # stay bit-for-bit identical to the shared registry).
    tenant: str = ""
    qos: str = ""
    vft: float = 0.0
    ns: Tuple[int, ...] = ()

    @property
    def full_ids(self) -> List[int]:
        """The prefill prefix: the prompt, plus — after a preemption —
        the committed generated tokens recompute must re-run (position
        of generated token j is len(ids) + j in BOTH incarnations, so
        every envelope/top-up formula can use absolute positions)."""
        if not self.resume_pref:
            return self.ids
        return self.ids + self.generated[: self.resume_pref]

    def flush_spans(self, now: float) -> None:
        """Record the request's scheduler-phase spans into its trace at
        terminal time (retire/fail): queue-wait (submit→slot), prefill
        (slot→decode-eligible), decode (eligible→terminal). One call per
        request, only when traced — zero work on the unsampled path."""
        tr = self.trace
        if tr is None:
            return
        try:
            if self.submitted_at and self.admitted_at:
                tr.add_span("sched.queue_wait", self.submitted_at,
                            self.admitted_at, rid=self.rid)
            elif self.submitted_at:
                # Never admitted (expired/cancelled while queued): its
                # whole life WAS queue wait.
                tr.add_span("sched.queue_wait", self.submitted_at, now,
                            rid=self.rid)
            if self.admitted_at:
                t_ready = self.ready_at or now
                # Reuse attribution rides the prefill span (ISSUE 14): a
                # traced request's timeline says how much of its prompt
                # the prefix cache already held and what that skip was
                # worth — beside the span whose wall it shortened.
                attrs = {"prompt_tokens": len(self.ids)}
                if self.prefix_digest:
                    attrs["prefix_digest"] = self.prefix_digest
                    attrs["tokens_reused"] = self.tokens_reused
                    attrs["tokens_prefilled"] = (
                        len(self.ids) - self.tokens_reused
                    )
                tr.add_span("sched.prefill", self.admitted_at, t_ready,
                            **attrs)
            if self.ready_at:
                tr.add_span("sched.decode", self.ready_at, now,
                            output_tokens=len(self.generated),
                            constrained=self.constraint is not None)
            # Preemption parking (ISSUE 12 satellite): one span per parked
            # interval, so a victim's exported timeline explains the gap —
            # an interval still open at terminal time (preempted, never
            # resumed: deadline burned while parked) closes at `now` with
            # resumed=False.
            for iv in self.parked:
                t0, t1 = iv[0], iv[1]
                tr.add_span("sched.preempted", t0, t1 or now,
                            rid=self.rid, resumed=bool(t1),
                            preemptions=self.preempted)
        except Exception:  # noqa: BLE001 — tracing must never kill the loop
            self.trace = None

    def emit(self, tok: int) -> None:
        if self.on_token is not None:
            try:
                self.on_token(tok)
            except Exception:  # noqa: BLE001 — consumer bugs must not kill serving
                self.on_token = None

    def past_deadline(self) -> bool:
        return self.deadline is not None and self.deadline.expired()

    def deadline_error(self) -> DeadlineExceeded:
        return DeadlineExceeded(
            f"request deadline exceeded with {len(self.generated)} of "
            f"{self.max_new} tokens generated"
        )


class ContinuousBatchingScheduler:
    """Admit → prefill → batched chunked decode → retire, on one device batch.

    `submit()` is thread-safe and returns a Future of generated token ids
    (stop token stripped). A daemon thread owns all device work.
    """

    #: Duck-typing flag (ISSUE 18): callers (SchedulerBackend, the
    #: supervisor, transports) only forward tenant/qos kwargs to
    #: schedulers that understand the axis — test fakes and older
    #: signatures keep working untouched.
    supports_qos = True

    def __init__(
        self,
        cfg: LlamaConfig,
        params: Params,
        num_slots: int = 8,
        max_seq: Optional[int] = None,
        decode_chunk: int = 8,
        prompt_bucket: int = 128,
        stop_ids: Optional[Sequence[int]] = None,
        mesh=None,
        prefix_cache_blocks: int = 64,
        kv_quant: Optional[str] = None,
        speculative_draft: int = 0,
        spec_ngram: int = 3,
        fuse_matmuls: bool = False,
        max_queue_depth: int = 0,
        slot_stall_rounds: int = 16,
        kv_layout: str = "contiguous",
        kv_page_size: Optional[int] = None,
        kv_pages: Optional[int] = None,
        kv_hbm_budget_bytes: Optional[int] = None,
        kv_overcommit: Optional[float] = None,
        kv_spill: Optional[bool] = None,
        kv_watermark_low: Optional[float] = None,
        kv_watermark_high: Optional[float] = None,
        phase_role: str = "mixed",
        # Unified ragged prefill+decode (ISSUE 19): admit prefill chunks
        # and decode slots into ONE compiled mixed-round launch (per-slot
        # query-length vector; prefill rows scatter their chunk, decode
        # rows emit tokens), retiring the separate prefill pass from the
        # loop's hot path. None = read LSOT_RAGGED (default off — the
        # alternating scheduler, bit for bit). Paged-only, mixed-role
        # only.
        ragged: Optional[bool] = None,
        # Multi-model serving (ISSUE 16): which registered checkpoint
        # this replica holds. "" (the default) is the single-model
        # fleet, bit for bit — the pool only routes on model when a
        # request names one AND replicas carry ids
        # (serve/modelpool.py owns the registry; LSOT_POOL_MODELS
        # gates the routing axis like LSOT_POOL_AFFINITY gates
        # affinity).
        model_id: str = "",
    ):
        self.cfg = cfg
        self.mesh = mesh
        # Disaggregated prefill/decode serving (ISSUE 13): "mixed" (the
        # default) is today's behavior bit for bit. A "prefill" replica
        # never enters its decode loop for fresh requests: the final
        # prompt chunk's sampled first token is committed and streamed,
        # the request's KV pages export into a portable handoff blob
        # (engine/paged_kv.export_pages — the spill format), and the
        # request parks in `_handoff` for the pool's phase-aware router
        # to re-place onto a decode replica (`on_handoff` wakes it; no
        # consumer wired → the replica arms the slot and decodes in
        # place, so a lone prefill-role scheduler still serves). A
        # "decode" replica is routing policy only — full capability, but
        # the router feeds it migrated requests and keeps fresh prompts
        # off it. Handoff needs pages to ship, hence paged-only.
        if phase_role not in PHASE_ROLES:
            raise ValueError(
                f"phase_role must be one of {PHASE_ROLES}, got "
                f"{phase_role!r}"
            )
        if phase_role != "mixed" and kv_layout != "paged":
            raise ValueError(
                f"phase_role={phase_role!r} needs kv_layout='paged': the "
                f"prefill→decode handoff ships KV pool pages"
            )
        self.phase_role = phase_role
        self.model_id = str(model_id or "")
        # Accepted tokens over this scheduler's lifetime (ISSUE 16):
        # bumped once per harvested round; per-model throughput
        # attribution reads it (pool.model_stats / lsot_model_*).
        self._tokens_emitted_total = 0
        # Handoff state. `_handoff_pending` holds (slot, req, tok, epoch)
        # for final chunks whose first token is still on device;
        # `_handoff` is the packed-blob queue the pool drains. Counters
        # feed handoff_stats / the lsot_handoff_* Prometheus families.
        self._handoff: "deque[_Request]" = deque()
        self._handoff_pending: list = []
        self.on_handoff: Optional[Callable[[], None]] = None
        # Bounded in-worker handoff buffer (ISSUE 17): when the pump's
        # consumer falls behind and the packed queue reaches this depth,
        # further handoffs decode in place instead of piling up blobs
        # (each one pins exported pages' worth of host memory).
        self._pump_depth = int(os.environ.get("LSOT_PUMP_DEPTH", "32")
                               or 32)
        self._ho_backpressure = 0
        self._ho_exports = 0
        self._ho_imports = 0
        self._ho_inplace = 0
        self._ho_pages_out = 0
        self._ho_pages_in = 0
        self._ho_bytes_out = 0
        self._ho_bytes_in = 0
        self._ho_wait_sum = 0.0
        self._ho_wait_count = 0
        # Per-round migration accumulators (flushed into the flight
        # record's pages_migrated/handoff_wait_s columns at harvest).
        self._mig_pages = 0
        self._mig_wait = 0.0
        # Prefill-side backlog signal: outstanding PROMPT tokens and a
        # per-prompt-token service EWMA (submit→handoff wall), so a
        # prefill replica's backlog_score prices compute backlog instead
        # of decode budgets it will never spend.
        self._pending_prompt_tokens = 0
        self._pref_stok_ewma: Optional[float] = None
        self._last_pack_t: Optional[float] = None
        # Per-slot stall retirement: a slot that appends nothing for this
        # many consecutive harvest rounds WHILE other slots advance is
        # retired typed (SlotStalled/504) — a wedged lane must not pin a
        # batch slot until its deadline burns. 0 disables. Organically
        # impossible with the current decode programs (every active slot
        # emits per round), so this is defense-in-depth plus the
        # `sched:slot_stall` chaos seam's contract.
        self.slot_stall_rounds = int(slot_stall_rounds)
        self._slot_stalls = 0
        # Liveness stamp the event loop touches every iteration (and per
        # harvested round): the supervisor's watchdog monitor reads it to
        # tell a wedged loop (hung XLA dispatch/tunnel — age grows while
        # busy) from a healthy or idle one. serve/watchdog.py.
        self.heartbeat = Heartbeat()
        # Flight recorder (serve/flightrecorder.py): one record per
        # HARVESTED round — occupancy, admitted/retired rids, emitted and
        # speculation-accepted tokens, round wall, cadence — in a bounded
        # ring. The postmortem black box a crash/stall/SIGTERM dump reads;
        # live at /debug/flightrecorder. `replica` is relabeled by
        # SchedulerPool so a pool's merged view attributes load.
        self.flight = FlightRecorder()
        # Scheduler-scope monotonic request ids for flight-recorder
        # attribution (independent of the supervisor's journal rids).
        self._rid_seq = 0
        # Rids admitted since the last harvested round's record.
        self._round_admitted: List[int] = []
        self._round_retired: List[int] = []
        # Admission control: submits beyond this many queued-not-yet-slotted
        # requests shed with a typed Overloaded (HTTP 429 upstream) instead
        # of growing the backlog without bound — under sustained overload an
        # unbounded queue turns every request into a timeout. 0 = unbounded
        # (the historical behavior, kept as default for library users).
        self.max_queue_depth = int(max_queue_depth)
        if fuse_matmuls:
            # Fewer, wider MXU matmuls for admission prefill (the phase
            # that stalls decode rounds under load).
            from ..models.llama import maybe_fuse

            params = maybe_fuse(params, mesh)
        if mesh is not None:
            if dict(mesh.shape).get("dp", 1) != 1:
                raise ValueError(
                    "scheduler mesh must have dp=1: request parallelism comes "
                    "from slots; the slot axis is dynamically indexed and "
                    "cannot shard"
                )
            validate_tp(cfg, mesh.shape["tp"])
            params = shard_params(params, cfg, mesh)
        self.params = params
        # Weight bits for the verify-cost model: immutable for this
        # scheduler's lifetime, so probe the tree ONCE instead of per
        # speculation_stats read (/metrics scrapes + bench deltas).
        from ..engine.speculative import infer_weight_bits

        self._weight_bits = infer_weight_bits(params)
        self.num_slots = num_slots
        self.max_seq = min(max_seq or cfg.max_seq_len, cfg.max_seq_len)
        self.decode_chunk = decode_chunk
        self.prompt_bucket = min(prompt_bucket, max(1, self.max_seq // 2))
        self.stop_ids = tuple(stop_ids) if stop_ids is not None else cfg.stop_ids
        self._impl = attention_impl(mesh)

        dtype = jax.tree.leaves(params)[0].dtype
        self._dtype = dtype
        if kv_quant not in (None, "int8"):
            raise ValueError(f"kv_quant must be None or 'int8', got {kv_quant!r}")
        self.kv_quant = kv_quant
        # Paged KV cache (kv_layout="paged", engine/paged_kv.py): the
        # persistent window becomes a shared page pool sized to an HBM
        # budget + per-slot page tables, instead of slots × S_max
        # contiguous rows. Admission allocates ceil(need/page) pages for
        # the request's ACTUAL envelope (bucketed prompt + budget +
        # overshoot), so concurrency is bounded by live tokens, mixed
        # long/short batches stop paying max-bucket padding, and
        # prefix-cache hits map shared pages zero-copy (refcounts;
        # copy-on-write only at a non-page-aligned boundary). Decode runs
        # the ragged-paged-attention path (models/llama.forward paged
        # branch; ops/pallas/paged_attention.py on TPU).
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be 'contiguous' or 'paged', got "
                f"{kv_layout!r}"
            )
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        if self._paged:
            # Composes with kv_quant="int8" (the pool stores int8 pages +
            # per-position scales — ~2x live tokens per HBM byte; page
            # accounting below prices the TRUE page bytes) and with a
            # dp=1 tp mesh (pool KV heads shard over tp exactly like the
            # contiguous cache; page tables replicate).
            ps = int(kv_page_size or default_page_size())
            if ps <= 0 or ps % 8:
                raise ValueError(
                    f"kv_page_size must be a positive multiple of 8, got "
                    f"{ps}"
                )
            self._page_size = ps
            # Logical pages per slot: enough table entries to address the
            # whole window (a slot never MAPS them all unless its request
            # actually needs max_seq).
            self._pages_per_slot = pages_for_tokens(self.max_seq, ps)
            if kv_pages:
                num_pages = int(kv_pages)
            elif kv_hbm_budget_bytes:
                # KV-dtype-aware sizing (ISSUE 11 satellite): an int8
                # pool's pages cost ~half a compute-dtype page, so the
                # same HBM budget buys ~2x the pages — capacity math must
                # price the KV dtype, not the compute dtype.
                num_pages = pages_for_budget(
                    cfg, kv_hbm_budget_bytes, ps, dtype.itemsize, kv_quant
                )
            else:
                # Default budget = the contiguous layout's own footprint:
                # same HBM, strictly more concurrency on mixed traffic.
                num_pages = num_slots * self._pages_per_slot
            if num_pages < self._pages_per_slot:
                raise ValueError(
                    f"page pool of {num_pages} pages cannot hold one "
                    f"max-length request ({self._pages_per_slot} pages of "
                    f"{ps} tokens for max_seq={self.max_seq}); raise "
                    f"kv_pages / kv_hbm_budget_bytes or lower max_seq"
                )
            self._page_alloc = PageAllocator(num_pages, ps)
            # Graceful degradation under page pressure (ISSUE 10).
            # Overcommit admission: reserve min(budget, max(ratio × budget,
            # EWMA of observed generation lengths)) generation tokens at
            # admission instead of the full max_new worst case — 1.0 (the
            # default) reproduces the exact-envelope admission bit for
            # bit; below 1.0, decode tops pages up at each harvest and a
            # failed top-up preempts a victim (fewest generated tokens
            # first, never the allocating slot) whose deterministic
            # resume re-prefills prompt+generated (or restores spilled
            # host page copies under kv_spill).
            if kv_overcommit is None:
                kv_overcommit = float(
                    os.environ.get("LSOT_KV_OVERCOMMIT", "1.0"))
            if not 0.0 < kv_overcommit <= 1.0:
                raise ValueError(
                    f"kv_overcommit must be in (0, 1], got {kv_overcommit}"
                )
            self._kv_overcommit = float(kv_overcommit)
            if kv_spill is None:
                kv_spill = os.environ.get("LSOT_KV_SPILL", "0").strip() \
                    .lower() in ("1", "true", "yes", "on")
            self._kv_spill = bool(kv_spill)
            # Watermark-driven eviction: when pool free pages fall under
            # low × pages, the loop proactively evicts LRU prefix-cache
            # entries until free recovers to high × pages — steady-state
            # pressure is relieved BEFORE an allocation fails, so traffic
            # rarely needs a preemption at all. low = 0 disables (the
            # default: the on-demand eviction inside _alloc_pages remains,
            # exactly as before).
            if kv_watermark_low is None:
                kv_watermark_low = float(
                    os.environ.get("LSOT_KV_WATERMARK_LOW", "0.0"))
            if kv_watermark_high is None:
                kv_watermark_high = float(
                    os.environ.get("LSOT_KV_WATERMARK_HIGH", "0.0"))
            if not 0.0 <= kv_watermark_low <= 1.0 or \
                    not 0.0 <= kv_watermark_high <= 1.0 or \
                    kv_watermark_high < kv_watermark_low:
                raise ValueError(
                    f"kv watermarks must satisfy 0 <= low <= high <= 1, "
                    f"got low={kv_watermark_low} high={kv_watermark_high}"
                )
            self._wm_low_pages = int(kv_watermark_low * num_pages)
            self._wm_high_pages = max(
                self._wm_low_pages, int(kv_watermark_high * num_pages))
            # EWMA of COMPLETED requests' generation lengths: the
            # "expected generation" admission reserves under overcommit.
            self._gen_ewma: Optional[float] = None
            # Host-side per-slot page lists (the device table's mirror).
            self._slot_pages: List[List[int]] = [[] for _ in range(num_slots)]
            # Paged prefix cache: content key (token prefix) -> pool page
            # ids covering it. Entries hold REFERENCES (refcounts), not
            # copies — publish and hit are both zero-copy.
            self._prefix_pages: "OrderedDict[Tuple[int, ...], Tuple[int, ...]]" = (
                OrderedDict()
            )
            # Requests admitted to a slot but waiting for pool pages
            # (admission is all-or-nothing so partial holders can't
            # deadlock); FIFO ahead of the main queue.
            self._page_wait: "deque[_Request]" = deque()
            self._page_wait_events = 0
        # Decode impl is cost-aware: the flash kernel's per-row kv_lens
        # bounding (parked slots stream nothing) only beats the einsum
        # path's zero-overhead full-cache read once the persistent
        # [slots, max_seq] cache is large per device — see
        # ops.pallas.decode_attention_impl for the measured crossover.
        # With the int8 KV cache the streamed bytes HALVE (which also
        # halves the full-read penalty the kernel amortizes), so the
        # crossover is fed the quantized byte count; past it, decode runs
        # flash_gqa_attention_quantized — int8 streaming and bounded
        # streaming stacked.
        from ..engine.kvcache import cache_bytes as _cache_bytes

        tp = dict(mesh.shape).get("tp", 1) if mesh is not None else 1
        if self._paged:
            # page_bytes already prices the KV dtype (int8 values +
            # scales), so no post-hoc halving; the pool's head axis
            # shards over tp like the contiguous cache.
            cache_dev_bytes = self._page_alloc.num_pages * page_bytes(
                cfg, self._page_size, dtype.itemsize, kv_quant
            ) // tp
        else:
            cache_dev_bytes = _cache_bytes(
                cfg, num_slots, self.max_seq, dtype.itemsize
            ) // tp
        if kv_quant and not self._paged:
            # Halving shifts the kernel/einsum crossover to the quantized
            # byte count. NOTE (advisor r4): the crossover threshold itself
            # was measured on the bf16 cache; quantization halves the
            # kernel's streamed bytes and the einsum's full-read penalty
            # roughly equally, so feeding the halved count to the bf16
            # threshold is an extrapolation, not a re-measurement — if int8
            # decode dispatch ever looks off, re-sweep the crossover with
            # the int8 cache (ops/pallas/dispatch.py has the recipe).
            cache_dev_bytes //= 2
        self._decode_impl = decode_attention_impl(mesh, cache_dev_bytes)
        # Per-round roofline ledger (ISSUE 12, utils/perfmodel.py): the
        # SAME analytic cost model bench.py prices artifacts with, built
        # once from everything immutable — model shape, weight bytes/bits,
        # KV layout/dtype pricing, tp shard, device peaks (CPU fallback
        # included) — so every harvested round can stamp achieved MFU,
        # HBM-bandwidth utilization, and a compute-vs-memory-bound
        # verdict for a handful of float multiplies (bench's
        # _obs_overhead prices the stamp against the <1% bar).
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — backend-less test doubles
            device_kind = ""
        self.perf = PerfModel(
            cfg,
            param_bytes=int(sum(x.nbytes for x in jax.tree.leaves(params))),
            weight_bits=self._weight_bits,
            kv_itemsize=dtype.itemsize,
            kv_quant=kv_quant,
            kv_layout=kv_layout,
            page_size=self._page_size if self._paged else None,
            tp=tp,
            device_kind=device_kind,
        )
        self._last_harvest_t: Optional[float] = None
        # On-demand device profiling (/debug/profile): armed captures
        # start at the next issued round on the WORKER thread and stop
        # after N harvested rounds; the process-wide guard in
        # utils/traceprof keeps at most one capture in flight fleet-wide.
        self._profile_lock = threading.Lock()
        self._profile_arm: Optional[Dict[str, object]] = None
        self._profile_active: Optional[Dict[str, object]] = None
        self._profile_last: Optional[Dict[str, object]] = None
        # The persistent cache is a TUPLE of arrays threaded through every
        # jitted op: (k, v) in bf16 mode, (k8, ks, v8, vs) with int8 KV
        # (values + per-slot scales, ops/quant.quantize_kv), (kp, vp) pool
        # arrays in paged mode (per-slot page tables ride beside them as
        # self._ptab, a non-donated arg to every program).
        if self._paged:
            pool = init_page_pool(
                cfg, self._page_alloc.num_pages, self._page_size,
                dtype=dtype, kv_quant=kv_quant,
            )
            arrs = ((pool["kp"], pool["kps"], pool["vp"], pool["vps"])
                    if kv_quant else (pool["kp"], pool["vp"]))
            # Device page tables: [slots, pages_per_slot], the UNMAPPED
            # sentinel is num_pages — one past the pool, so jax drops the
            # scatter writes of parked/padding rows and gathers clip to a
            # causally-masked real page.
            self._ptab = jnp.full(
                (num_slots, self._pages_per_slot),
                self._page_alloc.num_pages, jnp.int32,
            )
        else:
            cache = init_cache(cfg, num_slots, self.max_seq, dtype=dtype)
            if kv_quant:
                from ..ops.quant import quantize_cache

                arrs = _cache_tuple(quantize_cache(cache["k"], cache["v"]))
            else:
                arrs = (cache["k"], cache["v"])
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # Slots (contiguous) / pages (paged) unsharded, KV heads on
            # tp; scale tensors drop the trailing axis from the spec but
            # keep heads-over-tp. The same two specs serve all four cache
            # forms — [L, B|P, K, S|PS(, H)].
            arrs = tuple(
                jax.device_put(
                    x,
                    NamedSharding(
                        mesh,
                        P(None, None, "tp", None, None) if x.ndim == 5
                        else P(None, None, "tp", None),
                    ),
                )
                for x in arrs
            )
            if self._paged:
                # Page tables replicate: every device addresses the full
                # page axis of its own head shard.
                self._ptab = jax.device_put(
                    self._ptab, NamedSharding(mesh, P(None, None))
                )
        self._cache = arrs

        # Per-slot state lives ON DEVICE and chains between rounds: decode
        # rounds and admission scatters are issued asynchronously and the
        # host syncs only to harvest sampled tokens (one transfer per round,
        # one round LATE — see _loop). On a high-latency transport (this
        # repo's TPU rides a tunnel) per-round syncs, not device FLOPs, were
        # the measured serving bottleneck; overlapping the round-trip with
        # the next round's compute is the fix, and on a local chip the same
        # structure simply pipelines dispatch.
        # Inactive slots "park" at the last cache slot: decode rounds write
        # garbage K/V for every slot in the batch, and a parked write lands
        # where no query can ever see it (visibility needs query position
        # >= max_seq-1, and submit() caps requests below that). This is
        # what makes chunked prefill safe: while a slot's prompt streams in
        # over several chunks, interleaved decode rounds keep scribbling at
        # the park slot, not inside the freshly written prompt region.
        self._park = self.max_seq - 1
        self._cur = jnp.full((num_slots,), cfg.pad_id, jnp.int32)
        self._pos = jnp.full((num_slots,), self._park, jnp.int32)
        self._temps = jnp.zeros(num_slots, jnp.float32)
        self._topps = jnp.ones(num_slots, jnp.float32)
        self._topks = jnp.zeros(num_slots, jnp.int32)
        # Per-request RNG: seed + tokens-sampled-so-far give slot s's key for
        # its next token as fold_in(key(seed), count) — independent of what
        # else is in the batch. counts advance on device (decode fn),
        # mirroring nothing to the host.
        self._seeds = jnp.zeros(num_slots, jnp.uint32)
        self._counts = jnp.zeros(num_slots, jnp.int32)
        # Grammar constraining: per-slot DFA state (0 = unconstrained
        # sentinel row of the installed tables) and remaining token budget
        # (drives the closing-mask switch) — both live on device and chain
        # between rounds like every other slot array. ONE grammar's tables
        # are installed at a time ([S, V] mask/next/dist/closing, passed to
        # the decode jit as regular args); mixed constrained/unconstrained
        # batches need no recompilation because "no grammar" is just state
        # 0. Installing a DIFFERENT grammar (new schema) swaps the tables
        # on the worker thread once no constrained slot is active — that is
        # one retrace per grammar, never per request.
        self._cstates = jnp.zeros(num_slots, jnp.int32)
        # crem rests at 1 for inactive slots (sentinel need is 1, so the
        # parked row is genuinely all-allowed — see park_slot).
        self._crem = jnp.ones(num_slots, jnp.int32)
        self._constraint: Optional[CompiledMask] = None
        self._ctables = trivial_tables(cfg.vocab_size)
        self._constraint_wait: "deque[_Request]" = deque()
        self._slot_req: List[Optional[_Request]] = [None] * num_slots
        # Per-slot occupancy epoch, bumped at every admission, retirement
        # and preemption: in-flight rounds/firsts are stamped with it at
        # issue, and the harvest drops rows whose epoch is stale — the
        # request-identity check alone cannot catch a request preempted
        # and re-admitted into the SAME slot between issue and harvest.
        self._slot_epoch: List[int] = [0] * num_slots
        # In-flight rounds awaiting harvest: (issue-time slot->req list,
        # issue-time slot-epoch snapshot, toks device array, n_emit device
        # array or None, firsts list of (slot, req, first_tok device,
        # epoch), issue wall stamp, mixed_meta — the unified ragged
        # round's prefill-side attribution dict, None on alternating
        # rounds).
        self._pending: "deque[Tuple[List[Optional[_Request]], List[int], jax.Array, object, list, float, Optional[dict]]]" = deque()
        self._first_pending: list = []
        self._harvest_lag = 1  # rounds kept in flight before syncing
        (self._park_fn, self._ready_fn, self._retire_fn,
         self._resume_fn) = self._build_state_ops()
        if self._paged:
            (self._ptab_row_fn, self._copy_page_fn,
             self._restore_page_fn) = self._build_page_ops()
        # Unified ragged prefill+decode (ISSUE 19): one compiled
        # mixed-round program admits this round's prefill chunks and every
        # decode slot into the SAME launch — forward takes a per-slot
        # query-length vector (q_lens), prefill rows scatter their chunk
        # through the page table while decode rows emit tokens, and the
        # _loop hot path stops alternating a separate prefill pass with
        # decode rounds. LSOT_RAGGED=0 (the default) keeps the alternating
        # scheduler bit for bit.
        if ragged is None:
            ragged = os.environ.get("LSOT_RAGGED", "0").strip().lower() in (
                "1", "true", "yes", "on"
            )
            if ragged and not (self._paged and self.phase_role == "mixed"):
                # Env-driven opt-in degrades silently on replicas that
                # can't serve it (contiguous layout, phase-split roles):
                # one LSOT_RAGGED=1 environment may spawn heterogeneous
                # fleets.
                ragged = False
        elif ragged and not (self._paged and self.phase_role == "mixed"):
            raise ValueError(
                "ragged mixed rounds need kv_layout='paged' (prefill rows "
                "scatter chunks through page tables) and "
                "phase_role='mixed' (a phase-split replica has no mixed "
                "rounds to unify)"
            )
        self._ragged = bool(ragged)
        if self._ragged:
            from ..models.llama import _UNROLL_MAX_T

            # Mixed rounds run prefill chunks through forward's unrolled
            # paged path (one T for the whole batch), so chunks cap at the
            # unroll bound instead of an arbitrary prompt_bucket.
            self.prompt_bucket = min(self.prompt_bucket, _UNROLL_MAX_T)
            self._mixed_fns: Dict[int, Callable] = {}
        # Prompt-chunk buckets: powers of two up to prompt_bucket, so a short
        # prompt pays a small forward instead of a full prompt_bucket one
        # (one compiled prefill program per bucket, built lazily).
        b, buckets = min(16, self.prompt_bucket), []
        while b < self.prompt_bucket:
            buckets.append(b)
            b *= 2
        self._buckets = buckets + [self.prompt_bucket]
        # Batched prefill: up to kmax same-bucket admissions share one
        # forward (weight streaming amortizes across an admission burst).
        # Group size pads to a power-of-two k-bucket: a lone admission pays
        # a 1-row forward (low-concurrency TTFT unchanged), bursts pad at
        # most 2x, and compiled variants stay bounded at
        # len(buckets) * len(kbuckets) (built lazily).
        # kmax capped at 8: the prefill fn gathers/scatters its group's cache
        # rows through the whole stacked buffer, and larger groups also stall
        # the decode interleave for a full multi-kilotoken forward — kmax=16
        # measured 1075 tok/s vs kmax=8's 1836 on the v5e serving sweep.
        self._prefill_kmax = min(num_slots, 8)
        kb, kbuckets = 1, []
        while kb < self._prefill_kmax:
            kbuckets.append(kb)
            kb *= 2
        self._kbuckets = kbuckets + [self._prefill_kmax]

        # Speculative decoding (prompt-lookup, engine/speculative.py): when
        # speculative_draft=D > 0, decode rounds draft D tokens per slot
        # from an ON-DEVICE token history and verify them with one T=D+1
        # forward — greedy slots emit 1..D+1 tokens per round (exact
        # greedy chain), sampled slots emit 1..D+1 via rejection sampling
        # (unbiased: the emitted tokens are distributed exactly as
        # vanilla sample_runtime decode). The verify window takes the
        # unrolled small-T einsum path, which also composes with the
        # int8 KV cache.
        self._spec_draft = int(speculative_draft or 0)
        self._spec_ngram = spec_ngram
        if self._spec_draft:
            from ..models.llama import _UNROLL_MAX_T

            if not 1 <= self._spec_draft <= _UNROLL_MAX_T - 1:
                raise ValueError(
                    f"speculative_draft must be in [1, {_UNROLL_MAX_T - 1}]"
                    f" (verify window T = draft+1 must take the unrolled "
                    f"small-T path), got {self._spec_draft}"
                )
            # Prefix-cache reuse skips prefill forwards, so reused tokens
            # would never reach the on-device draft history; disable reuse
            # rather than draft from holes (both features target the same
            # copy-heavy workload — pick speculation when it's on).
            prefix_cache_blocks = 0
            # History rows are max_seq + D+1 wide: the emit scatter writes a
            # D+1 window at hlen (<= max_seq-1 by the submit bound), and the
            # extra tail absorbs it without dynamic_update_slice clamping.
            self._hist = jnp.full(
                (num_slots, self.max_seq + self._spec_draft + 1),
                cfg.pad_id, jnp.int32,
            )
            self._hlen = jnp.zeros(num_slots, jnp.int32)
            self._spec_ready_fn, self._spec_resume_fn = \
                self._build_spec_ready()
            # Acceptance accounting (VERDICT r4 next #5): without a counter
            # the bench could never say whether speculation PAYS — breakeven
            # is ~1.6 accepted tokens per verify round (the measured cost of
            # a T=D+1 verify vs a T=1 step, engine/speculative.py). Counted
            # at harvest for every emitting slot. The *_con pair counts the
            # CONSTRAINED subset of the totals: grammar-masked traffic has
            # a different acceptance profile (forced keyword/identifier
            # runs accept whole chains; branch points reject), and an
            # operator deciding whether speculation pays for the NL→SQL
            # hot path needs ITS tokens/round, not a blend with
            # unconstrained traffic. The *_samp pair counts the SAMPLED
            # (temperature>0) subset the same way: rejection-sampling
            # acceptance (u < target mass) runs systematically below
            # greedy's argmax-match acceptance, and the sampled class's
            # tokens/round is the go/no-go number for speculating on
            # sampled traffic (speculation_stats splits both axes;
            # /metrics carries all of it).
            self._spec_rounds = 0
            self._spec_tokens = 0
            self._spec_rounds_con = 0
            self._spec_tokens_con = 0
            self._spec_rounds_samp = 0
            self._spec_tokens_samp = 0

        # Prefix cache: block size = the smallest bucket, so chunk boundaries
        # always land on block boundaries. OrderedDict as LRU of
        # content-keyed cache-block tuples (one entry per cache array:
        # [L, 1, K, pblock, H] values, plus [L, 1, K, pblock] scales under
        # kv_quant).
        self._pblock = self._buckets[0]
        self._prefix_cache_blocks = max(0, prefix_cache_blocks)
        self._prefix_cache: "OrderedDict[Tuple[int, ...], Tuple[jax.Array, ...]]" = (
            OrderedDict()
        )
        # Publish gate: a block is copied out of the cache only once its
        # content key has been SEEN before (second occurrence onward). A
        # shared system/schema prefix repeats across requests, so it gets
        # published on request 2 and hit from request 3 on; one-off prompts
        # (every block unique) pay zero slice dispatches — publishing every
        # block of every prompt was a measured per-admission cost on the
        # serving path with nothing to ever reuse it.
        self._prefix_seen: "OrderedDict[Tuple[int, ...], None]" = OrderedDict()
        self._prefix_hits = 0
        self._prefix_blocks_reused = 0
        # --- Prefix-cache telemetry (ISSUE 14). Counters move as a group
        # under _submit_lock (the PR-1 speculation-counter pattern) so
        # /metrics scrapes and bench's pre/post delta bracketing never
        # read a torn (hits, misses, reused_tokens) triple; the worker
        # thread is the only writer.
        self._prefix_misses = 0
        self._prefix_evictions = 0
        self._prefix_reinserts = 0
        self._prefix_reused_tokens = 0
        self._prefix_flops_saved = 0.0
        self._prefix_s_saved = 0.0
        # Hit-rate EWMA over admissions (1.0 hit / 0.0 miss, alpha 0.2):
        # the live per-replica routing signal replica_loads() exports —
        # a ratio of lifetime counters would take hours to reflect a
        # cold cache after a restart.
        self._prefix_hit_ewma: Optional[float] = None
        # Content-addressed registry: per-entry live metadata keyed by
        # the same chain keys as the caches (digest, token length, hit
        # count, insert/last-hit round). Pages/bytes/refcounts are read
        # off the live structures at registry() time, never duplicated.
        self._prefix_meta: Dict[Tuple[int, ...], Dict[str, object]] = {}
        # Eviction-churn ghost: keys evicted from the cache, bounded like
        # _prefix_seen — a publish that finds its key here is a
        # REINSERTION (the cache was too small for the working set), the
        # churn signal the ring-size knob acts on.
        self._prefix_evicted_ghost: "OrderedDict[Tuple[int, ...], None]" = (
            OrderedDict()
        )
        # Reuse-distance ring: the last N admissions' schema-prefix
        # digests. distance = admissions since the same digest last
        # appeared, computed O(1) off a digest -> admission-seq map
        # (bounded: stale entries older than the ring window are swept
        # when the map doubles — a linear deque scan was the measured
        # hog of the admission stamp). Histogram buckets are powers of
        # two plus an overflow arm ("inf" = first sighting inside the
        # ring window).
        self._prefix_ring_cap = _prefix_bound(
            _PREFIX_RING, "LSOT_PREFIX_RING", 256)
        self._prefix_topk = _prefix_bound(
            _PREFIX_TOPK, "LSOT_PREFIX_TOPK", 32)
        self._prefix_adm_seq = 0
        self._prefix_ring_seq: Dict[str, int] = {}
        self._prefix_rd_buckets = _rd_buckets(self._prefix_ring_cap)
        self._prefix_rd_hist: Dict[str, int] = {}
        # Digest memo (chain key -> digest), LRU-bounded: packing a
        # Python token list into hashable bytes is the measured hog of
        # the admission stamp (~6µs/256 tokens), and steady-state traffic
        # repeats the SAME schema prefix — so the hot path is a tuple +
        # dict probe, and blake2b runs once per distinct prefix.
        self._prefix_digest_memo: "OrderedDict[Tuple[int, ...], str]" = (
            OrderedDict()
        )
        # Per-round reuse attribution, flushed into the flight record at
        # the next harvest ({rid, digest, reused, prefilled} per admitted
        # request that went through the prefix-match path).
        self._round_prefix: List[Dict[str, object]] = []
        # Contiguous block bytes (one cache entry's device footprint),
        # filled lazily from the first published entry.
        self._prefix_block_bytes = 0
        # Contiguous mode materializes prefix blocks by device copy; paged
        # mode shares pool pages by refcount instead and never needs the
        # slice/restore copies.
        self._slice_block_fn, self._restore_block_fn = (
            (None, None) if self._paged else self._build_block_ops()
        )

        # Recent per-request service time (EWMA of completed requests'
        # submit→retire wall): the backpressure estimate behind
        # retry_after_hint(). None until the first completion — the static
        # 1s floor serves until there is something to estimate from.
        self._svc_ewma: Optional[float] = None
        # Token-weighted backlog: sum of outstanding requests' max_new
        # (queued + slotted; += at submit/requeue, -= at terminal), and a
        # per-TOKEN service-time EWMA beside the per-request one. The
        # pool's least-loaded router scores replicas by
        # pending_tokens × sec/token / slots: the same service-time-EWMA
        # family as the Retry-After math, refined to token resolution —
        # request COUNTS tie constantly under a submit burst and say
        # nothing about skewed prompt lengths; outstanding token mass is
        # the signal that actually differs, and pricing it in seconds
        # keeps the score comparable to a request's deadline.
        self._pending_new_tokens = 0
        self._stok_ewma: Optional[float] = None

        # Multi-tenant QoS (ISSUE 18): weighted-fair queueing at admission
        # and _page_wait. `LSOT_QOS=0` switches every QoS path off — the
        # FIFO admission order, prefix-cache key shapes, and preemption
        # victim choice then reproduce the pre-QoS scheduler bit-for-bit
        # (reconciliation-tested at the token level). With QoS on, the
        # worker drains the submit queue into `_ready` and serves the
        # smallest virtual finish time: vft = max(global virtual time,
        # tenant's last vft) + (prompt+budget tokens)/weight — start-time
        # fair queueing, so a storm tenant's backlog inflates only its
        # OWN virtual clock and cannot head-of-line-block a light tenant.
        # `_ready` and the WFQ ledgers are touched only under
        # `_submit_lock` (extract_queued races the worker during drains).
        from .qos import (parse_tenant_weights as _ptw,
                          prefix_tenant_ns_enabled as _pns,
                          qos_enabled as _qen)
        self._qos = _qen()
        self._tenant_weights: Dict[str, float] = (
            _ptw(os.environ.get("LSOT_TENANT_WEIGHTS", ""))
            if self._qos else {}
        )
        self._prefix_tenant_ns = self._qos and _pns()
        self._wfq_vt = 0.0
        self._wfq_last: Dict[str, float] = {}
        self._ready: List[_Request] = []
        self._tenant_submitted: Dict[str, float] = {}
        self._tenant_preempted: Dict[str, float] = {}

        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue()
        self._prefill_q: "deque[Tuple[int, _Request]]" = deque()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._crash: Optional[BaseException] = None
        # Guards the closed-check+enqueue in submit() against the final queue
        # drain in _close(): a request either lands before the drain starts
        # (and is drained) or submit() observes _closed and raises.
        self._submit_lock = threading.Lock()
        self._closed = False
        self._prefill_fns: Dict[Tuple[int, int], object] = {}
        self._decode_fn = (self._build_spec_decode() if self._spec_draft
                           else self._build_decode())

    # ---------------------------------------------------------------- jitted

    def _build_state_ops(self):
        """Async per-slot state scatters (no host sync; ~bytes of traffic).

        park: point a freshly reserved slot's decode writes at the parking
        position before its prompt starts streaming in.
        ready: arm a slot for decode — first sampled token (still a device
        value from the prefill program), true position, sampling knobs, RNG
        stream (count=1: the prefill sample consumed fold index 0)."""
        park = self._park
        pad = self.cfg.pad_id

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def park_slot(cur, pos, cstates, crem, slot):
            # A freshly reserved slot also drops any previous occupant's
            # grammar state: parked garbage decode must run the sentinel
            # (all-allowed) row, not a stale budget-starved one. crem
            # parks at 1 — the sentinel row's need is 1, so `need <= crem`
            # genuinely allows everything (crem=0 would mask the whole
            # vocabulary: harmless for output, which is discarded, but the
            # inverse of the invariant); it never decrements while the
            # slot is inactive.
            return (
                cur.at[slot].set(pad),
                pos.at[slot].set(park),
                cstates.at[slot].set(0),
                crem.at[slot].set(1),
            )

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
        def retire_slot(temps, topps, topks, cstates, slot):
            # Reset the sampling knobs so a retired sampled request doesn't
            # leave temperature > 0 behind: sample_runtime's all-greedy
            # lax.cond fast path keys on EVERY slot's temperature, and one
            # stale hot slot would force the full vocab-sort path on all
            # subsequent rounds of an otherwise greedy workload. The
            # grammar state resets for the same hygiene (a stale
            # constrained state would keep masking the slot's parked
            # garbage decode).
            return (
                temps.at[slot].set(0.0),
                topps.at[slot].set(1.0),
                topks.at[slot].set(0),
                cstates.at[slot].set(0),
            )

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 8))
        def ready_slot(cur, pos, temps, topps, topks, seeds, counts,
                       cstates, crem, next_t, slot,
                       tok, pos_val, temp, topp, topk, seed, cinit, cbudget):
            # The first sampled token (still on device) advances the
            # grammar FSM here: cinit is the grammar start state (0 for
            # unconstrained requests — row 0 of next_t self-loops, so the
            # same scatter serves both).
            return (
                cur.at[slot].set(tok[0]),
                pos.at[slot].set(pos_val),
                temps.at[slot].set(temp),
                topps.at[slot].set(topp),
                topks.at[slot].set(topk),
                seeds.at[slot].set(seed),
                counts.at[slot].set(1),
                cstates.at[slot].set(next_t[cinit, tok[0]]),
                crem.at[slot].set(cbudget - 1),
            )

        @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 8))
        def resume_slot(cur, pos, temps, topps, topks, seeds, counts,
                        cstates, crem, slot, tok, pos_val, temp, topp, topk,
                        seed, count0, cstate0, crem0):
            # Arm a PREEMPTION-RESUMED slot from host scalars: no fresh
            # sample — `tok` is the last COMMITTED token (already
            # delivered), fed again at its own position so decode
            # continues exactly where the victim stopped. counts/cstate/
            # crem restore the committed RNG stream index, the replayed
            # FSM state, and the remaining grammar budget — the whole
            # determinism contract in one scatter.
            return (
                cur.at[slot].set(tok),
                pos.at[slot].set(pos_val),
                temps.at[slot].set(temp),
                topps.at[slot].set(topp),
                topks.at[slot].set(topk),
                seeds.at[slot].set(seed),
                counts.at[slot].set(count0),
                cstates.at[slot].set(cstate0),
                crem.at[slot].set(crem0),
            )

        return park_slot, ready_slot, retire_slot, resume_slot

    def _build_block_ops(self):
        """Jitted device-to-device prefix-block copy ops.

        slice:   each cache array [L, B, K, S(, H)] -> block [L, 1, K,
                 pblock(, H)] (values and, under kv_quant, their scales)
        restore: write the blocks back into a slot row at a block-aligned
                 start.
        Both are pure data movement — no compute — so a cache hit costs HBM
        copies instead of a transformer forward."""
        L, K, H = self.cfg.num_layers, self.cfg.num_kv_heads, self.cfg.head_dim
        pb = self._pblock
        nc = len(self._cache)

        def _sizes(arr):
            return (L, 1, K, pb, H) if arr.ndim == 5 else (L, 1, K, pb)

        def _idx(arr, slot, start):
            return ((0, slot, 0, start, 0) if arr.ndim == 5
                    else (0, slot, 0, start))

        @jax.jit
        def slice_block(*args):
            cache, (slot, start) = args[:nc], args[nc:]
            return tuple(
                lax.dynamic_slice(c, _idx(c, slot, start), _sizes(c))
                for c in cache
            )

        @partial(jax.jit, donate_argnums=tuple(range(nc)))
        def restore_block(*args):
            cache, blocks, (slot, start) = args[:nc], args[nc:2 * nc], args[2 * nc:]
            return tuple(
                lax.dynamic_update_slice(c, b, _idx(c, slot, start))
                for c, b in zip(cache, blocks)
            )

        return slice_block, restore_block

    def _build_page_ops(self):
        """Jitted paged-KV bookkeeping ops (async scatters, ~bytes of
        traffic), generic over the pool tuple — (kp, vp) compute-dtype or
        (kp, kps, vp, vps) int8 values + per-position scales:

        set_row: replace one slot's device page-table row (admission,
        retirement, copy-on-write remaps). Driven at the OOB slot index
        during warmup — jax drops the scatter, a true no-op.
        copy_page: one-page device copy for copy-on-write (a shared page
        about to be partially overwritten at a non-page-aligned boundary
        is copied into a fresh exclusive page first; the prefix-cache
        entry keeps the original). Under int8 the SCALES copy with their
        values — a page's content is (q8, s) pairs.
        restore_pages: spill-resume scatter; int8 spills restore values
        AND scales (the spill serialized both)."""
        nc = len(self._cache)

        @partial(jax.jit, donate_argnums=(0,))
        def set_row(ptab, slot, row):
            return ptab.at[slot].set(row)

        @partial(jax.jit, donate_argnums=tuple(range(nc)))
        def copy_page(*args):
            cache, (dst, src) = args[:nc], args[nc:]
            out = []
            for c in cache:
                head = (c.shape[0], 1) + c.shape[2:]
                zeros = (0,) * (c.ndim - 2)
                pg = lax.dynamic_slice(c, (0, src) + zeros, head)
                out.append(
                    lax.dynamic_update_slice(c, pg, (0, dst) + zeros)
                )
            return tuple(out)

        @partial(jax.jit, donate_argnums=tuple(range(nc)))
        def restore_pages(*args):
            # Spill-resume (LSOT_KV_SPILL) and handoff import (ISSUE 13):
            # write the host page copies [L, n, K, page(, H)] back into
            # freshly allocated pool pages in ONE scatter per array (one
            # dispatch + one transfer per resume, not per page; retraces
            # per distinct page count, bounded by pages_per_slot). The
            # scatter itself is engine/paged_kv.import_pages — the
            # first-class migration op — wrapped here with donation.
            cache, idx, stacks = args[:nc], args[nc], args[nc + 1:]
            return import_pages(cache, idx, stacks)

        return set_row, copy_page, restore_pages

    # ---------------------------------------------------- paged-KV host side

    def _sync_ptab_row(self, slot: int) -> None:
        """Mirror a slot's host page list into the device table (async
        scatter; unmapped tail entries carry the OOB sentinel)."""
        row = np.full(
            (self._pages_per_slot,), self._page_alloc.num_pages, np.int32
        )
        pages = self._slot_pages[slot]
        row[: len(pages)] = pages
        self._ptab = self._ptab_row_fn(
            self._ptab, jnp.int32(slot), jnp.asarray(row)
        )

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """All-or-nothing page grab, evicting LRU prefix-cache entries
        under pressure: cached prefixes are a perf win funded by SPARE
        pages, never a reason to make a live request wait."""
        while not self._page_alloc.can_alloc(n) and self._prefix_pages:
            key, pages = self._prefix_pages.popitem(last=False)
            self._prefix_note_evict(key, pages=pages)
            self._page_alloc.release(list(pages))
        return self._page_alloc.alloc(n)

    def _free_slot_pages(self, slot: int) -> None:
        """Retirement: drop the slot's page references (pages still held
        by prefix-cache entries survive for future hits) and unmap its
        device row."""
        if self._slot_pages[slot]:
            self._page_alloc.release(self._slot_pages[slot])
            self._slot_pages[slot] = []
            self._sync_ptab_row(slot)

    def _evict_entries_with(self, page: int) -> None:
        """Drop every prefix-cache entry referencing `page` (the
        copy-on-write fallback when the pool has no free page for the
        copy: un-publishing makes the page exclusive again, so the write
        can proceed in place without ever touching shared content)."""
        for key in [k for k, v in self._prefix_pages.items() if page in v]:
            pages = self._prefix_pages.pop(key)
            self._prefix_note_evict(key, pages=pages)
            self._page_alloc.release(list(pages))

    def _ensure_writable(self, slot: int, start_tok: int, end_tok: int) -> None:
        """Copy-on-write sweep before writing cache positions
        [start_tok, end_tok): any SHARED page in the range is either
        copied into a fresh exclusive page (content preserved, table
        remapped — the prefix-cache entry keeps the original) or, if the
        pool can't fund the copy, un-published until exclusive. Shared
        pages are never written in place — the invariant the allocator
        property tests pin. Page-aligned traffic never triggers this
        (full prefix pages sit below every write range); the only
        organic trigger is a non-page-aligned prefix boundary."""
        ps = self._page_size
        pages = self._slot_pages[slot]
        hi = min(pages_for_tokens(end_tok, ps), len(pages))
        for pi in range(start_tok // ps, hi):
            pg = pages[pi]
            if not self._page_alloc.is_shared(pg):
                continue
            fresh = self._alloc_pages(1)
            if fresh is None:
                # No page for a copy: un-publish instead. Slot-to-slot
                # sharing only ever covers FULL prefix pages below any
                # write range, so after entry eviction the page is ours.
                self._evict_entries_with(pg)
                if self._page_alloc.is_shared(pg):
                    raise RuntimeError(
                        f"page {pg} still shared inside a write range "
                        f"after un-publishing (slot {slot})"
                    )
                continue
            self._cache = self._copy_page_fn(
                *self._cache, jnp.int32(fresh[0]), jnp.int32(pg)
            )
            self._page_alloc.note_cow()
            self._page_alloc.release([pg])
            pages[pi] = fresh[0]
            self._sync_ptab_row(slot)

    # ------------------------------------------- pressure relief (ISSUE 10)

    def _reserve_new(self, req: _Request) -> int:
        """Generation tokens the admission envelope RESERVES for `req`.

        Exact mode (kv_overcommit = 1.0, the default): the full remaining
        budget — bit-for-bit the pre-overcommit envelope. Overcommitted:
        min(budget, max(ratio × budget, expected remaining generation)),
        where expected = EWMA of completed requests' generation lengths
        minus what this request already generated — the vLLM-style bet
        that most requests stop far short of max_new, with the ratio as
        the guaranteed floor. Decode tops up at each harvest; a failed
        top-up preempts (the overcommit's safety valve)."""
        remaining = max(0, req.max_new - len(req.generated))
        r = self._kv_overcommit
        if r >= 1.0 or remaining == 0:
            return remaining
        floor = -int(-remaining * r // 1)  # ceil
        expect = 0
        if self._gen_ewma is not None:
            expect = max(0, -int(-self._gen_ewma // 1)
                         - len(req.generated))
        return min(remaining, max(1, floor, expect))

    def _sample_pressure(self) -> None:
        """Chaos seam: the value-valued `kv:pressure` site withholds part
        of the pool (a fraction when the value < 1, absolute pages
        otherwise) for every loop iteration it fires — allocation and
        top-up failures become injectable, which is how the chaos stage
        forces a deterministic preemption storm. Pressure lifts the
        moment the site stops firing."""
        if not FAULTS.active:
            if self._page_alloc.withheld:
                self._page_alloc.withhold(0)
            return
        v = FAULTS.value("kv:pressure")
        if v is None:
            self._page_alloc.withhold(0)
            return
        total = self._page_alloc.num_pages
        self._page_alloc.withhold(
            int(v * total) if v < 1.0 else int(v)
        )

    def _watermark_sweep(self) -> None:
        """Proactive LRU eviction of prefix-cache pages: when available
        pages fall under the LOW watermark, evict entries until the HIGH
        watermark recovers (or the cache is empty) — pressure is relieved
        BEFORE an allocation fails, so steady-state traffic rarely needs
        a preemption. Disabled at low = 0 (the on-demand eviction inside
        _alloc_pages still backstops allocation)."""
        if not self._wm_low_pages or \
                self._page_alloc.pages_available >= self._wm_low_pages:
            return
        evicted = 0
        while self._prefix_pages and \
                self._page_alloc.pages_available < self._wm_high_pages:
            key, pages = self._prefix_pages.popitem(last=False)
            self._prefix_note_evict(key, pages=pages)
            self._page_alloc.release(list(pages))
            evicted += 1
        if evicted:
            self._page_alloc.note_evictions(evicted)
            resilience.inc("kv_evictions")
            self.flight.event("kv_evict", entries=evicted,
                              free=self._page_alloc.pages_free)

    def _sweep_page_wait(self) -> None:
        """Deadline enforcement for page-starved requests: a request
        parked on pool pages past its deadline fails fast with the typed
        DeadlineExceeded (504) instead of waiting forever — page-wait
        starvation is queue wait, and the same _observe_terminal path
        feeds the queue-wait span + histogram. Cancelled waiters resolve
        with whatever they had (the cancel contract)."""
        if not self._page_wait:
            return
        keep: "deque[_Request]" = deque()
        expired: List[_Request] = []
        while self._page_wait:
            req = self._page_wait.popleft()
            if req.cancelled:
                self._observe_terminal(req)
                req.future.set_result(req.generated)
            elif req.past_deadline():
                expired.append(req)
            else:
                keep.append(req)
        self._page_wait = keep
        # Expiry surfaces in DEADLINE order even when WFQ reorders the
        # SERVICE order (ISSUE 18 satellite): under QoS the deque is no
        # longer deadline-monotone — a heavy tenant's earlier-expiring
        # waiter can sit behind a light tenant's — and anything pairing
        # 504s with submit deadlines (clients racing timeouts, the chaos
        # harness's loss accounting) relies on earliest-first failure.
        expired.sort(key=lambda r: (r.deadline.expires_at
                                    if r.deadline is not None else 0.0))
        for req in expired:
            resilience.inc("deadline_expired")
            self._observe_terminal(req, error="DeadlineExceeded")
            req.future.set_exception(req.deadline_error())

    def _preempt_slot(self, slot: int) -> None:
        """Victim preemption: release the slot's pages and park the
        request for a DETERMINISTIC resume. Recompute mode re-runs
        prefill over prompt + committed tokens at re-admission; spill
        mode (LSOT_KV_SPILL=1) copies the committed pages to host first
        and restores them instead of recomputing. Either way the client
        keeps every delivered token and the continuation is
        token-identical to an unpreempted control: greedy trivially,
        sampled because `rng_count` restores the per-slot
        fold_in(key(seed), count) stream index, constrained because the
        FSM state is re-derived from the committed tokens."""
        req = self._slot_req[slot]
        if self._kv_spill and req.generated:
            plen = len(req.ids) + len(req.generated)
            npg = min(pages_for_tokens(plen, self._page_size),
                      len(self._slot_pages[slot]))
            # Syncs in-flight rounds; their uncommitted writes beyond the
            # committed positions ride along as garbage the resumed
            # decode overwrites before any read can see it (the same
            # write-before-read invariant every freed-page reuse relies
            # on). EVERY cache array spills — under an int8 pool the
            # quantization scales serialize beside the int8 pages, so a
            # restore reproduces the page content (q8, s) exactly and the
            # resumed output stays token-identical. export_pages is the
            # same first-class op the prefill→decode handoff ships.
            req.spilled = export_pages(
                self._cache, self._slot_pages[slot][:npg]
            )
            self._page_alloc.note_spill(int(npg))
        req.resume_pref = len(req.generated)
        req.preempted += 1
        req.ready = False
        req.prefilled = 0
        self._slot_req[slot] = None
        self._slot_epoch[slot] += 1
        if self._prefill_q:
            # Purge the victim's queued prefill entries NOW: a mid-prefill
            # victim re-admitted into the SAME slot would otherwise leave
            # a stale (slot, req) pair that _prefill_step's identity check
            # cannot tell from the fresh one — the chunk would prefill
            # twice and `prefilled` would advance two chunks for one
            # chunk's KV.
            self._prefill_q = deque(
                (s, r) for (s, r) in self._prefill_q if r is not req
            )
        # Same hygiene as retirement: a lingering temperature > 0 would
        # defeat the all-greedy fast path for every later round.
        self._temps, self._topps, self._topks, self._cstates = \
            self._retire_fn(self._temps, self._topps, self._topks,
                            self._cstates, jnp.int32(slot))
        self._free_slot_pages(slot)
        self._page_alloc.note_preempt()
        resilience.inc("kv_preemptions")
        if self._qos:
            from .qos import bounded_bump
            with self._submit_lock:
                bounded_bump(self._tenant_preempted, req.tenant)
        # Open a parked interval for the request trace tree (closed at
        # resume; flush_spans exports it as a "sched.preempted" span).
        req.parked.append([time.perf_counter(), 0.0])
        self.flight.event(
            "preempt", slot=slot, rid=req.rid,
            generated=len(req.generated), spill=req.spilled is not None,
        )
        # Victims resume ahead of never-admitted waiters: they were
        # admitted first and already hold delivered tokens.
        self._page_wait.appendleft(req)

    def _preempt_for(self, n_pages: int, protect: int) -> Optional[List[int]]:
        """Fund a failed mid-decode allocation by preempting victims —
        fewest generated tokens first (cheapest recompute), never the
        allocating slot — until the grab succeeds or no victim remains."""
        while True:
            got = self._alloc_pages(n_pages)
            if got is not None:
                return got
            victims = [
                (len(r.generated), i)
                for i, r in enumerate(self._slot_req)
                if r is not None and i != protect
            ]
            if not victims:
                return None
            if self._qos:
                # QoS enforcement arm (ISSUE 18): prefer evicting the
                # tenant holding the most WEIGHTED slot share — the one
                # over its fair allocation — before falling back to the
                # cheapest-recompute tie-break. QoS off keeps the exact
                # pre-QoS (fewest-generated, lowest-slot) choice.
                share: Dict[str, float] = {}
                for r in self._slot_req:
                    if r is not None:
                        t = r.tenant
                        share[t] = share.get(t, 0.0) + 1.0 / self._wfq_weight(t)
                victims.sort(key=lambda v: (
                    -share.get(self._slot_req[v[1]].tenant, 0.0), v[0], v[1]))
            else:
                victims.sort()
            self._preempt_slot(victims[0][1])

    def _topup_pages(self) -> None:
        """Keep every decoding slot's mapped pages ahead of the device's
        write frontier: at each harvest the committed position is
        len(ids) + len(generated), and in-flight + next-issued rounds can
        write at most `overshoot` further before the next harvest tops up
        again — so covering committed + overshoot here means the device
        NEVER writes through an unmapped (silently dropped) table entry.
        Exact-envelope admission (kv_overcommit = 1.0) prepaid the whole
        budget, so this pass allocates nothing there. A failed top-up
        preempts a victim; if even that cannot fund it (pressure
        withholding the pool), the needing slot preempts ITSELF — parked
        with a deterministic resume beats silent KV loss."""
        overshoot, ps = self.overshoot, self._page_size
        for i in range(self.num_slots):
            req = self._slot_req[i]
            if req is None or not req.ready:
                continue
            target = len(req.ids) + len(req.generated) + overshoot
            need = pages_for_tokens(target, ps) - len(self._slot_pages[i])
            if need <= 0:
                continue
            got = self._alloc_pages(need)
            if got is None:
                got = self._preempt_for(need, i)
            if got is None:
                self._preempt_slot(i)
                continue
            self._slot_pages[i].extend(got)
            self._sync_ptab_row(i)
            req.page_end = max(req.page_end,
                               len(self._slot_pages[i]) * ps)

    def _resume_ready(self, slot: int, req: _Request,
                      mode: str = "recompute") -> None:
        """Arm a preemption-resumed slot: the last COMMITTED token is fed
        again at its own position (its KV rewrite is value-identical),
        the RNG stream index restores from the host mirror, and the
        grammar FSM state is re-derived by replaying the committed tokens
        through the compiled tables — after this scatter the slot's
        device state equals the unpreempted control's at the same commit
        frontier, which is the whole token-identical-resume contract."""
        ids = req.full_ids
        plen = len(ids)
        cstate0 = 0
        if req.constraint is not None:
            cstate0 = req.constraint.walk(req.generated)
            if cstate0 is None:
                # Committed tokens came out of the masked decode, so a
                # dead replay means corrupted state — fail typed, never
                # resume into a wrong grammar row.
                raise RuntimeError(
                    f"resume FSM replay left the grammar after "
                    f"{len(req.generated)} committed tokens (rid {req.rid})"
                )
        crem0 = max(0, req.max_new - len(req.generated))
        (self._cur, self._pos, self._temps, self._topps, self._topks,
         self._seeds, self._counts, self._cstates,
         self._crem) = self._resume_fn(
            self._cur, self._pos, self._temps, self._topps, self._topks,
            self._seeds, self._counts, self._cstates, self._crem,
            jnp.int32(slot), jnp.int32(req.generated[-1]),
            jnp.int32(plen - 1),
            jnp.float32(req.temperature), jnp.float32(req.top_p),
            jnp.int32(req.top_k), jnp.uint32(req.seed & 0xFFFFFFFF),
            jnp.int32(req.rng_count), jnp.int32(cstate0),
            jnp.int32(crem0),
        )
        if self._spec_draft:
            row = np.full((self._hist.shape[1],), self.cfg.pad_id,
                          np.int32)
            row[:plen] = ids
            self._hist, self._hlen = self._spec_resume_fn(
                self._hist, self._hlen, jnp.int32(slot),
                jnp.asarray(row), jnp.int32(plen),
            )
        req.ready = True
        req.ready_at = time.perf_counter()
        if req.parked and not req.parked[-1][1]:
            # Close the parked interval: the trace span now bounds
            # exactly preempt → re-armed.
            req.parked[-1][1] = req.ready_at
        ho = req.handoff
        if ho is not None:
            # Prefill→decode migration landed (ISSUE 13): close the
            # handoff interval — pack wall, page/byte volume, and the
            # wait for a decode slot — into the request trace (the
            # `sched.handoff` span that explains the Perfetto gap
            # between prefill and first decode token), the per-round
            # flight columns, and the lsot_handoff_* counters.
            wait = max(0.0, req.ready_at - float(ho["t_pack"]))
            self._ho_wait_sum += wait
            self._ho_wait_count += 1
            self._mig_pages += int(ho["pages"])
            self._mig_wait += wait
            if req.trace is not None:
                try:
                    req.trace.add_span(
                        "sched.handoff", float(ho["t_pack"]),
                        req.ready_at, rid=req.rid,
                        pages=int(ho["pages"]), bytes=int(ho["bytes"]),
                        export_s=float(ho["export_s"]),
                        wait_s=round(wait, 6), src=ho.get("src", ""),
                    )
                except Exception:  # noqa: BLE001 — tracing must never kill the loop
                    req.trace = None
            self.flight.event("handoff_import", slot=slot, rid=req.rid,
                              pages=int(ho["pages"]),
                              wait_s=round(wait, 6), src=ho.get("src", ""))
            req.handoff = None
        # Decode re-writes [plen - 1, page_end): COW any page the
        # re-prefill's publish shared before the slot goes
        # decode-eligible (spill resumes never published — no-op there).
        self._ensure_writable(slot, max(0, plen - 1), req.page_end)
        self.flight.event("resume", slot=slot, rid=req.rid,
                          generated=len(req.generated), mode=mode)

    def _restore_spilled(self, slot: int, req: _Request) -> None:
        """Spill-resume (LSOT_KV_SPILL=1): write the host page copies —
        values AND, under an int8 pool, their quantization scales — back
        into the freshly allocated pages and arm the slot directly; no
        re-prefill forward at all."""
        parts = req.spilled
        n = parts[0].shape[1]
        idx = jnp.asarray(self._slot_pages[slot][:n], jnp.int32)
        self._cache = self._restore_page_fn(
            *self._cache, idx, *(jnp.asarray(p) for p in parts),
        )
        if req.handoff is None:
            self._page_alloc.note_restore(int(n))
        else:
            # A MIGRATED blob, not a preemption spill: counted in the
            # handoff families so the spill path's spilled == restored
            # reconciliation stays exact per pool.
            self._ho_imports += 1
            self._ho_pages_in += int(n)
            self._ho_bytes_in += handoff_bytes(parts)
        mode = "import" if req.handoff is not None else "spill"
        req.spilled = None
        req.prefilled = len(req.full_ids)
        self._resume_ready(slot, req, mode=mode)

    # ----------------------------- prefill→decode handoff (ISSUE 13)

    def _pack_handoffs(self) -> None:
        """Prefill-role terminal step: sync the parked first tokens of
        every just-completed prompt (one device_get for the whole
        group), run the same stop/budget/cancel/deadline checks a
        harvest would, commit + stream the first token, and either
        export the request's pages into a handoff blob for the pool's
        router (`on_handoff` wired) or arm the slot to decode in place
        (no consumer — a lone prefill-role scheduler still serves)."""
        if not self._handoff_pending:
            return
        pending, self._handoff_pending = self._handoff_pending, []
        vals = jax.device_get([t for (_, _, t, _) in pending])
        emitted = 0
        packed = 0
        for (slot, req, _, epoch), fv in zip(pending, vals):
            # _append_first IS the first-token commit sequence (identity/
            # epoch guard, cancel, deadline, stop-id, append+emit, budget
            # retire) — sharing it keeps the prefill-role path bit-
            # identical to the mixed harvest's, which the token-identity
            # contract depends on. Return 1 with the slot still held
            # means "committed and mid-generation": the handoff case.
            emitted += self._append_first(slot, req,
                                          int(np.asarray(fv)[0]),
                                          epoch=epoch)
            if req is not self._slot_req[slot]:
                continue  # terminal (retired/failed/budget-exhausted)
            # Chaos seam: `sched:handoff` kills the prefill loop exactly
            # here — first token committed and possibly already streamed,
            # blob never shipped. The supervisor must re-prefill on a
            # sibling with the delivered prefix suppressed (the
            # crash-mid-handoff chaos tests).
            FAULTS.check("sched:handoff")
            if self.on_handoff is None:
                self._arm_inplace(slot, req)
                continue
            if self._pump_depth and len(self._handoff) >= self._pump_depth:
                # Bounded buffer: the pump's consumer is behind by a full
                # window of packed blobs — decoding in place is cheaper
                # than pinning more exported pages on the host.
                self._ho_backpressure += 1
                self._arm_inplace(slot, req)
                continue
            self._export_handoff(slot, req)
            packed += 1
        if packed:
            cb = self.on_handoff
            try:
                cb()
            except Exception:  # noqa: BLE001 — a broken pump must not strand work
                _log.exception("on_handoff pump failed; decoding in place")
                # Reclaim whatever the pump left behind: requeue to
                # ourselves — re-admission restores the blob and decodes
                # here (the fall-back-to-in-place rule, applied late).
                for req in self.extract_handoffs():
                    self.requeue(req)
        self._record_prefill_round(emitted, packed)

    def _export_handoff(self, slot: int, req: _Request) -> None:
        """Pack one request's live KV into a portable blob and park it in
        the handoff queue: pages covering the committed positions
        (prompt + the first token — whose KV the importer's first decode
        round writes, exactly like a preemption resume rewrites its last
        committed token) extract via export_pages, and the request's
        resume state (`resume_pref`, `rng_count`) is staged so the
        importing replica's spill-restore machinery arms a slot
        device-state-identical to a mixed replica's post-prefill arm —
        the token-identity contract."""
        t0 = time.perf_counter()
        ps = self._page_size
        committed = len(req.ids) + len(req.generated)
        npg = min(pages_for_tokens(committed, ps),
                  len(self._slot_pages[slot]))
        blob = export_pages(self._cache, self._slot_pages[slot][:npg])
        wall = time.perf_counter() - t0
        nbytes = handoff_bytes(blob)
        req.spilled = blob
        req.resume_pref = len(req.generated)
        req.rng_count = 1  # the prefill sample consumed fold index 0
        req.handoff = {
            "t_pack": time.perf_counter(), "export_s": round(wall, 6),
            "pages": int(npg), "bytes": nbytes,
            "src": self.flight.replica,
        }
        self._ho_exports += 1
        self._ho_pages_out += int(npg)
        self._ho_bytes_out += nbytes
        # Prefill service EWMA: submit→pack wall per prompt token — the
        # compute-backlog price backlog_score quotes the router.
        if req.submitted_at > 0.0:
            pstok = (time.perf_counter() - req.submitted_at) \
                / max(1, len(req.ids))
            prev = self._pref_stok_ewma
            self._pref_stok_ewma = (pstok if prev is None
                                    else 0.2 * pstok + 0.8 * prev)
        # The request leaves this replica's backlog (the importing side's
        # requeue re-adds it there); its rid reads as retired in THIS
        # replica's flight attribution.
        with self._submit_lock:
            self._pending_new_tokens = max(
                0, self._pending_new_tokens - req.max_new)
            self._pending_prompt_tokens = max(
                0, self._pending_prompt_tokens - len(req.ids))
        self._round_retired.append(req.rid)
        if req.trace is not None:
            try:
                req.trace.event("sched.handoff_export", rid=req.rid,
                                pages=int(npg), bytes=nbytes)
            except Exception:  # noqa: BLE001 — tracing must never kill the loop
                req.trace = None
        self.flight.event("handoff_export", slot=slot, rid=req.rid,
                          pages=int(npg), bytes=nbytes,
                          export_s=round(wall, 6))
        self._release_slot(slot)
        self._handoff.append(req)

    def _arm_inplace(self, slot: int, req: _Request) -> None:
        """Fallback when no handoff consumer exists (bare prefill-role
        scheduler, or the pool pump failed): decode in place. The resume
        machinery arms the slot exactly as a mixed replica's ready path
        would — cur = the committed first token at its own position,
        counts = 1, FSM replayed, budget decremented — so the output is
        token-identical either way."""
        self._ho_inplace += 1
        req.resume_pref = len(req.generated)
        req.rng_count = 1
        req.prefilled = len(req.full_ids)
        self.flight.event("handoff_inplace", slot=slot, rid=req.rid)
        self._resume_ready(slot, req, mode="inplace")

    def _record_prefill_round(self, emitted: int, handoffs: int) -> None:
        """Prefill-role round bookkeeping: a pure prefill replica never
        harvests a decode round, so the flight record, heartbeat cadence
        and prefill roofline attribution land here — one record per pack
        pass that concluded at least one request (handoff, in-place arm
        or terminal)."""
        if not (emitted or handoffs or self._round_retired
                or self._round_admitted):
            return
        self.heartbeat.round_done()
        now = time.perf_counter()
        prev, self._last_pack_t = self._last_pack_t, now
        interval = round(now - prev, 6) if prev is not None else 0.0
        ewma = self.heartbeat.expected_round_s()
        rec = {
            "round": self.heartbeat.rounds,
            "occupancy": sum(1 for r in self._slot_req if r is not None),
            "queued": self._queue.qsize(),
            "admitted": self._round_admitted,
            "retired": self._round_retired,
            "emitted": emitted,
            "handoffs": handoffs,
            "round_wall_s": interval,
            "cadence_s": round(ewma, 6) if ewma is not None else None,
            "phase": "prefill",
        }
        if prev is not None:
            # First pack pass has no interval origin: leave the banked
            # FLOPs for the next record instead of attributing a real
            # wall of work over a degenerate denominator (the inflated
            # MFU would pollute the EWMA and bench --compare's gates).
            pre = self.perf.flush_prefill(interval)
            if pre is not None:
                rec["prefill_mfu"] = pre["mfu"]
                rec["prefill_hbm_util"] = pre["hbm_util"]
        if self._paged:
            rec["kv_pages"] = self._page_alloc.pages_in_use
            rec["kv_pages_free"] = self._page_alloc.pages_free
            rec["kv_pressure"] = self._page_alloc.withheld
        self.flight.record(**rec)
        self._round_admitted = []
        self._round_retired = []

    @property
    def handoff_stats(self) -> Optional[Dict[str, object]]:
        """Disaggregation observability (None for a mixed replica that
        never touched a handoff): export/import/fallback counters, page
        and byte volumes, and the summed wait for a decode slot — the
        lsot_handoff_* Prometheus families and the /metrics
        serving.handoff payload."""
        if self.phase_role == "mixed" and not (
                self._ho_exports or self._ho_imports or self._ho_inplace):
            return None
        return {
            "replica": self.flight.replica,
            "phase_role": self.phase_role,
            "exports": self._ho_exports,
            "imports": self._ho_imports,
            "inplace_fallbacks": self._ho_inplace,
            "pages_out": self._ho_pages_out,
            "pages_in": self._ho_pages_in,
            "bytes_out": self._ho_bytes_out,
            "bytes_in": self._ho_bytes_in,
            "wait_s_sum": round(self._ho_wait_sum, 6),
            "wait_count": self._ho_wait_count,
            "queued_handoffs": len(self._handoff),
            "backpressure": self._ho_backpressure,
        }

    @property
    def page_stats(self) -> Optional[Dict[str, int]]:
        """Paged-KV observability (None when contiguous): pool occupancy
        and sharing counters — `zero_copy_shares` rising with prefix hits
        while `cow_copies` stays at boundary-only counts is the
        "sharing, not copying" proof the bench artifact records; a leaked
        page shows up as pages_in_use that never drains. The pressure
        block (preemptions/evictions/spilled/withheld + watermarks) is
        the graceful-degradation dashboard."""
        if not self._paged:
            return None
        out = self._page_alloc.stats()
        out["pages_per_slot"] = self._pages_per_slot
        out["page_waits"] = self._page_wait_events
        out["overcommit"] = self._kv_overcommit
        out["spill"] = int(self._kv_spill)
        out["watermark_low_pages"] = self._wm_low_pages
        out["watermark_high_pages"] = self._wm_high_pages
        # KV-dtype-aware capacity (ISSUE 11 satellite): the TRUE device
        # bytes of one page — int8 pools report ~half a compute-dtype
        # page — so /metrics serving.kv_pages, watermark ratios and
        # overcommit dashboards act on real bytes, not compute-dtype
        # fiction.
        out["kv_quant"] = self.kv_quant or ""
        out["page_bytes"] = page_bytes(
            self.cfg, self._page_size, self._dtype.itemsize, self.kv_quant
        )
        return out

    # --------------------------------------------------- performance ledger

    @property
    def perf_stats(self) -> Dict[str, object]:
        """The `serving.perf` /metrics payload: the analytic model's
        pricing assumptions + per-phase EWMAs of the live roofline
        position (prefill/decode/draft/verify MFU, HBM util, binding
        roof), replica-labeled for the Prometheus gauges."""
        return {"replica": self.flight.replica, **self.perf.stats()}

    # ------------------------------------------------ on-demand profiling

    def _profile_owner(self) -> str:
        return f"sched:{self.flight.replica}:{id(self):x}"

    def profile_rounds(self, rounds: Optional[int] = None,
                       out_dir: Optional[str] = None) -> Dict[str, object]:
        """Arm a bounded `jax.profiler` device-trace capture around the
        next `rounds` scheduler rounds (the /debug/profile seam). The
        capture starts on the worker thread at the next issued round and
        stops after N harvested rounds; the artifact (Perfetto-loadable
        *.trace.json.gz, the same format the per-request trace exports
        use) lands under `out_dir` — default: next to the tracer's
        export dir (utils/traceprof.profile_defaults). Raises
        RuntimeError when ANY capture is already in flight fleet-wide
        (the process-wide guard)."""
        import tempfile

        d_def, r_def = traceprof.profile_defaults()
        # None -> the configured default; an EXPLICIT 0 must be a clear
        # request error, never a silent default-8 capture that takes the
        # fleet-wide slot nobody asked for.
        n = r_def if rounds is None else int(rounds)
        if n < 1:
            raise ValueError(f"rounds must be >= 1, got {n}")
        owner = self._profile_owner()
        if not traceprof.try_acquire_capture(owner):
            raise RuntimeError(
                f"a device profile capture is already in flight "
                f"(owner {traceprof.capture_owner()}); one at a time "
                f"fleet-wide"
            )
        base = out_dir or d_def
        try:
            if base:
                d = os.path.join(
                    base, f"profile-{int(time.time() * 1000)}-"
                          f"{self.flight.replica}"
                )
                os.makedirs(d, exist_ok=True)
            else:
                d = tempfile.mkdtemp(prefix="lsot_profile_")
        except OSError:
            traceprof.release_capture(owner)
            raise
        with self._profile_lock:
            self._profile_arm = {"rounds": n, "dir": d, "owner": owner,
                                 "armed_at": time.time()}
        return {"state": "armed", "rounds": n, "dir": d,
                "replica": self.flight.replica}

    def profile_status(self) -> Dict[str, object]:
        """Live capture state: armed (waiting for the next round) /
        capturing (rounds left) / the last finished capture's artifact
        list — what the smoke script polls."""
        with self._profile_lock:
            arm, active, last = (self._profile_arm, self._profile_active,
                                 self._profile_last)
            out: Dict[str, object] = {"replica": self.flight.replica}
            if active is not None:
                out.update({"state": "capturing",
                            "rounds_left": active["rounds_left"],
                            "dir": active["dir"]})
            elif arm is not None:
                out.update({"state": "armed", "rounds": arm["rounds"],
                            "dir": arm["dir"]})
            else:
                out["state"] = "idle"
            if last is not None:
                out["last"] = dict(last)
        return out

    def _maybe_start_profile(self) -> None:
        """Worker-thread start: consume the armed request and open the
        device trace so the next issued round is inside the capture."""
        with self._profile_lock:
            arm = self._profile_arm
            if arm is None or self._profile_active is not None:
                return
            self._profile_arm = None
        try:
            jax.profiler.start_trace(arm["dir"])
        except Exception as e:  # noqa: BLE001 — profiling must not kill serving
            traceprof.release_capture(arm["owner"])
            with self._profile_lock:
                self._profile_last = {"state": "error",
                                      "error": str(e)[:200],
                                      "dir": arm["dir"]}
            return
        with self._profile_lock:
            self._profile_active = {
                "rounds_left": arm["rounds"], "rounds": arm["rounds"],
                # Rounds already in flight were ISSUED before the trace
                # started: their harvests must not count toward the
                # capture, or a lag-deep pipeline under live traffic
                # brackets only N-1 (or zero) complete in-trace rounds.
                "skip": len(self._pending),
                "dir": arm["dir"], "owner": arm["owner"],
                "started": time.time(),
            }
        self.flight.event("profile_start", rounds=arm["rounds"],
                          dir=arm["dir"])

    def _profile_round_done(self) -> None:
        with self._profile_lock:
            st = self._profile_active
            if st is None:
                return
            if st["skip"] > 0:
                st["skip"] -= 1  # pre-trace round draining the pipeline
                return
            st["rounds_left"] -= 1
            if st["rounds_left"] > 0:
                return
            self._profile_active = None
        self._finish_profile(st)

    def _finish_profile(self, st: Dict[str, object],
                        error: Optional[str] = None) -> None:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — a failed stop is still a finish
            error = error or str(e)[:200]
        arts = traceprof.find_profile_artifacts(str(st["dir"]))
        out: Dict[str, object] = {
            "state": "done" if arts else "error",
            "dir": st["dir"],
            "rounds": st["rounds"],
            "artifacts": arts,
            "artifact_bytes": sum(
                os.path.getsize(a) for a in arts if os.path.exists(a)
            ),
            "wall_s": round(time.time() - float(st["started"]), 3),
        }
        if error:
            out["error"] = error
            out["state"] = "error"
        with self._profile_lock:
            self._profile_last = out
        traceprof.release_capture(str(st["owner"]))
        self.flight.event("profile_done", state=out["state"],
                          artifacts=len(arts))

    def _abort_profile(self, reason: str) -> None:
        """Shutdown/crash hygiene: an armed or mid-flight capture must
        not leak the fleet-wide guard (or a dangling jax trace) past the
        loop that owned it."""
        with self._profile_lock:
            arm, self._profile_arm = self._profile_arm, None
            active, self._profile_active = self._profile_active, None
        if arm is not None:
            traceprof.release_capture(str(arm["owner"]))
            with self._profile_lock:
                self._profile_last = {"state": "aborted", "error": reason,
                                      "dir": arm["dir"]}
        if active is not None:
            self._finish_profile(active, error=reason)

    def _build_prefill(self, t_bucket: int, k: int):
        cfg, impl, mesh = self.cfg, self._impl, self.mesh
        quant, dtype = self.kv_quant, self._dtype
        nc = len(self._cache)
        spec = bool(self._spec_draft)
        paged = self._paged
        if paged:
            ps, np_tab = self._page_size, self._pages_per_slot
            num_pages = self._page_alloc.num_pages

        # Speculative mode appends the on-device draft history as one more
        # donated arg: the chunk's tokens scatter into hist rows at the
        # same positions their K/V land at (drafting needs the prompt text,
        # and it is already on device for the forward anyway).
        # Paged mode appends the device page tables LAST (non-donated:
        # tables are tiny and in-flight rounds must keep reading the
        # version they were issued with).
        donate = tuple(range(1, 1 + nc)) + ((12 + nc,) if spec else ())

        @partial(jax.jit, donate_argnums=donate)
        def prefill(params, *args):
            """One prompt chunk for EACH of k slots in one forward — prefill
            is MXU-bound and weight streaming amortizes across the batch
            (admission bursts would otherwise pay a full weight pass per
            B=1 request). Row i's tokens occupy absolute positions
            [starts[i], starts[i]+lengths[i]); its last real logit samples
            with the request's own stream at fold index 0 (used only on
            final chunks).

            Padding rows carry slot index num_slots (out of bounds): the
            gather clamps harmlessly and the scatter DROPS their cache
            writes (jax scatter OOB semantics), so a partially filled
            k-batch is safe without duplicate-slot scatters.

            With kv_quant, the gathered rows dequantize to the compute
            dtype for the chunk forward, but only THIS chunk's window
            [start, start+t) requantizes and scatters back: a full-row
            scatter would round-trip earlier chunks' entries
            int8→bf16→int8 once per subsequent chunk, and bf16 rounding
            of q8·s can flip int8 LSBs each pass — drift would accumulate
            over a long multi-chunk prompt. Windowed, every entry is
            quantized exactly once (scales are per-position, so the
            window owns its scales too).
            """
            cache = args[:nc]
            (tokens, lengths, slots, starts, temps, topps, topks,
             seeds, cinits, cbudgets) = args[nc:nc + 10]
            g_need = args[nc + 10]
            hist = args[nc + 11] if spec else None
            if paged:
                ptab = args[-1]
                # Per-row page tables: OOB padding slots get an all-sentinel
                # row (mode="fill"), so BOTH their gather garbage is
                # causally masked and their scatter-back below drops — a
                # clamped gather would alias a real slot's pages and the
                # scatter would corrupt them.
                tab = jnp.take(
                    ptab, slots, axis=0, mode="fill", fill_value=num_pages
                )  # [k, NP]
                safe = jnp.clip(tab, 0, num_pages - 1)

                def rowview(pool):
                    # [L, P, K, ps(, H)] -> contiguous per-row view
                    # [L, k, K, NP*ps(, H)] for the chunk forward (the same
                    # row gather the contiguous path pays via c[:, slots];
                    # the scale arrays of an int8 pool drop the H axis).
                    g = pool[:, safe]  # [L, k, NP, K, ps(, H)]
                    perm = ((0, 1, 3, 2, 4, 5) if pool.ndim == 5
                            else (0, 1, 3, 2, 4))
                    shape = (pool.shape[0], safe.shape[0], pool.shape[2],
                             np_tab * ps) + (
                        (pool.shape[4],) if pool.ndim == 5 else ())
                    return g.transpose(perm).reshape(shape)

                if quant:
                    # int8 pool: dequantize the gathered rows for the
                    # chunk forward (q8 × per-position scale), exactly
                    # the contiguous int8 prefill's gather-dequant — the
                    # scatter-back below requantizes ONLY this chunk's
                    # window, so every entry quantizes exactly once.
                    row_cache = {
                        "k": (rowview(cache[0]).astype(dtype)
                              * rowview(cache[1])[..., None].astype(dtype)),
                        "v": (rowview(cache[2]).astype(dtype)
                              * rowview(cache[3])[..., None].astype(dtype)),
                    }
                else:
                    row_cache = {"k": rowview(cache[0]),
                                 "v": rowview(cache[1])}
            else:
                rows = [c[:, slots] for c in cache]  # [L, k, K, S(, H)]
                if quant:
                    row_cache = {
                        "k": (rows[0].astype(dtype)
                              * rows[1][..., None].astype(dtype)),
                        "v": (rows[2].astype(dtype)
                              * rows[3][..., None].astype(dtype)),
                    }
                else:
                    row_cache = {"k": rows[0], "v": rows[1]}
            positions = (
                starts[:, None] + jnp.arange(t_bucket, dtype=jnp.int32)[None, :]
            )
            logits, new = forward(
                cfg, params, tokens, positions, row_cache,
                logit_indices=lengths - 1, attn_impl=impl, mesh=mesh,
            )
            if paged:
                # Scatter ONLY this chunk's window through the page
                # tables: the quant path's windowed-scatter template, with
                # (page, offset) indices instead of (slot, position) —
                # other pages of the row may be SHARED prefix pages that
                # must never be written (the host's ensure-writable sweep
                # guarantees the window's own pages are exclusive).
                pos_idx = positions  # [k, t] = starts[:, None] + arange(t)
                row_ar = jnp.arange(pos_idx.shape[0], dtype=jnp.int32)
                wk = new["k"][:, row_ar[:, None], :, pos_idx]  # [k,t,L,K,H]
                wv = new["v"][:, row_ar[:, None], :, pos_idx]
                page_idx = pos_idx // ps
                pages = jnp.take_along_axis(
                    tab, jnp.clip(page_idx, 0, np_tab - 1), axis=1
                )  # [k, t]; sentinel rows/entries drop their writes
                # Positions past the virtual row (a resumed prompt's final
                # chunk bucket can overhang it) must DROP, not clip: the
                # clipped lookup would alias the row's LAST mapped page
                # and overwrite real KV at matching offsets.
                pages = jnp.where(page_idx < np_tab, pages,
                                  jnp.int32(num_pages))
                offs = pos_idx % ps
                if quant:
                    # int8 pool: requantize the chunk's window (values +
                    # per-position scales) and scatter both through the
                    # table — windowed, so earlier chunks' entries never
                    # round-trip int8→bf16→int8 (the same
                    # exactly-once-quantized contract as the contiguous
                    # int8 path).
                    from ..ops.quant import quantize_cache

                    wins = _cache_tuple(quantize_cache(wk, wv))
                    cache = tuple(
                        c.at[:, pages, :, offs].set(w)
                        for c, w in zip(cache, wins)
                    )
                else:
                    cache = (
                        cache[0].at[:, pages, :, offs].set(wk),
                        cache[1].at[:, pages, :, offs].set(wv),
                    )
            elif quant:
                from ..ops.quant import quantize_cache

                # Window gather BY THE SAME positions the forward wrote and
                # the scatter below targets — not a dynamic_slice, whose
                # clamped *start* would shift the whole window when a
                # prefix-cache-misaligned final chunk runs past S
                # (start + t_bucket > S): gather clamps and scatter drops
                # PER ELEMENT, so every in-bounds position j still maps
                # new[start+j] -> cache[start+j] and only the past-the-end
                # tail (whose writes the old full-row scatter also never
                # materialized) degenerates.
                pos_idx = positions  # [k, t] = starts[:, None] + arange(t)
                row_ar = jnp.arange(pos_idx.shape[0], dtype=jnp.int32)
                # Advanced indices at non-adjacent dims broadcast to the
                # FRONT: windows come out [k, t, L, K(, H)] — exactly the
                # layout the scatter below expects.
                wk = new["k"][:, row_ar[:, None], :, pos_idx]
                wv = new["v"][:, row_ar[:, None], :, pos_idx]
                wins = _cache_tuple(quantize_cache(wk, wv))
                cache = tuple(
                    # OOB padding slots / past-the-end positions drop their
                    # writes (jax scatter OOB semantics), as before.
                    c.at[:, slots[:, None], :, pos_idx].set(w)
                    for c, w in zip(cache, wins)
                )
            else:
                cache = tuple(
                    c.at[:, slots].set(n)
                    for c, n in zip(cache, (new["k"], new["v"]))
                )
            keys = jax.vmap(
                lambda s: jax.random.fold_in(jax.random.key(s), 0)
            )(seeds)
            # Constrained rows sample their FIRST token under the grammar
            # start-state's budget-aware mask, computed ON DEVICE from the
            # installed need table and per-row (init state, budget) scalars
            # — the host ships 2*k ints per round, not a [k, vocab] bool
            # array. Unconstrained/padding rows carry state 0 (need 1):
            # all-allowed.
            first_logits = apply_token_mask(
                logits[:, 0], g_need[cinits] <= cbudgets[:, None]
            )
            toks = sample_runtime(first_logits, temps, topps, topks, keys)
            if spec:
                # OOB padding slots drop their history writes too.
                hist = hist.at[slots[:, None], positions].set(tokens)
                return (*cache, hist, toks)
            return (*cache, toks)

        return prefill

    def _build_decode(self):
        cfg, impl, chunk = self.cfg, self._decode_impl, self.decode_chunk
        mesh = self.mesh
        pad_id = cfg.pad_id
        nc = len(self._cache)
        paged = self._paged

        def cache_in(cache, ptab):
            if paged:
                return _paged_cache_dict(cache, ptab)
            return _cache_dict(cache)

        def cache_out(new_cache):
            if paged:
                return _paged_cache_tuple(new_cache)
            return _cache_tuple(new_cache)

        @partial(jax.jit,
                 donate_argnums=tuple(range(1, 3 + nc))
                 + (8 + nc, 9 + nc, 10 + nc))
        def decode(params, *args):
            cache = args[:nc]
            (cur, pos, active, temps, topps, topks, seeds,
             counts, cstates, crem, g_next, g_need) = args[nc:nc + 12]
            ptab = args[nc + 12] if paged else None
            # Per-layer slices outside the chunk scan: decode-matmul layout
            # conversions run once per round, not per token (split_blocks).
            params = split_blocks(params)

            def step(carry, i):
                cache, cur, pos, cstates, crem = carry
                logits, new_cache = forward(
                    cfg, params, cur[:, None], pos[:, None],
                    cache_in(cache, ptab), attn_impl=impl, mesh=mesh,
                    # Parked slots (decoding garbage at the park position)
                    # stream ZERO KV blocks; live slots stream only up to
                    # their own position — without this every decode step
                    # pays S_max bandwidth per slot (pallas/paged impls).
                    kv_lens=jnp.where(active, pos + 1, 0),
                )
                # Grammar masking: ONE table gather + compare per step, no
                # host involvement and no per-token vocab iteration. A
                # token is allowed iff the tokens it commits to (itself +
                # shortest completion + stop id, the precomputed `need`
                # table) fit the slot's remaining budget — so constrained
                # completions always parse, never truncate. cstate 0 is
                # the all-allowed sentinel row (need 1), so mixed
                # constrained/unconstrained batches share this one
                # program.
                step_logits = apply_token_mask(
                    logits[:, 0], g_need[cstates] <= crem[:, None]
                )
                # Slot s's i-th token of this chunk is sample number
                # counts[s]+i of its request's stream — reproducible across
                # any batch composition.
                keys = jax.vmap(
                    lambda s, c: jax.random.fold_in(jax.random.key(s), c)
                )(seeds, counts + i)
                nxt = sample_runtime(step_logits, temps, topps, topks, keys)
                nxt = jnp.where(active, nxt, pad_id)
                cstates = jnp.where(active, g_next[cstates, nxt], cstates)
                crem = jnp.where(active, crem - 1, crem)
                pos = jnp.where(active, pos + 1, pos)
                return (cache_out(new_cache), nxt, pos, cstates, crem), nxt

            (cache, cur, pos, cstates, crem), toks = lax.scan(
                step, (cache, cur, pos, cstates, crem), jnp.arange(chunk)
            )
            # RNG stream bookkeeping advances on device too: every active
            # slot consumed `chunk` samples.
            counts = jnp.where(active, counts + chunk, counts)
            # toks: [slots, chunk]
            return (*cache, cur, pos, counts, cstates, crem, toks.T)

        return decode

    def _build_spec_ready(self):
        """Jitted history arm for a freshly prefilled slot: the first
        sampled token lands at position plen and the valid length becomes
        plen + 1 (the prompt tokens themselves were scattered into the
        history by the prefill fn, chunk by chunk)."""

        @partial(jax.jit, donate_argnums=(0, 1))
        def spec_ready(hist, hlen, slot, tok, plen):
            return hist.at[slot, plen].set(tok[0]), hlen.at[slot].set(plen + 1)

        @partial(jax.jit, donate_argnums=(0, 1))
        def spec_resume(hist, hlen, slot, row, plen):
            # Preemption resume: rewrite the slot's WHOLE history row
            # (prompt + committed generated tokens, pad beyond) and set
            # hlen to the committed length — the ngram draft source is
            # then byte-identical to the unpreempted control's, which the
            # sampled-speculative determinism contract needs. Serves both
            # recompute (prefill re-scattered the same tokens; this
            # overwrite is a content no-op that also scrubs any stale
            # previous-occupant tail) and spill-restore (no prefill ran,
            # so this IS the history rebuild).
            return hist.at[slot].set(row), hlen.at[slot].set(plen)

        return spec_ready, spec_resume

    def _build_spec_decode(self):
        """One speculative round for the whole slot batch: draft D tokens
        per slot by prompt lookup over the on-device history, verify with a
        single T=D+1 forward, emit the accepted chain. Greedy slots verify
        by exact argmax (token-identical to vanilla greedy decode);
        temperature>0 slots verify by REJECTION SAMPLING
        (engine/speculative.rejection_sample_chain): draft token i is
        accepted iff a uniform draw lands under its mass in the target
        distribution (grammar-masked, temperature/top-k/top-p-filtered —
        softmax of ops.sampling.filtered_runtime_logits, the same
        distribution a vanilla sample_runtime step draws from), and the
        round's final token comes from the normalized residual (first
        rejection) or the target itself (all accepted) — so sampled slots
        emit 1..D+1 tokens per round, distribution-identical to vanilla
        sampling. Both classes ride this ONE compiled program: greedy vs
        sampled is a per-row `temps <= 0` select, and an all-greedy round
        skips the window-wide sort/softmax via lax.cond (mirroring
        sample_runtime's fast path). Per-slot state — history, length,
        position, RNG counts, grammar FSM state and budget — advances on
        device; the host harvests (emitted [slots, D+1], n_emit [slots]) a
        lag late, exactly like vanilla rounds.

        Sampled determinism: slot s's round keys derive as
        fold_in(key(seed), counts) with counts advancing by one per
        harvested sampled round, so a (seed, request) pair reproduces the
        same tokens whatever other traffic shares the batch — the
        contract crash-replay token suppression (serve/supervisor.py)
        depends on.

        Grammar constraining composes per position: each slot's draft
        chain advances its FSM (constrain.fsm_advance_chain — drafts stop
        counting at the first grammar-rejected token), every verify
        position's logits are masked with its OWN per-position state's
        budget-aware row before argmax, acceptance is capped at the
        grammar-valid prefix, and the committed `cstate` is the state
        after the accepted prefix — rejected drafts never advance it (the
        FSM twin of the rejected-K/V rewind the cache-visibility invariant
        already covers). Unconstrained slots sit at the sentinel state 0
        (need 1 = all-allowed), so mixed constrained/unconstrained batches
        ride this ONE compiled program, exactly like vanilla decode.

        Attention runs the einsum impl: the verify window needs the
        unrolled small-T path (which is also the only int8-KV path), and
        the pallas decode kernel is a T=1 specialization. Parked slots
        verify garbage at the parking position — their cache writes clamp
        into their own row's tail, which the visibility invariant covers —
        and emit nothing (n_emit=0); their history write is routed past
        max_seq so a slot mid-chunked-prefill cannot have its freshly
        scattered prompt history punched by pad writes at a stale hlen."""
        from ..constrain.masks import fsm_advance_chain
        from ..engine.speculative import (
            emit_chain,
            ngram_draft,
            rejection_sample_chain,
        )

        cfg, mesh = self.cfg, self.mesh
        D, ngram = self._spec_draft, self._spec_ngram
        d1 = D + 1
        pad_id = cfg.pad_id
        nc = len(self._cache)
        paged = self._paged

        @partial(jax.jit,
                 donate_argnums=tuple(range(1, nc + 5))
                 + (nc + 10, nc + 11, nc + 12))
        def spec_decode(params, *args):
            cache = args[:nc]
            (hist, hlen, cur, pos, active, temps, topps, topks, seeds,
             counts, cstates, crem, g_next, g_need) = args[nc:nc + 14]
            ptab = args[nc + 14] if paged else None
            params = split_blocks(params)
            drafts = ngram_draft(hist, hlen, D, ngram)           # [S, D]
            verify = jnp.concatenate([cur[:, None], drafts], 1)  # [S, D+1]
            jd = jnp.arange(d1, dtype=jnp.int32)[None, :]
            vpos = pos[:, None] + jd
            logits, new_cache = forward(
                cfg, params, verify, vpos,
                (_paged_cache_dict(cache, ptab) if paged
                 else _cache_dict(cache)),
                attn_impl="xla", mesh=mesh,
            )
            # Per-position grammar masking: pstates[:, j] is the slot's
            # FSM state after accepting drafts[:, :j], vlen the longest
            # grammar-valid draft prefix under the per-position budget
            # (crem - j — the exact mask a vanilla round would apply at
            # that step). Masked argmax at position j therefore IS the
            # vanilla constrained greedy token there, which is what makes
            # constrained+speculative output token-identical to
            # constrained vanilla decode.
            pstates, vlen = fsm_advance_chain(
                g_next, g_need, cstates, drafts, crem
            )                                                    # [S,D+1],[S]
            logits = apply_token_mask(
                logits, g_need[pstates] <= (crem[:, None] - jd)[:, :, None]
            )
            preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, D+1]
            # preds[j] is the true greedy token after verify[j] iff every
            # draft before j was accepted; accept the longest such chain —
            # capped at the grammar-valid prefix (a rejected draft must
            # not be accepted even where the masked model would agree).
            eq = ((drafts == preds[:, :D])
                  & (jd[:, :D] < vlen[:, None])).astype(jnp.int32)
            acc = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)         # [S]
            greedy = temps <= 0.0
            keys = jax.vmap(
                lambda s, c: jax.random.fold_in(jax.random.key(s), c)
            )(seeds, counts)
            ns = preds.shape[0]

            def rejection_path(_):
                # Filtered target logits at EVERY verify position: the
                # grammar mask was applied above (exactly where a vanilla
                # round applies it), so softmax(filt[:, j]) is the
                # distribution vanilla sample_runtime would draw token j
                # from, grammar-rejected drafts carry zero target mass
                # (auto-reject, capping acceptance at the valid prefix),
                # and the rejection residual is grammar-renormalized for
                # free. One [S, D+1, V] sort per round.
                filt = filtered_runtime_logits(
                    logits, temps[:, None], topps[:, None], topks[:, None],
                )
                return rejection_sample_chain(filt, drafts, keys)

            # All-greedy rounds (the NL→SQL common case) skip the
            # window-wide sort/softmax/draws entirely — the same fast
            # path sample_runtime keys on, lifted to the whole window.
            acc_s, extra = lax.cond(
                jnp.all(greedy),
                lambda _: (jnp.zeros((ns,), jnp.int32),
                           jnp.zeros((ns,), jnp.int32)),
                rejection_path, None,
            )
            emitted_s = emit_chain(drafts, acc_s, extra, pad_id)
            n_emit = jnp.where(
                active, jnp.where(greedy, acc + 1, acc_s + 1), 0
            )
            emitted = jnp.where(greedy[:, None], preds, emitted_s)
            emitted = jnp.where(jd < n_emit[:, None], emitted, pad_id)
            # Inactive rows write past max_seq (clamped into the history's
            # spare tail), never at their stale hlen.
            write_at = jnp.where(
                active, hlen, jnp.int32(hist.shape[1])
            )
            hist = jax.vmap(
                lambda h, e, s: lax.dynamic_update_slice(h, e, (s,))
            )(hist, emitted, write_at)
            cur = jax.vmap(
                lambda e, n, c: jnp.where(n > 0, e[jnp.maximum(n - 1, 0)], c)
            )(emitted, n_emit, cur)
            # Commit the FSM to the state after the accepted prefix: the
            # last emitted token advances from ITS per-position state
            # (for accepted drafts emitted[j] == drafts[j] in BOTH
            # classes, so this lands exactly on the chain state; a
            # sampled row's residual/bonus token advances from the state
            # after its accepted prefix). n_emit == 0 rows freeze —
            # rejected drafts never move the committed state (rewind by
            # construction).
            idx = jnp.maximum(n_emit - 1, 0)
            last_s = jnp.take_along_axis(pstates, idx[:, None], 1)[:, 0]
            last_t = jnp.take_along_axis(emitted, idx[:, None], 1)[:, 0]
            cstates = jnp.where(n_emit > 0, g_next[last_s, last_t], cstates)
            crem = crem - n_emit
            pos = pos + n_emit
            hlen = hlen + n_emit
            # Sampled slots consumed one stream index per ROUND (the
            # round key fans out into the window's accept/residual draws
            # inside rejection_sample_chain); greedy argmax consumed
            # none. Round count per request is deterministic — drafting
            # reads only the row's own history — so (seed, request)
            # reproduces the same tokens under any batch mix.
            counts = counts + jnp.where(active & ~greedy, 1, 0)
            out_cache = (_paged_cache_tuple(new_cache) if paged
                         else _cache_tuple(new_cache))
            return (*out_cache, hist, hlen, cur, pos, counts,
                    cstates, crem, emitted, n_emit)

        return spec_decode

    def _build_mixed(self, t_bucket: int):
        """One compiled MIXED round (LSOT_RAGGED=1, ISSUE 19): this
        iteration's prompt chunks and the decode round ride a single
        [S, t_bucket] ragged launch instead of alternating programs.
        Prefill rows (is_pref) carry their whole chunk and scatter it
        through their page tables; decode rows carry their current token
        in column 0 with dead padding beyond — the per-row q_lens vector
        routes dead columns' K/V writes to the sentinel page and (pallas)
        zeroes their attention output, so neither class perturbs the
        other. Step 0 is ONE ragged forward; chunk steps 1..chunk-1 reuse
        _build_decode's T=1 step body verbatim under lax.scan (prefill
        rows are inactive there: not yet armed, `active` gates every
        advance). Sampling stays per-row deterministic: prefill rows
        sample their first token at fold 0 under the grammar start
        state's budget mask (== _build_prefill), decode rows sample chunk
        token i at fold counts+i under their committed state (==
        _build_decode) — so each request's token stream is identical to
        the alternating control's; only round BOUNDARIES shift (a slot
        finishing prefill here decodes starting next round)."""
        cfg, mesh = self.cfg, self.mesh
        impl, dimpl = self._impl, self._decode_impl
        chunk = self.decode_chunk
        pad_id = cfg.pad_id
        nc = len(self._cache)
        t = t_bucket
        ps, np_tab = self._page_size, self._pages_per_slot
        s_virt = np_tab * ps  # dead-col sentinel position (write drops)

        @partial(jax.jit,
                 donate_argnums=tuple(range(1, 3 + nc))
                 + (8 + nc, 9 + nc, 10 + nc))
        def mixed(params, *args):
            cache = args[:nc]
            (cur, pos, active, temps, topps, topks, seeds,
             counts, cstates, crem) = args[nc:nc + 10]
            (p_tokens, p_lengths, p_starts, is_pref, p_temps, p_topps,
             p_topks, p_seeds, p_cinits, p_cbudgets) = args[nc + 10:nc + 20]
            g_next, g_need = args[nc + 20:nc + 22]
            ptab = args[nc + 22]
            params = split_blocks(params)
            col = jnp.arange(t, dtype=jnp.int32)[None, :]
            tokens0 = jnp.where(
                is_pref[:, None], p_tokens,
                jnp.where(col == 0, cur[:, None], pad_id),
            )
            # Dead decode columns sit at the virtual-row position: their
            # page lookup lands on the sentinel (write drops) and the
            # causal mask over kv_lens keeps their garbage logits finite.
            pos0 = jnp.where(
                is_pref[:, None], p_starts[:, None] + col,
                jnp.where(col == 0, pos[:, None], jnp.int32(s_virt)),
            )
            q_lens_v = jnp.where(is_pref, t, 1).astype(jnp.int32)
            kv0 = jnp.where(
                is_pref, jnp.clip(p_starts + t, 0, s_virt),
                jnp.where(active, pos + 1, 0),
            ).astype(jnp.int32)
            logit_idx = jnp.where(is_pref, p_lengths - 1, 0)
            logits, new_cache = forward(
                cfg, params, tokens0, pos0,
                _paged_cache_dict(cache, ptab),
                logit_indices=logit_idx, attn_impl=impl, mesh=mesh,
                kv_lens=kv0, q_lens=q_lens_v,
            )
            cache = _paged_cache_tuple(new_cache)
            # Combined first sample, per-row knobs: prefill rows fold 0
            # of THEIR seed under (init state, full budget); decode rows
            # fold counts under (committed state, remaining budget).
            m_states = jnp.where(is_pref, p_cinits, cstates)
            m_rem = jnp.where(is_pref, p_cbudgets, crem)
            m_seeds = jnp.where(is_pref, p_seeds, seeds)
            m_counts = jnp.where(is_pref, 0, counts)
            m_temps = jnp.where(is_pref, p_temps, temps)
            m_topps = jnp.where(is_pref, p_topps, topps)
            m_topks = jnp.where(is_pref, p_topks, topks)
            keys = jax.vmap(
                lambda s, c: jax.random.fold_in(jax.random.key(s), c)
            )(m_seeds, m_counts)
            logits0 = apply_token_mask(
                logits[:, 0], g_need[m_states] <= m_rem[:, None]
            )
            toks0 = sample_runtime(logits0, m_temps, m_topps, m_topks, keys)
            firsts = toks0
            # Decode rows commit chunk token 0 (prefill rows arm on the
            # host AFTER this launch, so `active` excludes them here).
            d_nxt = jnp.where(active, toks0, pad_id)
            cstates = jnp.where(active, g_next[cstates, d_nxt], cstates)
            crem = jnp.where(active, crem - 1, crem)
            pos = jnp.where(active, pos + 1, pos)
            cur = d_nxt

            def step(carry, i):
                # _build_decode's step body, verbatim (T=1 per row).
                cache, cur, pos, cstates, crem = carry
                logits, new_cache = forward(
                    cfg, params, cur[:, None], pos[:, None],
                    _paged_cache_dict(cache, ptab), attn_impl=dimpl,
                    mesh=mesh, kv_lens=jnp.where(active, pos + 1, 0),
                )
                step_logits = apply_token_mask(
                    logits[:, 0], g_need[cstates] <= crem[:, None]
                )
                keys = jax.vmap(
                    lambda s, c: jax.random.fold_in(jax.random.key(s), c)
                )(seeds, counts + i)
                nxt = sample_runtime(step_logits, temps, topps, topks, keys)
                nxt = jnp.where(active, nxt, pad_id)
                cstates = jnp.where(active, g_next[cstates, nxt], cstates)
                crem = jnp.where(active, crem - 1, crem)
                pos = jnp.where(active, pos + 1, pos)
                return (_paged_cache_tuple(new_cache), nxt, pos, cstates,
                        crem), nxt

            # chunk == 1 leaves an empty scan: toks is just step 0's
            # column. Fold indices continue at counts+1 where step 0
            # (fold counts) left off — the control's i=1..chunk-1 steps.
            (cache, cur, pos, cstates, crem), toks_rest = lax.scan(
                step, (cache, cur, pos, cstates, crem),
                jnp.arange(1, chunk),
            )
            toks = jnp.concatenate([d_nxt[None], toks_rest], 0).T
            counts = jnp.where(active, counts + chunk, counts)
            return (*cache, cur, pos, counts, cstates, crem, toks, firsts)

        return mixed

    def _build_mixed_spec(self, t_bucket: int):
        """Speculative twin of _build_mixed: decode rows run their verify
        window (T = D+1) and prefill rows their chunk (T = t_bucket) in
        the SAME ragged launch — the window is padded to
        max(t_bucket, D+1) columns and q_lens tells the kernel which
        prefix of each row is real. The verify math (draft, per-position
        grammar masking, greedy/rejection acceptance, history commit) is
        _build_spec_decode's, applied to the window's first D+1 columns;
        prefill rows additionally scatter their chunk into the draft
        history (== _build_prefill's hist write) and sample their first
        token from the chunk's last real logit at fold 0."""
        from ..constrain.masks import fsm_advance_chain
        from ..engine.speculative import (
            emit_chain,
            ngram_draft,
            rejection_sample_chain,
        )

        cfg, mesh, impl = self.cfg, self.mesh, self._impl
        D, ngram = self._spec_draft, self._spec_ngram
        d1 = D + 1
        t = t_bucket
        T = max(t, d1)
        pad_id = cfg.pad_id
        nc = len(self._cache)
        ps, np_tab = self._page_size, self._pages_per_slot
        s_virt = np_tab * ps

        @partial(jax.jit,
                 donate_argnums=tuple(range(1, nc + 5))
                 + (nc + 10, nc + 11, nc + 12))
        def mixed_spec(params, *args):
            cache = args[:nc]
            (hist, hlen, cur, pos, active, temps, topps, topks, seeds,
             counts, cstates, crem) = args[nc:nc + 12]
            (p_tokens, p_lengths, p_starts, is_pref, p_temps, p_topps,
             p_topks, p_seeds, p_cinits, p_cbudgets) = args[nc + 12:nc + 22]
            g_next, g_need = args[nc + 22:nc + 24]
            ptab = args[nc + 24]
            params = split_blocks(params)
            drafts = ngram_draft(hist, hlen, D, ngram)           # [S, D]
            verify = jnp.concatenate([cur[:, None], drafts], 1)  # [S, D+1]
            jd = jnp.arange(d1, dtype=jnp.int32)[None, :]
            vpos = pos[:, None] + jd
            col = jnp.arange(T, dtype=jnp.int32)[None, :]
            if T > d1:
                verify = jnp.pad(verify, ((0, 0), (0, T - d1)),
                                 constant_values=pad_id)
                vpos = jnp.pad(vpos, ((0, 0), (0, T - d1)),
                               constant_values=s_virt)
            pt = p_tokens
            if T > t:
                pt = jnp.pad(pt, ((0, 0), (0, T - t)),
                             constant_values=pad_id)
            p_pos = jnp.where(col < t, p_starts[:, None] + col,
                              jnp.int32(s_virt))
            tokens0 = jnp.where(is_pref[:, None], pt, verify)
            pos0 = jnp.where(is_pref[:, None], p_pos, vpos)
            q_lens_v = jnp.where(is_pref, t, d1).astype(jnp.int32)
            kv0 = jnp.where(
                is_pref, jnp.clip(p_starts + t, 0, s_virt),
                jnp.where(active, pos + d1, 0),
            ).astype(jnp.int32)
            logits, new_cache = forward(
                cfg, params, tokens0, pos0,
                _paged_cache_dict(cache, ptab),
                attn_impl=impl, mesh=mesh, kv_lens=kv0, q_lens=q_lens_v,
            )
            # ----- verify math: _build_spec_decode, on the first D+1
            # columns (mid-prefill slots sit at temps=0/state park, same
            # values the alternating control's spec round sees).
            vlogits = logits[:, :d1]
            pstates, vlen = fsm_advance_chain(
                g_next, g_need, cstates, drafts, crem
            )                                                    # [S,D+1],[S]
            vlogits = apply_token_mask(
                vlogits, g_need[pstates] <= (crem[:, None] - jd)[:, :, None]
            )
            preds = jnp.argmax(vlogits, axis=-1).astype(jnp.int32)
            eq = ((drafts == preds[:, :D])
                  & (jd[:, :D] < vlen[:, None])).astype(jnp.int32)
            acc = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)         # [S]
            greedy = temps <= 0.0
            keys = jax.vmap(
                lambda s, c: jax.random.fold_in(jax.random.key(s), c)
            )(seeds, counts)
            ns = preds.shape[0]

            def rejection_path(_):
                filt = filtered_runtime_logits(
                    vlogits, temps[:, None], topps[:, None], topks[:, None],
                )
                return rejection_sample_chain(filt, drafts, keys)

            acc_s, extra = lax.cond(
                jnp.all(greedy),
                lambda _: (jnp.zeros((ns,), jnp.int32),
                           jnp.zeros((ns,), jnp.int32)),
                rejection_path, None,
            )
            emitted_s = emit_chain(drafts, acc_s, extra, pad_id)
            n_emit = jnp.where(
                active, jnp.where(greedy, acc + 1, acc_s + 1), 0
            )
            emitted = jnp.where(greedy[:, None], preds, emitted_s)
            emitted = jnp.where(jd < n_emit[:, None], emitted, pad_id)
            write_at = jnp.where(
                active, hlen, jnp.int32(hist.shape[1])
            )
            hist = jax.vmap(
                lambda h, e, s: lax.dynamic_update_slice(h, e, (s,))
            )(hist, emitted, write_at)
            cur = jax.vmap(
                lambda e, n, c: jnp.where(n > 0, e[jnp.maximum(n - 1, 0)], c)
            )(emitted, n_emit, cur)
            idx = jnp.maximum(n_emit - 1, 0)
            last_s = jnp.take_along_axis(pstates, idx[:, None], 1)[:, 0]
            last_t = jnp.take_along_axis(emitted, idx[:, None], 1)[:, 0]
            cstates = jnp.where(n_emit > 0, g_next[last_s, last_t], cstates)
            crem = crem - n_emit
            pos = pos + n_emit
            hlen = hlen + n_emit
            counts = counts + jnp.where(active & ~greedy, 1, 0)
            # ----- prefill rows: chunk into the draft history (row S is
            # the OOB drop for everyone else — disjoint from the emitted
            # write above, whose prefill rows landed in the spare tail)
            # and the first token from the chunk's last real logit.
            rows = jnp.where(
                is_pref, jnp.arange(is_pref.shape[0], dtype=jnp.int32),
                jnp.int32(is_pref.shape[0]),
            )
            hist = hist.at[
                rows[:, None],
                p_starts[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :],
            ].set(p_tokens)
            fl = jnp.take_along_axis(
                logits, jnp.clip(p_lengths - 1, 0, T - 1)[:, None, None],
                axis=1,
            )[:, 0]
            fl = apply_token_mask(fl, g_need[p_cinits] <= p_cbudgets[:, None])
            p_keys = jax.vmap(
                lambda s: jax.random.fold_in(jax.random.key(s), 0)
            )(p_seeds)
            firsts = sample_runtime(fl, p_temps, p_topps, p_topks, p_keys)
            out_cache = _paged_cache_tuple(new_cache)
            return (*out_cache, hist, hlen, cur, pos, counts,
                    cstates, crem, emitted, n_emit, firsts)

        return mixed_spec

    # ------------------------------------------------------------- lifecycle

    def warmup(self, prompt_len: Optional[int] = None) -> None:
        """Pre-compile (and execute once) every (bucket, k-bucket) prefill
        variant for a `prompt_len`-sized prompt — deterministically, unlike
        warming through generate() (concurrent admission groups race, so
        some k-buckets can stay uncompiled and stall a later request with
        an XLA compile). Every row targets the out-of-bounds padding slot:
        the scatter drops all writes, so no slot or cache state changes.
        Also compiles the DECODE program (one all-inactive round: every
        write lands at the park position, which no query can see) and the
        per-slot state scatters (driven at the out-of-bounds slot: jax
        drops OOB scatter writes, so they are true no-ops). Call before
        start() (or while the loop is idle).

        Liveness note: an unwarmed loop blocks its own thread on each
        cold XLA compile, which a tight watchdog stall threshold
        (serve/watchdog.py) cannot tell from a genuine wedge — warm
        before serving, or keep LSOT_STALL_MIN_S above the compile wall.
        The supervisor's restart driver warms every rebuilt scheduler
        through this method while the monitor is quiet."""
        want = prompt_len or self.prompt_bucket
        t = next((b for b in self._buckets if b >= want), self.prompt_bucket)
        pad = self.cfg.pad_id
        for kb in self._kbuckets:
            if (t, kb) not in self._prefill_fns:
                self._prefill_fns[(t, kb)] = self._build_prefill(t, kb)
            args = [
                jnp.full((kb, t), pad, jnp.int32),
                jnp.ones(kb, jnp.int32),
                jnp.full((kb,), self.num_slots, jnp.int32),  # all OOB
                jnp.zeros(kb, jnp.int32),
                jnp.zeros(kb, jnp.float32),
                jnp.ones(kb, jnp.float32),
                jnp.zeros(kb, jnp.int32),
                jnp.zeros(kb, jnp.uint32),
                jnp.zeros(kb, jnp.int32),   # cinits: sentinel state
                jnp.ones(kb, jnp.int32),    # cbudgets: need<=1 all-True
                self._ctables["need"],
            ]
            if self._spec_draft:
                args.append(self._hist)
            if self._paged:
                args.append(self._ptab)
            out = self._prefill_fns[(t, kb)](self.params, *self._cache, *args)
            nc = len(self._cache)
            self._cache = out[:nc]
            if self._spec_draft:
                self._hist = out[nc]
        self._warm_state_ops()
        self._warm_decode()

    def _warm_state_ops(self) -> None:
        """Compile the per-slot state scatters at the OOB padding slot
        (index num_slots): jax drops out-of-bounds scatter writes, so
        these executions change nothing while caching the compiled
        programs the first admission would otherwise block the loop on."""
        oob = jnp.int32(self.num_slots)
        self._cur, self._pos, self._cstates, self._crem = self._park_fn(
            self._cur, self._pos, self._cstates, self._crem, oob
        )
        self._temps, self._topps, self._topks, self._cstates = \
            self._retire_fn(self._temps, self._topps, self._topks,
                            self._cstates, oob)
        (self._cur, self._pos, self._temps, self._topps, self._topks,
         self._seeds, self._counts, self._cstates,
         self._crem) = self._ready_fn(
            self._cur, self._pos, self._temps, self._topps, self._topks,
            self._seeds, self._counts, self._cstates, self._crem,
            self._ctables["next"], oob,
            jnp.full((1,), self.cfg.pad_id, jnp.int32), jnp.int32(self._park),
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
            jnp.uint32(0), jnp.int32(0), jnp.int32(1),
        )
        (self._cur, self._pos, self._temps, self._topps, self._topks,
         self._seeds, self._counts, self._cstates,
         self._crem) = self._resume_fn(
            self._cur, self._pos, self._temps, self._topps, self._topks,
            self._seeds, self._counts, self._cstates, self._crem,
            oob, jnp.int32(self.cfg.pad_id), jnp.int32(self._park),
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
            jnp.uint32(0), jnp.int32(1), jnp.int32(0), jnp.int32(1),
        )
        if self._spec_draft:
            self._hist, self._hlen = self._spec_ready_fn(
                self._hist, self._hlen, oob,
                jnp.full((1,), self.cfg.pad_id, jnp.int32), jnp.int32(0),
            )
            self._hist, self._hlen = self._spec_resume_fn(
                self._hist, self._hlen, oob,
                jnp.full((self._hist.shape[1],), self.cfg.pad_id,
                         jnp.int32),
                jnp.int32(0),
            )
        if self._paged:
            # Table-row scatter at the OOB slot (dropped) and a page-0
            # self-copy (content no-op): compiles the paged bookkeeping
            # ops so the first admission doesn't block the loop on them.
            self._ptab = self._ptab_row_fn(
                self._ptab, oob,
                jnp.full((self._pages_per_slot,),
                         self._page_alloc.num_pages, jnp.int32),
            )
            self._cache = self._copy_page_fn(
                *self._cache, jnp.int32(0), jnp.int32(0)
            )

    def _warm_decode(self) -> None:
        """Compile (and execute once) the decode program with every slot
        inactive: parked-position garbage writes only — the same rounds
        free slots run between requests anyway, covered by the cache
        visibility invariant."""
        nc = len(self._cache)
        t = self._ctables
        inactive = np.zeros(self.num_slots, bool)
        extra = (self._ptab,) if self._paged else ()
        if self._spec_draft:
            out = self._decode_fn(
                self.params, *self._cache, self._hist, self._hlen,
                self._cur, self._pos, jnp.asarray(inactive), self._temps,
                self._topps, self._topks, self._seeds, self._counts,
                self._cstates, self._crem, t["next"], t["need"], *extra,
            )
            self._cache = out[:nc]
            (self._hist, self._hlen, self._cur, self._pos, self._counts,
             self._cstates, self._crem, _, _) = out[nc:]
        else:
            out = self._decode_fn(
                self.params, *self._cache, self._cur, self._pos,
                jnp.asarray(inactive), self._temps, self._topps, self._topks,
                self._seeds, self._counts, self._cstates, self._crem,
                t["next"], t["need"], *extra,
            )
            self._cache = out[:nc]
            (self._cur, self._pos, self._counts, self._cstates, self._crem,
             _) = out[nc:]

    def _crash_error(self) -> SchedulerCrashed:
        """The typed "engine dead" error for this scheduler's crash (HTTP
        503 upstream, vs a per-request 500): carries the loop's original
        traceback so every rejected submit points at the real device
        failure, not just its own stack."""
        if isinstance(self._crash, SchedulerCrashed):
            return self._crash
        return SchedulerCrashed.from_exception(self._crash)

    def start(self) -> "ContinuousBatchingScheduler":
        if self._thread is None:
            if self._crash is not None:
                raise self._crash_error()
            if self._paged:
                # Re-sync every device table row from the host mirror: a
                # previous _close released abandoned slots' pages host-side
                # only, and a stale row would route the freed slots' parked
                # writes into pages a future occupant owns. No-op cost on
                # first start (rows are already the unmapped sentinel).
                for i in range(self.num_slots):
                    self._sync_ptab_row(i)
            self._stop_evt.clear()
            with self._submit_lock:
                self._closed = False
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def shutdown(self, timeout: Optional[float] = None) -> None:
        """Stop the event loop. `timeout` bounds the join: a WEDGED loop
        (hung XLA dispatch — the case the watchdog escalates) would block
        an unbounded join forever, so the supervisor's teardown passes a
        bound and ABANDONS the daemon thread if it doesn't exit in time.
        An abandoned zombie exits at its next top-of-loop check once it
        unwedges; its futures are superseded by the supervisor's replay
        (bare-scheduler callers should keep the default blocking join —
        abandonment leaves inner futures unresolved)."""
        if self._thread is not None:
            self._stop_evt.set()
            self._queue.put(None)  # wake the loop
            self._thread.join(timeout)
            if self._thread.is_alive():
                with self._submit_lock:
                    self._closed = True
                _log.warning(
                    "scheduler loop did not join within %.2fs; abandoning "
                    "wedged worker thread (it exits when it unwedges)",
                    timeout,
                )
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()

    # ---------------------------------------------------------------- client

    def submit(
        self,
        ids: Sequence[int],
        max_new_tokens: int = 256,
        sampling: SamplingParams = SamplingParams(),
        # Honored: the request samples from its own fold_in(key(seed), i)
        # stream, so (ids, sampling, seed, max_new) reproduces the same
        # tokens regardless of concurrent traffic. (Note the stream indexing
        # differs from InferenceEngine's shared-batch keys, so scheduler and
        # engine agree token-for-token on greedy but not on sampled runs.)
        seed: int = 0,
        # Streaming consumer: called with each accepted token id in order
        # from the worker thread (see _Request.on_token).
        on_token: Optional[Callable[[int], None]] = None,
        # Grammar constraining (constrain.CompiledMask): the request's
        # tokens are masked to the compiled language; the slot's FSM state
        # rides the decode program on device. Requests with and without a
        # constraint share the batch; a request with a DIFFERENT grammar
        # than the installed one waits for constrained slots to drain, then
        # swaps the tables (one retrace per grammar, never per request).
        constraint: Optional[CompiledMask] = None,
        # Per-request latency budget in seconds (serve/resilience.Deadline):
        # the request fails with a typed DeadlineExceeded — fast at
        # admission if it expired while queued, or at the next harvest once
        # in flight. None = no deadline.
        deadline_s: Optional[float] = None,
        # Request-scoped tracing (utils/tracing.RequestTrace): when the
        # request was head-sampled, the worker thread records queue-wait /
        # prefill / per-round decode spans into this tree. None (the
        # unsampled fast path) costs nothing anywhere in the loop.
        trace=None,
        # Multi-model serving (ISSUE 16): the model the request wants.
        # "" accepts (single-model callers never name one); a non-empty
        # id must match THIS replica's checkpoint — a mismatch is the
        # caller's routing bug and fails typed instead of decoding the
        # prompt against the wrong weights.
        model_id: str = "",
        # Multi-tenant QoS (ISSUE 18): the tenant the request bills to
        # and its service class (interactive|batch|replay). "" = the
        # unlabeled single-tenant shape; with LSOT_QOS=0 both are
        # carried but never consulted.
        tenant: str = "",
        qos: str = "",
    ) -> "Future[List[int]]":
        if not ids:
            raise ValueError("empty prompt")
        if model_id and model_id != self.model_id:
            from .modelpool import UnknownModel

            raise UnknownModel(
                f"request names model {model_id!r} but this replica "
                f"serves {self.model_id or '<unset>'!r}"
            )
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        if constraint is not None:
            if max_new_tokens < constraint.min_new_tokens:
                raise ValueError(
                    f"max_new_tokens={max_new_tokens} cannot hold a "
                    f"complete constrained parse (grammar needs >= "
                    f"{constraint.min_new_tokens} tokens incl. the stop id)"
                )
        # Overshoot bound: the device can run (harvest_lag + 1) rounds past
        # a budget or stop token before the host notices (rounds are
        # harvested one lag late); those tokens are discarded but their
        # cache writes must stay inside the window.
        overshoot = self.overshoot
        need = bucket_len(len(ids), self.prompt_bucket) + max_new_tokens + overshoot
        if need > self.max_seq - 1:  # the last cache slot is the parking spot
            raise ValueError(
                f"prompt ({len(ids)} tokens, bucketed) + max_new_tokens "
                f"({max_new_tokens}) + overshoot ({overshoot}) "
                f"= {need} exceeds scheduler max_seq={self.max_seq}"
            )
        req = _Request(
            ids=list(ids), max_new=max_new_tokens,
            temperature=sampling.temperature, top_p=sampling.top_p,
            top_k=sampling.top_k, seed=seed,
            future=Future(), on_token=on_token, constraint=constraint,
            deadline=(Deadline.after(deadline_s)
                      if deadline_s is not None else None),
            trace=trace,
            model_id=model_id or self.model_id,
            tenant=str(tenant or ""), qos=str(qos or ""),
        )
        req.future._lsot_request = req  # cancel() handle
        try:
            # Chaos seam: mark THIS request's slot as a silently
            # no-progress lane (its harvest rows read empty) — the
            # per-slot stall retirement's injectable trigger. Checked on
            # the SUBMITTING thread so a test can scope the spec to
            # exactly the requests it wants wedged, deterministically.
            FAULTS.check("sched:slot_stall")
        except InjectedFault:
            req.stall_inject = True
        with self._submit_lock:
            if self._closed:
                if self._crash is not None:
                    raise self._crash_error()
                raise RuntimeError("scheduler has shut down")
            if self._thread is None:
                raise RuntimeError(
                    "scheduler not started — call start() or use it as a "
                    "context manager (a queued Future would never resolve)"
                )
            # Admission control: shed instead of queueing without bound.
            # qsize() counts requests not yet pulled into slots/prefill —
            # the true backlog a new request would wait behind.
            if self.max_queue_depth and \
                    self._queue.qsize() + len(self._ready) \
                    >= self.max_queue_depth:
                resilience.inc("shed")
                raise Overloaded(
                    f"scheduler queue at capacity "
                    f"({self.max_queue_depth} waiting requests)",
                    # Backpressure hint: current queue depth × the recent
                    # per-request service time (retry_after_hint), with a
                    # 1s floor until the first completion seeds the EWMA.
                    retry_after_s=self.retry_after_hint(),
                )
            self._rid_seq += 1
            req.rid = self._rid_seq
            req.future._lsot_replica = self.flight.replica
            req.submitted_at = time.perf_counter()
            if self._qos:
                self._stamp_qos_locked(req)
            self._pending_new_tokens += req.max_new
            self._pending_prompt_tokens += len(req.ids)
            self._queue.put(req)
        return req.future

    def generate(
        self,
        prompts: List[List[int]],
        max_new_tokens: int = 256,
        sampling: SamplingParams = SamplingParams(),
        seed: int = 0,
    ) -> List[List[int]]:
        """Synchronous batch helper (engine-compatible signature)."""
        futs = [
            self.submit(p, max_new_tokens=max_new_tokens, sampling=sampling, seed=seed)
            for p in prompts
        ]
        return [f.result() for f in futs]

    @staticmethod
    def cancel(future: "Future[List[int]]") -> None:
        """Cooperatively cancel a submitted request: the worker retires it
        (resolving the future with whatever was generated) at its next
        harvest instead of decoding the remaining budget for an abandoned
        consumer. Safe on finished/foreign futures (no-op). A REMOTE
        request's `_Request` lives in another process — its future
        carries an `_lsot_cancel` callable instead (serve/remote.py),
        which ships the cancel over the wire."""
        req = getattr(future, "_lsot_request", None)
        if req is not None:
            req.cancelled = True
            return
        cb = getattr(future, "_lsot_cancel", None)
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — cancel of the unreachable is moot
                pass

    # ------------------------------------------------------ multi-tenant WFQ

    def _wfq_weight(self, tenant: str) -> float:
        """WFQ weight for a tenant (LSOT_TENANT_WEIGHTS; 1.0 default —
        including the unlabeled "" tenant, which competes as one tenant)."""
        w = self._tenant_weights.get(tenant, 1.0)
        return w if w > 0 else 1.0

    def _stamp_qos_locked(self, req: _Request) -> None:
        """Stamp the WFQ virtual finish time and the tenant's prefix
        namespace salt (callers hold _submit_lock; QoS on only).

        Start-time fair queueing: a request starts at max(global virtual
        time, its tenant's last finish) and finishes cost/weight later,
        cost = prompt + budget tokens. A tenant submitting a storm only
        advances its OWN clock — its k-th queued request finishes k
        virtual-costs out, while a light tenant's next request starts at
        the global clock and is served ahead of the whole backlog."""
        from .qos import bounded_bump, tenant_salt
        cost = (len(req.ids) + req.max_new) / self._wfq_weight(req.tenant)
        req.vft = max(self._wfq_vt, self._wfq_last.get(req.tenant, 0.0)) + cost
        self._wfq_last[req.tenant] = req.vft
        if len(self._wfq_last) > 128:
            # Idle-tenant ledger hygiene: a finish time at/behind the
            # global clock no longer orders anything.
            self._wfq_last = {t: v for t, v in self._wfq_last.items()
                              if v > self._wfq_vt}
        if self._prefix_tenant_ns and req.tenant:
            req.ns = tenant_salt(req.tenant)
        bounded_bump(self._tenant_submitted, req.tenant)

    def _drain_ready(self) -> None:
        """Move every queued submit into the WFQ ready pool (worker
        thread; QoS on only). queue.Queue hands each item to exactly one
        consumer, so this never duplicates against extract_queued."""
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is None:
                continue
            with self._submit_lock:
                self._ready.append(req)

    def _ready_pop(self) -> Optional[_Request]:
        """Serve the smallest virtual finish time (rid tie-break keeps
        same-tenant FIFO and determinism) and advance the global virtual
        clock to it."""
        with self._submit_lock:
            if not self._ready:
                return None
            i = min(range(len(self._ready)),
                    key=lambda j: (self._ready[j].vft, self._ready[j].rid))
            req = self._ready.pop(i)
            self._wfq_vt = max(self._wfq_vt, req.vft)
            return req

    def _page_wait_pop(self) -> _Request:
        """Next page-starved waiter to re-try admission. QoS off: FIFO
        popleft — the pre-QoS order bit-for-bit. QoS on: preempted
        victims still resume ahead of never-admitted waiters (they were
        admitted first and hold delivered tokens), then smallest virtual
        finish time — a storm tenant's parked backlog cannot
        head-of-line-block a light tenant's waiter."""
        pw = self._page_wait
        if not self._qos or len(pw) == 1:
            return pw.popleft()
        best = min(range(len(pw)),
                   key=lambda i: (0 if pw[i].preempted else 1,
                                  pw[i].vft, i))
        if best == 0:
            req = pw.popleft()
        else:
            pw.rotate(-best)
            req = pw.popleft()
            pw.rotate(best)
        self._wfq_vt = max(self._wfq_vt, req.vft)
        return req

    def qos_stats(self) -> Optional[Dict[str, object]]:
        """Per-tenant WFQ/admission counters for /metrics (the
        lsot_tenant_* families): None when QoS is off — the pre-QoS
        payload byte-for-byte."""
        if not self._qos:
            return None
        with self._submit_lock:
            backlog: Dict[str, int] = {}
            for r in self._ready:
                key = r.tenant or "default"
                backlog[key] = backlog.get(key, 0) + 1
            out: Dict[str, object] = {
                "virtual_time": round(self._wfq_vt, 3),
                "ready": len(self._ready),
                # Contiguous layouts have no page-wait deque at all.
                "page_wait": len(getattr(self, "_page_wait", ())),
                "submitted": dict(self._tenant_submitted),
                "preempted": dict(self._tenant_preempted),
            }
            if self._tenant_weights:
                out["weights"] = dict(self._tenant_weights)
            if backlog:
                out["backlog"] = backlog
            return out

    @property
    def overshoot(self) -> int:
        """Max tokens/positions the device can run past a budget or stop
        before the host notices: pending rounds × max tokens per round,
        plus (speculatively) one verify window of cache-write lookahead
        beyond the last emitted position."""
        if self._spec_draft:
            d1 = self._spec_draft + 1
            return (self._harvest_lag + 1) * d1 + self._spec_draft
        return (self._harvest_lag + 1) * self.decode_chunk

    @property
    def speculation_stats(self) -> Optional[Dict[str, float]]:
        """Speculative-decoding acceptance (None when speculation is off):
        verify rounds and tokens emitted across every emitting slot,
        tokens/round (1.0 = no draft ever accepted .. D+1 = every draft
        accepted), and the estimated speedup vs vanilla decode given the
        measured ~1.6x verify-round cost (engine/speculative.py breakeven
        math) — the go/no-go number for --speculative on a given
        workload. `by_class` splits the same acceptance figures by
        constrained vs unconstrained requests: grammar-masked NL→SQL
        traffic accepts differently (forced keyword/identifier runs vs
        free text). `by_sampling` splits them by greedy vs sampled
        (temperature>0) requests: rejection-sampling acceptance (u <
        target mass) runs systematically below greedy's argmax match, so
        the sampled class prices its own speedup instead of hiding in a
        blend (/metrics carries both splits)."""
        if not self._spec_draft:
            return None
        from ..engine.speculative import (
            VERIFY_COST_CALIBRATION,
            verify_cost_ratio,
        )

        # Copy the counters under the scheduler's lock: the harvest thread
        # updates them as a group under it, so this read can never see a
        # half-applied round (ADVICE.md r5 #2).
        with self._submit_lock:
            rounds, toks = self._spec_rounds, self._spec_tokens
            rounds_con, toks_con = (self._spec_rounds_con,
                                    self._spec_tokens_con)
            rounds_samp, toks_samp = (self._spec_rounds_samp,
                                      self._spec_tokens_samp)
        # The verify cost scales with THIS scheduler's draft length
        # (ADVICE r5 #3: a D=4 deployment's breakeven is not D=8's) — the
        # per-D linear model replaces the old single 1.6 constant — and
        # with its MODEL SHAPE (ROADMAP carried-over: the 1B-anchored
        # slope mispriced 7B/int4 configs; unembed-marginal over
        # weight-stream-fixed rescales it). Weight bits were probed once
        # at construction.
        ratio = verify_cost_ratio(
            self._spec_draft, cfg=self.cfg, weight_bits=self._weight_bits,
        )

        def acceptance(r: int, t: int) -> Dict[str, float]:
            tpr = t / r if r else 0.0
            return {
                "verify_rounds": r,
                "tokens_emitted": t,
                "tokens_per_round": round(tpr, 3),
                "est_speedup_vs_vanilla": round(tpr / ratio, 3) if r else 0.0,
            }

        return {
            **acceptance(rounds, toks),
            # The estimate's denominator, at this config's draft length,
            # plus where the model's anchors were measured — a 7B/int4/TP
            # serving config can still sit meaningfully off it.
            "verify_cost_ratio": round(ratio, 3),
            "est_speedup_calibration": VERIFY_COST_CALIBRATION,
            "by_class": {
                "constrained": acceptance(rounds_con, toks_con),
                "unconstrained": acceptance(rounds - rounds_con,
                                            toks - toks_con),
            },
            "by_sampling": {
                "greedy": acceptance(rounds - rounds_samp,
                                     toks - toks_samp),
                "sampled": acceptance(rounds_samp, toks_samp),
            },
        }

    def retry_after_hint(self) -> float:
        """Queue-depth-aware Retry-After (ROADMAP follow-up): a shed client
        should wait roughly until the current backlog has drained through
        the slot pool — queue depth × recent per-request service time /
        concurrent lanes — not a static constant. Clamped to [1, 60]s:
        the floor keeps retry storms decorrelated when the estimate is
        tiny (or not yet seeded), the ceiling keeps one pathological slow
        request from telling everyone to come back in an hour. Shared by
        the 429 shed path and the drain-mode 503.

        Lock-free read ON PURPOSE: submit() calls this while HOLDING
        _submit_lock (the Overloaded raise), so taking the lock here
        would self-deadlock; a float attribute read is atomic under the
        GIL and a one-update-stale estimate is still an estimate."""
        ewma = self._svc_ewma
        if ewma is None:
            return 1.0
        # The retry waits behind itself too; under QoS the WFQ ready pool
        # is backlog the queue alone no longer counts.
        depth = self._queue.qsize() + len(self._ready) + 1
        return float(min(60.0, max(1.0, depth * ewma / max(1, self.num_slots))))

    def backlog_score(self) -> Tuple[float, int]:
        """Placement score for the pool's least-loaded router:
        `(estimated backlog seconds, pending new tokens)`, compared
        lexicographically. The seconds estimate is the Retry-After
        hint's service-time-EWMA math refined to TOKEN resolution —
        outstanding token mass × measured sec/token / slots — unclamped
        (a router comparing replicas needs the raw estimate, not the
        [1, 60] s client courtesy). Token-weighted on purpose: under a
        submit burst, request COUNTS tie constantly and a per-request
        EWMA degenerates into count-balancing, which on skewed prompt
        lengths reproduces round-robin's pathology (all the long
        requests stack one replica); token mass is the load that
        actually differs, and pricing it in seconds keeps the score
        comparable against a request's deadline. Until the first
        completion seeds the EWMA the estimate is 0.0 and the raw token
        tie-break carries the routing. Lock-free read like
        retry_after_hint (atomic attribute reads; a hair-stale estimate
        is still an estimate)."""
        if self.phase_role == "prefill":
            # A prefill replica's backlog is COMPUTE backlog: outstanding
            # prompt tokens priced by the measured submit→handoff wall
            # per prompt token — the decode budgets it will never spend
            # say nothing about how long a new prompt waits here.
            toks = int(self._pending_prompt_tokens)
            stok = self._pref_stok_ewma
            secs = (toks * stok / max(1, self.num_slots)
                    if stok is not None else 0.0)
            return float(secs), toks
        stok = self._stok_ewma
        toks = int(self._pending_new_tokens)
        secs = (toks * stok / max(1, self.num_slots)
                if stok is not None else 0.0)
        return float(secs), toks

    def extract_queued(self) -> List[_Request]:
        """Pull every queued-not-yet-admitted request OUT of this
        scheduler (the pool's drain-one-replica re-placement seam).
        Safe against the live worker: `queue.Queue` hands each item to
        exactly one consumer, so a request is either extracted here or
        admitted there, never both — requests the worker already pulled
        finish on this replica during the drain grace. Wake sentinels
        (None) are dropped; the loop's 50 ms poll re-arms them."""
        out: List[_Request] = []
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                out.append(req)
        with self._submit_lock:
            # Under QoS the worker stages queued submits in the WFQ ready
            # pool — those are still queued-not-yet-admitted and must
            # leave with the drain (the lock serializes against the
            # worker's own _drain_ready/_ready_pop).
            if self._ready:
                out.extend(self._ready)
                self._ready.clear()
            if out:
                self._pending_new_tokens = max(
                    0, self._pending_new_tokens
                    - sum(r.max_new for r in out)
                )
                self._pending_prompt_tokens = max(
                    0, self._pending_prompt_tokens
                    - sum(len(r.ids) for r in out)
                )
        return out

    def extract_handoffs(self) -> List[_Request]:
        """Drain the packed-handoff queue (the pool's placement pump and
        the drain-replica re-placement both come through here). Each
        request carries its portable KV blob (`spilled` + `handoff`
        metadata), so any paged sibling can `requeue()` it and resume
        decode without re-prefilling. Backlog accounting already left
        this replica at pack time — no decrement here."""
        out: List[_Request] = []
        while True:
            try:
                out.append(self._handoff.popleft())
            except IndexError:
                break
        return out

    def requeue(self, req: _Request) -> None:
        """Accept a request extracted from a sibling replica (pool
        re-placement on drain/remove): fresh rid + replica restamp, then
        straight into the queue. BYPASSES max_queue_depth on purpose —
        the request was already admitted (acknowledged) once; shedding
        acknowledged work because it had to move replicas would turn a
        drain into data loss."""
        if req.spilled is not None:
            # A migrated/spilled blob can only restore into a COMPATIBLE
            # pool: paged, same page size (blob pages are [L, n, K, ps
            # (, H)] slices of the source pool). The pool's handoff
            # placement treats this ValueError as "target can't take it"
            # and tries the next sibling.
            if not self._paged:
                raise ValueError(
                    "cannot requeue a KV-page blob onto a contiguous "
                    "scheduler"
                )
            if req.spilled[0].shape[3] != self._page_size:
                raise ValueError(
                    f"handoff blob page size {req.spilled[0].shape[3]} "
                    f"!= this pool's {self._page_size}"
                )
        with self._submit_lock:
            if self._closed:
                if self._crash is not None:
                    raise self._crash_error()
                raise RuntimeError("scheduler has shut down")
            if self._thread is None:
                raise RuntimeError("scheduler not started")
            self._rid_seq += 1
            req.rid = self._rid_seq
            req.future._lsot_replica = self.flight.replica
            if self._qos:
                # Re-placed requests re-enter THIS replica's virtual
                # clock (vft from another replica's clock is meaningless
                # here) and re-derive the prefix namespace locally.
                self._stamp_qos_locked(req)
            self._pending_new_tokens += req.max_new
            self._pending_prompt_tokens += len(req.ids)
            self._queue.put(req)

    def _record_service_time(self, req: _Request) -> None:
        """EWMA of submit→retire wall for COMPLETED requests (failures and
        cancels say nothing about healthy service time — a disconnect-heavy
        streaming workload retiring fractional decodes would otherwise
        drag the estimate down and tell shed clients to retry too soon).
        Under the submit lock: retry_after_hint reads it from HTTP
        threads."""
        if req.submitted_at <= 0.0 or req.cancelled:
            return
        wall = time.perf_counter() - req.submitted_at
        stok = wall / max(1, len(req.generated))
        with self._submit_lock:
            prev = self._svc_ewma
            self._svc_ewma = wall if prev is None else 0.2 * wall + 0.8 * prev
            prev_t = self._stok_ewma
            self._stok_ewma = (stok if prev_t is None
                               else 0.2 * stok + 0.8 * prev_t)
            if self._paged:
                # Observed generation length: what overcommit admission
                # reserves instead of the worst-case budget. Completed
                # requests only (a cancelled fraction says nothing about
                # how long requests RUN).
                g = float(len(req.generated))
                prev_g = self._gen_ewma
                self._gen_ewma = (g if prev_g is None
                                  else 0.2 * g + 0.8 * prev_g)

    # ------------------------------------- prefix-cache telemetry (ISSUE 14)

    def _digest_for(self, key: Tuple[int, ...]) -> str:
        """Memoized content digest of a chain key (worker thread only;
        see _prefix_digest_memo for why)."""
        memo = self._prefix_digest_memo
        d = memo.get(key)
        if d is None:
            d = prefix_digest(key)
            memo[key] = d
            bound = 4 * max(self._prefix_topk,
                            self._prefix_cache_blocks or 1)
            while len(memo) > bound:
                memo.popitem(last=False)
        else:
            memo.move_to_end(key)
        return d

    def _prefix_note_publish(self, key: Tuple[int, ...]) -> None:
        """Register a freshly published cache entry: content digest +
        live metadata, and the eviction-churn check — a key coming back
        through publish while still on the evicted ghost means the cache
        was too small for the working set (reinsertion, the signal the
        capacity knob acts on). Worker thread only; the lock is for
        registry/metrics readers."""
        digest = self._digest_for(key)
        with self._submit_lock:
            if key in self._prefix_evicted_ghost:
                del self._prefix_evicted_ghost[key]
                self._prefix_reinserts += 1
            self._prefix_meta[key] = {
                "digest": digest,
                "tokens": len(key),
                "hits": 0,
                "insert_round": self.heartbeat.rounds,
                "last_hit_round": None,
            }

    def _prefix_note_evict(self, key: Tuple[int, ...],
                           pages: Optional[Tuple[int, ...]] = None) -> None:
        """Entry left the cache (capacity cap, allocation pressure,
        watermark sweep, or COW un-publish): count it, drop its registry
        metadata, remember the key on the churn ghost, and release the
        allocator's per-page resident-prefix accounting."""
        if pages is not None:
            self._page_alloc.prefix_drop(list(pages))
        with self._submit_lock:
            self._prefix_evictions += 1
            self._prefix_meta.pop(key, None)
            self._prefix_evicted_ghost[key] = None
            while len(self._prefix_evicted_ghost) > \
                    4 * self._prefix_cache_blocks:
                self._prefix_evicted_ghost.popitem(last=False)

    def _prefix_note_admission(self, req: _Request, ids: Sequence[int],
                               reuse: int, blocks: int) -> None:
        """Per-request reuse attribution, at the one instant admission
        knows both the request and the match: stamp the request (digest,
        tokens_reused, analytic prefill seconds saved), move the
        hit/miss counter group under the scheduler lock, feed the
        reuse-distance ring, and queue the {rid, digest, reused,
        prefilled} row for the next flight record. `reuse` is in tokens
        (always a whole number of pblock blocks), `blocks` = reuse //
        pblock."""
        pb = self._pblock
        max_blocks = (len(ids) - 1) // pb
        hit = reuse > 0
        # HIT: the digest is the MATCHED chain entry's (ids[:reuse]) —
        # joinable against /debug/prefixcache and the resident-digest
        # sets, and stable across requests whose tails differ. MISS: the
        # longest whole-block prompt prefix is the best schema-identity
        # guess available (there is no match to name); once the prefix
        # publishes and hits, later admissions converge on the matched
        # digest, so the reuse-distance ring sees the recurrence.
        # `req.ns` (the tenant namespace salt, ISSUE 18) prefixes every
        # key/digest exactly as the cache-key sites do: a tenant's digest
        # only ever joins against its own namespace. () for unlabeled
        # traffic — the shared-registry digests, unchanged.
        if hit:
            digest = self._digest_for(req.ns + tuple(ids[:reuse]))
        elif max_blocks:
            digest = self._digest_for(req.ns + tuple(ids[: max_blocks * pb]))
        else:
            digest = ""
        flops = secs = 0.0
        if hit:
            flops, secs = self.perf.prefill_saved(reuse)
        req.prefix_digest = digest
        req.tokens_reused = reuse
        req.prefill_s_saved = secs
        # Reuse distance BEFORE this admission joins the ring: admissions
        # since the same schema-prefix digest last appeared, from the
        # O(1) digest -> seq map; a sighting older than the ring window
        # counts as absent (the "inf" histogram arm).
        bucket = None
        if digest:
            seq = self._prefix_adm_seq
            last = self._prefix_ring_seq.get(digest)
            dist = (seq - last
                    if last is not None
                    and seq - last <= self._prefix_ring_cap else None)
            bucket = "inf"
            if dist is not None:
                # dist <= ring cap by the window check above, and the
                # bucket list tops out AT the ring cap — next() always
                # finds an arm, however wide the ring is configured.
                bucket = str(next(b for b in self._prefix_rd_buckets
                                  if dist <= b))
        with self._submit_lock:
            if hit:
                self._prefix_hits += 1
                self._prefix_blocks_reused += blocks
                self._prefix_reused_tokens += reuse
                self._prefix_flops_saved += flops
                self._prefix_s_saved += secs
                meta = self._prefix_meta.get(req.ns + tuple(ids[:reuse]))
                if meta is not None:
                    meta["hits"] += 1
                    meta["last_hit_round"] = self.heartbeat.rounds
            elif digest:
                # CACHEABLE admissions only: a prompt shorter than one
                # block (digest == "") can never hit, and counting it as
                # a miss would deflate hit_rate / the EWMA routing signal
                # on short-query traffic the cache was never for.
                self._prefix_misses += 1
            if digest:
                x = 1.0 if hit else 0.0
                prev = self._prefix_hit_ewma
                self._prefix_hit_ewma = (x if prev is None
                                         else 0.2 * x + 0.8 * prev)
            if bucket is not None:
                self._prefix_rd_hist[bucket] = \
                    self._prefix_rd_hist.get(bucket, 0) + 1
                self._prefix_ring_seq[digest] = self._prefix_adm_seq
                self._prefix_adm_seq += 1
                if len(self._prefix_ring_seq) > 2 * self._prefix_ring_cap:
                    # Amortized sweep of sightings older than the window.
                    cutoff = self._prefix_adm_seq - self._prefix_ring_cap
                    self._prefix_ring_seq = {
                        d: s for d, s in self._prefix_ring_seq.items()
                        if s >= cutoff
                    }
        if digest:
            self._round_prefix.append({
                "rid": req.rid,
                "digest": digest,
                "reused": reuse,
                "prefilled": len(ids) - reuse,
            })

    def _prefix_snapshot(self) -> Dict[str, object]:
        """ONE-acquisition copy of the whole telemetry counter group (the
        PR-1 speculation-counter pattern, widened): every field a reader
        pairs — hits/misses/reused beside the priced savings and the
        EWMA — comes from the same instant, so /metrics scrapes and
        bench's pre/post delta bracketing can never see a hits delta
        inconsistent with its prefill_s_saved delta."""
        with self._submit_lock:
            return {
                "hits": self._prefix_hits,
                "misses": self._prefix_misses,
                "blocks_reused": self._prefix_blocks_reused,
                "reused_tokens": self._prefix_reused_tokens,
                "evictions": self._prefix_evictions,
                "reinserts": self._prefix_reinserts,
                "flops_saved": self._prefix_flops_saved,
                "s_saved": self._prefix_s_saved,
                "hit_ewma": self._prefix_hit_ewma,
                "resident_entries": len(self._prefix_meta),
            }

    @staticmethod
    def _prefix_stats_from(snap: Dict[str, object]) -> Dict[str, object]:
        total = int(snap["hits"]) + int(snap["misses"])
        return {
            "hits": snap["hits"],
            "misses": snap["misses"],
            "hit_rate": (round(int(snap["hits"]) / total, 4) if total
                         else 0.0),
            "blocks_reused": snap["blocks_reused"],
            "reused_tokens": snap["reused_tokens"],
            "evictions": snap["evictions"],
        }

    @property
    def prefix_stats(self) -> Dict[str, object]:
        """Prefix-cache observability: requests that reused any blocks vs
        requests the match path came up empty for (`hit_rate` =
        hits/(hits+misses)), total blocks and TOKENS reused (each block
        is a skipped pblock-token prefill), entries evicted, and the
        current LRU size (paged mode: entries are zero-copy page
        references; page_stats carries the sharing counters). The counter
        group is copied under the scheduler lock in ONE acquisition so a
        /metrics scrape or bench's pre/post delta bracketing never
        observes a torn (hits, blocks_reused) pair."""
        return {
            **self._prefix_stats_from(self._prefix_snapshot()),
            "cached_blocks": (len(self._prefix_pages) if self._paged
                              else len(self._prefix_cache)),
        }

    @property
    def prefix_telemetry(self) -> Optional[Dict[str, object]]:
        """The `serving.prefix` /metrics block (ISSUE 14): the counter
        group plus churn, the live hit-rate EWMA, the priced value of the
        hits (analytic prefill FLOPs/seconds saved —
        utils/perfmodel.prefill_saved), and what the cache currently
        HOLDS (entries / tokens / device bytes; paged residency comes
        from the allocator's unique-page accounting, so chained entries
        are not double-counted). None when the cache is off
        (prefix_cache_blocks=0 — including speculative schedulers, which
        disable reuse by design). The whole block derives from ONE locked
        snapshot, so no field pairs across a concurrent admission."""
        if not self._prefix_cache_blocks:
            return None
        snap = self._prefix_snapshot()
        st = self._prefix_stats_from(snap)
        st["cached_blocks"] = (len(self._prefix_pages) if self._paged
                               else len(self._prefix_cache))
        reinserts = snap["reinserts"]
        flops = float(snap["flops_saved"])
        secs = float(snap["s_saved"])
        ewma = snap["hit_ewma"]
        entries = int(snap["resident_entries"])
        # Residency counts what the cache HOLDS, deduped: chained entries
        # overlap on their leading pages, so paged tokens/bytes come from
        # the allocator's unique-page accounting; a contiguous entry
        # holds exactly ONE pblock-token block regardless of its chain
        # key's length (summing per-entry chain lengths would overstate
        # residency ~2x on deep chains).
        if self._paged:
            resident_pages = self._page_alloc.prefix_resident_pages
            tokens = resident_pages * self._page_size
            resident_bytes = resident_pages * page_bytes(
                self.cfg, self._page_size, self._dtype.itemsize,
                self.kv_quant,
            )
        else:
            tokens = entries * self._pblock
            resident_bytes = entries * self._prefix_block_bytes
        return {
            "replica": self.flight.replica,
            **st,
            "reinserts": reinserts,
            "hit_rate_ewma": round(ewma, 4) if ewma is not None else 0.0,
            "prefill_flops_saved": round(flops, 1),
            "prefill_s_saved": round(secs, 6),
            "resident_entries": entries,
            "resident_tokens": tokens,
            "resident_bytes": resident_bytes,
        }

    def resident_digests(self, limit: Optional[int] = None) -> List[str]:
        """Hottest-K resident prefix digests (by hit count, then token
        mass): the bounded per-replica residency set `replica_loads()`
        exports and `SchedulerPool.prefix_affinity` matches a request's
        chain digests against — the cache-aware routing feed the
        multi-host ROADMAP item consumes."""
        k = limit if limit and limit > 0 else self._prefix_topk
        with self._submit_lock:
            metas = sorted(
                self._prefix_meta.values(),
                key=lambda m: (int(m["hits"]), int(m["tokens"])),
                reverse=True,
            )[:k]
        return [str(m["digest"]) for m in metas]

    def prefix_registry(self, top_k: Optional[int] = None
                        ) -> Dict[str, object]:
        """The /debug/prefixcache payload for this replica: top-K
        resident entries by token mass (digest, token length, pages/
        blocks + device bytes held, live share refcount, hit count,
        insert/last-hit round), the reuse-distance histogram over the
        bounded admission ring, and the eviction-churn counters. Bounded
        by `top_k` (default LSOT_PREFIX_TOPK) so a huge cache never turns
        a debug scrape into a token-list dump — entries carry digests,
        never token ids."""
        k = top_k if top_k and top_k > 0 else self._prefix_topk
        tel = self.prefix_telemetry
        # Snapshot metadata, page tuples AND refcounts under ONE lock
        # acquisition: read piecemeal, an entry evicted mid-iteration
        # could have its freed page reallocated to another slot, and the
        # registry would report the unrelated slot's refcount as the
        # entry's share count.
        with self._submit_lock:
            rd = dict(self._prefix_rd_hist)
            metas = []
            for key, m in self._prefix_meta.items():
                pages = self._prefix_pages.get(key) if self._paged else None
                shares = (self._page_alloc.refcount(pages[-1])
                          if pages else None)
                metas.append((m, pages, shares))
        entries: List[Dict[str, object]] = []
        for m, pages, shares in metas:
            e: Dict[str, object] = {
                "digest": m["digest"],
                "tokens": m["tokens"],
                "hits": m["hits"],
                "insert_round": m["insert_round"],
                "last_hit_round": m["last_hit_round"],
            }
            if self._paged:
                if pages is None:
                    continue  # evicted between its meta pop and page pop
                e["pages"] = len(pages)
                e["bytes"] = len(pages) * page_bytes(
                    self.cfg, self._page_size, self._dtype.itemsize,
                    self.kv_quant,
                )
                # How many owners the chain's DEEPEST page had at the
                # snapshot (1 = resident but unmapped by any slot).
                e["shares"] = shares
            else:
                e["blocks"] = 1
                e["bytes"] = self._prefix_block_bytes
            entries.append(e)
        entries.sort(key=lambda e: (int(e["tokens"]), int(e["hits"])),
                     reverse=True)
        return {
            "replica": self.flight.replica,
            "enabled": bool(self._prefix_cache_blocks),
            "block_tokens": self._pblock,
            "capacity": self._prefix_cache_blocks,
            "ring": self._prefix_ring_cap,
            "top_k": k,
            "entries": entries[:k],
            "reuse_distance": rd,
            **({k2: v for k2, v in tel.items() if k2 != "replica"}
               if tel else {}),
        }

    @property
    def watchdog_stats(self) -> Dict[str, object]:
        """Liveness observability for /metrics: the loop's heartbeat (age,
        busy, rounds, measured cadence) and per-slot stall retirements.
        The supervisor layers its stall-detection counters on top."""
        return {
            "heartbeat": self.heartbeat.snapshot(),
            "slots_retired_stalled": self._slot_stalls,
        }

    # ------------------------------------------------------------ event loop

    def _free_slots(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _constrained_busy(self) -> bool:
        return any(
            r is not None and r.constraint is not None for r in self._slot_req
        )

    def _grammar_matches(self, c: CompiledMask) -> bool:
        """Is `c` servable by the INSTALLED tables? Content identity
        (fingerprint + stop ids), not object identity: the constrain-side
        compile cache is LRU-bounded, so the same schema can legitimately
        arrive as a fresh CompiledMask object after an eviction — a
        spurious drain+reinstall for identical tables would serialize the
        batch for nothing."""
        inst = self._constraint
        return inst is not None and (
            c is inst
            or (c.fingerprint == inst.fingerprint
                and c.eos_ids == inst.eos_ids)
        )

    def _install_constraint(self, compiled: CompiledMask) -> None:
        """Swap in a grammar's precompiled device tables (worker thread
        only; callers guarantee no constrained slot is active, so no live
        FSM state can index into the wrong table). Tables are compiled and
        cached by constrain.get_constraint — installing is a device_put of
        existing arrays plus ONE decode retrace when the state count
        changes; per-request admissions with the already-installed grammar
        touch nothing."""
        self._constraint = compiled
        self._ctables = compiled.device_tables(self.cfg.vocab_size)
        self.flight.event("grammar_swap",
                          fingerprint=str(getattr(compiled, "fingerprint",
                                                  ""))[:16])

    def _admit_paged(self, slot: int, req: _Request) -> bool:
        """Paged admission: allocate the request's page envelope and map
        any cached prefix ZERO-COPY (shared pages by refcount; one-page
        copy-on-write only when the matched prefix ends mid-page).
        Returns False — with no side effects — when the pool cannot fund
        the envelope right now (the loop parks the request in _page_wait
        until retirements free pages; all-or-nothing, so partial holders
        can never deadlock each other)."""
        ps, pb = self._page_size, self._pblock
        ids = req.full_ids  # prompt + committed tokens after a preemption
        plen = len(ids)
        n = 0
        # Spill resumes restore page CONTENT into fresh exclusive pages —
        # a shared prefix mapping would be overwritten, so they skip the
        # prefix cache entirely (the pages already hold the prefix).
        if self._prefix_cache_blocks and req.spilled is None:
            # Every lookup keys through the request's tenant namespace
            # salt (`req.ns`, ISSUE 18): a tenant can only ever match —
            # or evict — entries its own admissions published. () for
            # unlabeled traffic keeps the shared-registry keys exact.
            max_blocks = (plen - 1) // pb
            while n < max_blocks and \
                    req.ns + tuple(ids[: (n + 1) * pb]) in self._prefix_pages:
                n += 1
            # Same chunk-envelope cap as the contiguous path: a reuse
            # offset shifts every chunk start, and the final chunk's
            # bucket must still land inside the virtual row.
            s_virt = self._pages_per_slot * ps
            while n and self._chunk_end(n * pb, plen) > s_virt:
                n -= 1
        reuse = n * pb
        # The envelope admission must cover: every position chunked
        # prefill writes, plus decode through the RESERVED generation
        # budget + overshoot. Exact mode (kv_overcommit=1.0) reserves the
        # full remaining budget — today's envelope bit for bit;
        # overcommit reserves the expected generation and decode tops up
        # at each harvest (_topup_pages). Clamped to the per-slot virtual
        # row: a RESUME's prompt (original + committed tokens) re-rounds
        # to the next prompt bucket, which can push the raw formula past
        # max_seq even though every real write stays below it (submit's
        # bound) — unclamped, the allocation could outgrow the device
        # table row. Fresh admissions never hit the clamp (submit's bound
        # keeps their envelope inside the row), so exact-envelope
        # accounting is untouched.
        s_virt = self._pages_per_slot * ps
        need_end = min(s_virt, max(
            self._chunk_end(reuse, plen),
            bucket_len(plen, self.prompt_bucket)
            + self._reserve_new(req) + self.overshoot,
        ))
        need_pages = pages_for_tokens(need_end, ps)
        full = reuse // ps
        entry = (self._prefix_pages.get(req.ns + tuple(ids[:reuse]))
                 if reuse else None)
        shared = list(entry[:full]) if entry else []
        boundary_src = entry[full] if (entry and reuse % ps) else None
        # Take the refs BEFORE allocating: _alloc_pages evicts LRU prefix
        # entries under pressure, and the matched entry must survive it.
        # count=False: these holds are transient until admission succeeds
        # (released on the shortage path below, and the boundary hold only
        # lives until its COW copy) — the shares counter must track
        # mappings that PERSIST, not per-retry churn.
        self._page_alloc.share(shared, count=False)
        if boundary_src is not None:
            self._page_alloc.share([boundary_src], count=False)
        fresh = self._alloc_pages(need_pages - full)
        if fresh is None:
            self._page_alloc.release(shared)
            if boundary_src is not None:
                self._page_alloc.release([boundary_src])
            if not req.page_waited:
                # Count REQUESTS that waited, not per-round retries.
                req.page_waited = True
                self._page_wait_events += 1
            return False
        if boundary_src is not None:
            # Copy-on-write at the non-page-aligned boundary: ONE page
            # copy (vs the contiguous path's whole-prefix gather-copy);
            # prefill resumes mid-page inside the private copy while the
            # cache entry keeps the original.
            self._cache = self._copy_page_fn(
                *self._cache, jnp.int32(fresh[0]), jnp.int32(boundary_src)
            )
            self._page_alloc.note_cow()
            self._page_alloc.release([boundary_src])
        self._slot_pages[slot] = shared + fresh
        self._sync_ptab_row(slot)
        # The full-page mappings are now permanent for this request's
        # lifetime: count them as the zero-copy shares they are (the
        # boundary page was a COW copy, already counted as one).
        self._page_alloc.note_shares(len(shared))
        req.page_end = need_end
        if reuse:
            req.prefilled = reuse
            for j in range(n):  # LRU touch along the matched chain
                key = req.ns + tuple(ids[: (j + 1) * pb])
                if key in self._prefix_pages:
                    self._prefix_pages.move_to_end(key)
        if self._prefix_cache_blocks and req.spilled is None:
            # Reuse attribution at the one instant admission knows both
            # the request and the match (counters move inside, under the
            # scheduler lock — ISSUE 14).
            self._prefix_note_admission(req, ids, reuse, n)
        return True

    def _admit(self, slot: int, req: _Request) -> bool:
        """Reserve `slot` and queue the prompt for chunked prefill, reusing
        any cached prefix first (zero-copy page sharing in paged mode,
        device-to-device block copy in contiguous mode). Returns False —
        side-effect free — only in paged mode when the page pool cannot
        hold the request yet."""
        if req.cancelled:  # cancelled while queued: never occupy a slot
            self._observe_terminal(req)
            req.future.set_result(req.generated)
            return True
        if req.past_deadline():
            # Expired while queued: fail fast with the typed error before
            # ever occupying a slot — under overload, prefilling work whose
            # caller already gave up only steals device time from requests
            # that can still make their deadlines. Terminal bookkeeping
            # still runs: the trace gets its queue-wait span (the one span
            # that explains a 504-from-queue) and the flight record lists
            # the rid as retired.
            resilience.inc("deadline_expired")
            self._observe_terminal(req, error="DeadlineExceeded")
            req.future.set_exception(req.deadline_error())
            return True
        if self._paged and not self._admit_paged(slot, req):
            return False
        if not req.admitted_at:
            # Resumes keep their ORIGINAL admission stamp: the queue-wait
            # span/histogram measure submit → first slot, not decode time
            # an earlier incarnation already spent.
            req.admitted_at = time.perf_counter()
        self._round_admitted.append(req.rid)
        self._slot_req[slot] = req
        # Per-slot incarnation epoch: rounds and prefill first-tokens
        # harvested later carry the epoch they were issued under, so a
        # slot preempted and re-occupied (even by the SAME request —
        # identity checks can't see that) never commits a stale round's
        # tokens.
        self._slot_epoch[slot] += 1
        # Park the slot's decode writes before its prompt starts streaming in
        # (it may still be frozen at the previous occupant's position).
        # Async scatter — no host sync.
        self._cur, self._pos, self._cstates, self._crem = self._park_fn(
            self._cur, self._pos, self._cstates, self._crem, jnp.int32(slot)
        )
        if self._paged and req.spilled is not None:
            # Spill resume: restore the host page copies and arm the slot
            # directly — no re-prefill, no first-token sample.
            self._restore_spilled(slot, req)
            return True
        if self._prefix_cache_blocks and not self._paged:
            pb = self._pblock
            # At least one prompt token must go through real prefill: the
            # final chunk's logit samples the first output token.
            max_blocks = (len(req.ids) - 1) // pb
            n = 0
            while n < max_blocks:
                # Tenant-namespaced key (req.ns, ISSUE 18): () unlabeled.
                if req.ns + tuple(req.ids[: (n + 1) * pb]) \
                        not in self._prefix_cache:
                    break
                n += 1
            # Cap reuse so the chunk envelope stays inside the cache: the
            # un-reused chunking ends at bucket_len(P) <= max_seq-1, but a
            # block-aligned (not bucket-aligned) reuse offset R shifts every
            # chunk start, and the final chunk (whose BUCKET can exceed the
            # tokens left) can then end past the cache. forward's cache
            # write is a dynamic_update_slice whose clamped START would
            # silently shift the whole chunk's KV — so shrink the reuse
            # until the exact envelope fits (n=0 restores the proven-safe
            # un-reused geometry).
            s_cache = self._cache[0].shape[3]
            while n and self._chunk_end(n * pb, len(req.ids)) > s_cache:
                n -= 1
            for j in range(n):
                key = req.ns + tuple(req.ids[: (j + 1) * pb])
                blocks = self._prefix_cache[key]
                self._prefix_cache.move_to_end(key)  # LRU touch
                self._cache = self._restore_block_fn(
                    *self._cache, *blocks, jnp.int32(slot),
                    jnp.int32(j * pb),
                )
            if n:
                req.prefilled = n * pb
            self._prefix_note_admission(req, req.ids, n * pb, n)
        self._prefill_q.append((slot, req))
        return True

    def _next_bucket(self, req: _Request) -> int:
        remaining = len(req.full_ids) - req.prefilled
        return next(
            (b for b in self._buckets if b >= remaining), self.prompt_bucket
        )

    def _chunk_end(self, start: int, total: int) -> int:
        """Highest cache position (exclusive) the chunked prefill of tokens
        [start, total) will WRITE — the final chunk writes its whole bucket,
        which can exceed the tokens left. Mirrors _next_bucket's chunking."""
        end = start
        while start < total:
            remaining = total - start
            t = next(
                (b for b in self._buckets if b >= remaining),
                self.prompt_bucket,
            )
            end = start + t
            start += min(t, remaining)
        return end

    def _prefill_step(self) -> None:
        """Run ONE prompt chunk for up to `_prefill_kmax` waiting requests
        in a single batched forward (Sarathi-style chunked prefill, batched
        over admissions): long prompts interleave with decode rounds instead
        of stalling every active slot (SURVEY.md §7 'without starving
        either'), and admission bursts amortize the weight stream across the
        batch instead of paying a full pass per request. The chunk size is
        the smallest power-of-two bucket covering what's left of the prompt;
        only same-bucket entries batch together (one compiled program per
        (bucket, k-bucket) pair, built lazily)."""
        group: List[Tuple[int, _Request]] = []
        deferred = []
        t = 0
        while self._prefill_q and len(group) < self._prefill_kmax:
            s, r = self._prefill_q.popleft()
            if self._slot_req[s] is not r:
                # Preempted while queued for prefill (its pages are gone
                # and the slot may belong to someone else): the request
                # re-admits from _page_wait, this stale entry just drops.
                continue
            if not group:
                t = self._next_bucket(r)
                group.append((s, r))
            elif self._next_bucket(r) == t:
                group.append((s, r))
            else:
                deferred.append((s, r))
        for item in reversed(deferred):  # keep arrival order for next passes
            self._prefill_q.appendleft(item)
        if not group:
            return

        kb = next(b for b in self._kbuckets if b >= len(group))
        if (t, kb) not in self._prefill_fns:
            self._prefill_fns[(t, kb)] = self._build_prefill(t, kb)
        if self._paged:
            # Copy-on-write sweep over each chunk's write window: a page
            # the publisher shared with the prefix cache last chunk must
            # not be written in place this chunk (only non-page-aligned
            # block boundaries ever trigger it).
            for slot, req in group:
                self._ensure_writable(slot, req.prefilled, req.prefilled + t)

        tokens, lengths, slots, starts = [], [], [], []
        temps, topps, topks, seeds, chunk_lens = [], [], [], [], []
        # First-token grammar state/budget per row: the grammar start
        # state on FINAL chunks of constrained requests (admission
        # guarantees the request's grammar IS the installed one), state 0
        # (the all-allowed sentinel) everywhere else. The prefill fn turns
        # these into a budget-aware mask on device — 2 ints per row cross
        # the host boundary, never a [k, vocab] array.
        cinits, cbudgets = [], []
        for slot, req in group:
            full = req.full_ids
            chunk_ids = full[req.prefilled : req.prefilled + t]
            tokens.append(chunk_ids + [self.cfg.pad_id] * (t - len(chunk_ids)))
            lengths.append(len(chunk_ids))
            chunk_lens.append(len(chunk_ids))
            slots.append(slot)
            starts.append(req.prefilled)
            temps.append(req.temperature)
            topps.append(req.top_p)
            topks.append(req.top_k)
            seeds.append(req.seed & 0xFFFFFFFF)
            final = req.prefilled + len(chunk_ids) >= len(full)
            # Resumed rows discard the prefill's sampled token (the next
            # input is the last COMMITTED token, re-armed by
            # _resume_ready), so they ride the unconstrained sentinel.
            con = (req.constraint is not None and final
                   and not req.resume_pref)
            cinits.append(req.constraint.init_state if con else 0)
            cbudgets.append(req.max_new if con else 1)
        # Padding rows: OOB slot index (writes dropped), positions [0, t)
        # over the clamped gather row — finite garbage, output discarded.
        for _ in range(kb - len(group)):
            tokens.append([self.cfg.pad_id] * t)
            lengths.append(1)
            slots.append(self.num_slots)
            starts.append(0)
            temps.append(0.0)
            topps.append(1.0)
            topks.append(0)
            seeds.append(0)
            cinits.append(0)
            cbudgets.append(1)

        call_args = [
            jnp.asarray(tokens, jnp.int32), jnp.asarray(lengths, jnp.int32),
            jnp.asarray(slots, jnp.int32), jnp.asarray(starts, jnp.int32),
            jnp.asarray(temps, jnp.float32), jnp.asarray(topps, jnp.float32),
            jnp.asarray(topks, jnp.int32), jnp.asarray(seeds, jnp.uint32),
            jnp.asarray(cinits, jnp.int32), jnp.asarray(cbudgets, jnp.int32),
            self._ctables["need"],
        ]
        if self._spec_draft:
            call_args.append(self._hist)
        if self._paged:
            call_args.append(self._ptab)
        out = self._prefill_fns[(t, kb)](self.params, *self._cache, *call_args)
        # Roofline ledger: bank this chunk batch's analytic work; the
        # next harvested round attributes the pile over the measured
        # inter-harvest wall — chunks dispatch asynchronously, so there
        # is no honest per-chunk device wall outside /debug/profile.
        # rows = kb, the PADDED k-bucket: the device computes every
        # padding row's FLOPs too (finite garbage, writes dropped) —
        # the same every-row convention the decode ledger uses
        # (rows = num_slots), so prefill MFU is not understated vs
        # decode's on small admission groups. ctx is the real group's
        # mean attention context (padding rows attend over [0, t)).
        avg_start = sum(starts[: len(group)]) // len(group)
        self.perf.note_prefill(rows=kb, tokens=t,
                               ctx=avg_start + t // 2)
        nc = len(self._cache)
        self._cache, toks = out[:nc], out[-1]
        if self._spec_draft:
            self._hist = out[nc]

        for i, (slot, req) in enumerate(group):
            chunk_start = req.prefilled
            req.prefilled += chunk_lens[i]
            full = req.full_ids
            if self._prefix_cache_blocks:
                if self._paged:
                    self._publish_blocks_paged(slot, req, chunk_start)
                else:
                    self._publish_blocks(slot, req, chunk_start)
            if req.prefilled < len(full):
                self._prefill_q.append((slot, req))
                continue
            if req.resume_pref:
                # Preemption resume (recompute mode): the KV is rebuilt;
                # arm the slot from the COMMITTED state — the prefill's
                # sampled token is discarded (the continuation's first
                # token comes from the next decode round, exactly where
                # the unpreempted control would produce it).
                self._resume_ready(slot, req)
                continue
            if self.phase_role == "prefill":
                # Disaggregation (ISSUE 13): don't arm the slot for
                # decode — park the final chunk's still-on-device first
                # token; _pack_handoffs (called right after this step)
                # syncs it, commits/streams it, and exports the slot's
                # pages into the handoff blob. The ready/spec-ready
                # scatters are skipped on purpose: the importing replica
                # arms everything through the resume machinery.
                self._handoff_pending.append(
                    (slot, req, toks[i : i + 1], self._slot_epoch[slot])
                )
                continue
            # No sync: arm the slot with the still-on-device first token and
            # attach it to the next round's harvest. Stop-token / budget
            # checks on the first token happen there, one round late — the
            # slot may decode a round of garbage first, which the
            # visibility invariant absorbs and submit()'s overshoot bound
            # accounts for.
            req.ready = True
            req.ready_at = time.perf_counter()
            if self._paged:
                # Decode writes [len(ids), page_end): the final chunk's
                # publish may have shared the page holding the prompt
                # tail — COW it before the slot goes decode-eligible, so
                # decode never writes a shared page in place.
                self._ensure_writable(slot, len(req.ids), req.page_end)
            tok = toks[i : i + 1]
            cinit = (req.constraint.init_state if req.constraint is not None
                     else 0)
            (self._cur, self._pos, self._temps, self._topps, self._topks,
             self._seeds, self._counts, self._cstates,
             self._crem) = self._ready_fn(
                self._cur, self._pos, self._temps, self._topps, self._topks,
                self._seeds, self._counts, self._cstates, self._crem,
                self._ctables["next"], jnp.int32(slot), tok,
                jnp.int32(len(req.ids)),
                jnp.float32(req.temperature), jnp.float32(req.top_p),
                jnp.int32(req.top_k), jnp.uint32(req.seed & 0xFFFFFFFF),
                jnp.int32(cinit), jnp.int32(req.max_new),
            )
            # The host mirror of the slot's on-device RNG stream index
            # (ready_slot set counts = 1: the prefill sample consumed
            # fold index 0) — what a later preemption restores.
            req.rng_count = 1
            if self._spec_draft:
                self._hist, self._hlen = self._spec_ready_fn(
                    self._hist, self._hlen, jnp.int32(slot), tok,
                    jnp.int32(len(req.ids)),
                )
            self._first_pending.append(
                (slot, req, tok, self._slot_epoch[slot])
            )

    def _publish_blocks(self, slot: int, req: _Request, chunk_start: int) -> None:
        """Publish the chunk's completed prefix blocks (chunk_start is always
        block-aligned: reuse stops on block boundaries and every non-final
        chunk is a bucket = multiple of pblock)."""
        pb = self._pblock
        for b0 in range(chunk_start // pb, req.prefilled // pb):
            key = req.ns + tuple(req.ids[: (b0 + 1) * pb])
            if key in self._prefix_cache:
                self._prefix_cache.move_to_end(key)
                continue
            if key not in self._prefix_seen:
                # First sighting: remember the content, copy nothing.
                self._prefix_seen[key] = None
                while len(self._prefix_seen) > 4 * self._prefix_cache_blocks:
                    self._prefix_seen.popitem(last=False)
                continue
            entry = self._slice_block_fn(
                *self._cache, jnp.int32(slot), jnp.int32(b0 * pb)
            )
            self._prefix_cache[key] = entry
            if not self._prefix_block_bytes:
                # One block's device footprint (constant per scheduler):
                # the registry's contiguous resident-bytes unit.
                self._prefix_block_bytes = sum(int(b.nbytes) for b in entry)
            self._prefix_note_publish(key)
            while len(self._prefix_cache) > self._prefix_cache_blocks:
                old_key, _ = self._prefix_cache.popitem(last=False)
                self._prefix_note_evict(old_key)

    def _publish_blocks_paged(self, slot: int, req: _Request,
                              chunk_start: int) -> None:
        """Paged publish: an entry is a REFERENCE to the publisher's pages
        (refcount++), not a copy — zero data movement, same publish gate
        and hash-chain content keys as the contiguous path. The publisher
        itself COWs before its next write into a page it just shared
        (_ensure_writable), so entry content is immutable from here on."""
        pb, ps = self._pblock, self._page_size
        ids = req.full_ids
        for b0 in range(chunk_start // pb, req.prefilled // pb):
            key = req.ns + tuple(ids[: (b0 + 1) * pb])
            if key in self._prefix_pages:
                self._prefix_pages.move_to_end(key)
                continue
            if key not in self._prefix_seen:
                # First sighting: remember the content, share nothing.
                self._prefix_seen[key] = None
                while len(self._prefix_seen) > 4 * self._prefix_cache_blocks:
                    self._prefix_seen.popitem(last=False)
                continue
            covered = (b0 + 1) * pb
            pages = tuple(
                self._slot_pages[slot][: pages_for_tokens(covered, ps)]
            )
            self._page_alloc.share(list(pages))
            self._page_alloc.prefix_hold(list(pages))
            self._prefix_pages[key] = pages
            self._prefix_note_publish(key)
            while len(self._prefix_pages) > self._prefix_cache_blocks:
                old_key, old = self._prefix_pages.popitem(last=False)
                self._prefix_note_evict(old_key, pages=old)
                self._page_alloc.release(list(old))

    def _issue_decode(self) -> None:
        """Dispatch one decode round asynchronously: state chains on device,
        nothing syncs here. The round's tokens are harvested `_harvest_lag`
        rounds later so the transfer round-trip overlaps later compute."""
        # Chaos seam (utils/faults.py): a `sched:decode` fault simulates a
        # device/loop failure mid-round — the loop dies, _run wraps it in
        # SchedulerCrashed, and every client future must fail typed, never
        # hang (asserted by the chaos tests).
        FAULTS.check("sched:decode")
        # Duration-valued hang seam: `sched:hang:p:secs` SLEEPS here —
        # the wedge that never raises (hung XLA dispatch, stuck tunnel).
        # The heartbeat was stamped at the loop top, so its age grows for
        # the whole sleep and the supervisor's watchdog must detect and
        # escalate it (SchedulerStalled → restart/replay).
        FAULTS.check("sched:hang")
        if FAULTS.active:
            # Replica-ADDRESSABLE seam (`sched:wedge_r1:p[:secs]`): wedge
            # (duration form) or crash (raising form) exactly ONE pool
            # replica by its label, leaving siblings untouched — the
            # fleet chaos stage's targeted-restart trigger. Gated on
            # FAULTS.active so the idle path never builds the site string.
            FAULTS.check(f"sched:wedge_{self.flight.replica}")
        active = np.asarray(
            [r is not None and r.ready for r in self._slot_req]
        )
        issue_reqs = [
            self._slot_req[i] if active[i] else None
            for i in range(self.num_slots)
        ]
        nc = len(self._cache)
        extra = (self._ptab,) if self._paged else ()
        if self._spec_draft:
            t = self._ctables
            out = self._decode_fn(
                self.params, *self._cache, self._hist, self._hlen,
                self._cur, self._pos, jnp.asarray(active), self._temps,
                self._topps, self._topks, self._seeds, self._counts,
                self._cstates, self._crem, t["next"], t["need"], *extra,
            )
            self._cache = out[:nc]
            (self._hist, self._hlen, self._cur, self._pos, self._counts,
             self._cstates, self._crem, toks, n_emit) = out[nc:]
        else:
            t = self._ctables
            out = self._decode_fn(
                self.params, *self._cache, self._cur, self._pos,
                jnp.asarray(active), self._temps, self._topps, self._topks,
                self._seeds, self._counts, self._cstates, self._crem,
                t["next"], t["need"], *extra,
            )
            self._cache = out[:nc]
            (self._cur, self._pos, self._counts, self._cstates, self._crem,
             toks) = out[nc:]
            n_emit = None
        self._pending.append((issue_reqs, list(self._slot_epoch), toks,
                              n_emit, self._first_pending,
                              time.perf_counter(), None))
        self._first_pending = []

    def _issue_mixed(self) -> bool:
        """LSOT_RAGGED=1 hot path (ISSUE 19): ONE compiled launch admits
        this iteration's prompt chunks AND the decode round — no phase
        alternation, no off-phase idle. Same group selection as
        _prefill_step (one bucket per round, arrival order), same host
        tail (publish / requeue / arm), same async pending/harvest
        plumbing as _issue_decode — the round just carries a mixed_meta
        so harvest attributes both phases' analytic work over one wall.
        Returns False (caller falls back to the alternating path for
        this iteration) when every queued entry was stale."""
        group: List[Tuple[int, _Request]] = []
        deferred = []
        t = 0
        while self._prefill_q and len(group) < self._prefill_kmax:
            s, r = self._prefill_q.popleft()
            if self._slot_req[s] is not r:
                continue  # preempted while queued; re-admits via _page_wait
            if not group:
                t = self._next_bucket(r)
                group.append((s, r))
            elif self._next_bucket(r) == t:
                group.append((s, r))
            else:
                deferred.append((s, r))
        for item in reversed(deferred):  # keep arrival order for next passes
            self._prefill_q.appendleft(item)
        if not group:
            return False
        # Chaos seams: the mixed round IS the decode round, so the same
        # crash/hang/wedge sites fire here (chaos contracts hold with
        # ragged on).
        FAULTS.check("sched:decode")
        FAULTS.check("sched:hang")
        if FAULTS.active:
            FAULTS.check(f"sched:wedge_{self.flight.replica}")
        if t not in self._mixed_fns:
            self._mixed_fns[t] = (
                self._build_mixed_spec(t) if self._spec_draft
                else self._build_mixed(t)
            )
        # COW sweep over each chunk's write window (ragged implies paged).
        for slot, req in group:
            self._ensure_writable(slot, req.prefilled, req.prefilled + t)

        # S-wide prefill-row vectors: non-group rows carry the inert
        # defaults (is_pref=False routes them to the decode lane; the
        # rest are never read for such rows).
        S = self.num_slots
        p_tokens = [[self.cfg.pad_id] * t for _ in range(S)]
        p_lengths = [1] * S
        p_starts = [0] * S
        is_pref = [False] * S
        p_temps = [0.0] * S
        p_topps = [1.0] * S
        p_topks = [0] * S
        p_seeds = [0] * S
        p_cinits = [0] * S
        p_cbudgets = [1] * S
        chunk_lens: Dict[int, int] = {}
        for slot, req in group:
            full = req.full_ids
            chunk_ids = full[req.prefilled : req.prefilled + t]
            p_tokens[slot] = (
                chunk_ids + [self.cfg.pad_id] * (t - len(chunk_ids))
            )
            p_lengths[slot] = len(chunk_ids)
            chunk_lens[slot] = len(chunk_ids)
            p_starts[slot] = req.prefilled
            is_pref[slot] = True
            p_temps[slot] = req.temperature
            p_topps[slot] = req.top_p
            p_topks[slot] = req.top_k
            p_seeds[slot] = req.seed & 0xFFFFFFFF
            final = req.prefilled + len(chunk_ids) >= len(full)
            con = (req.constraint is not None and final
                   and not req.resume_pref)
            p_cinits[slot] = req.constraint.init_state if con else 0
            p_cbudgets[slot] = req.max_new if con else 1

        active = np.asarray(
            [r is not None and r.ready for r in self._slot_req]
        )
        issue_reqs = [
            self._slot_req[i] if active[i] else None
            for i in range(self.num_slots)
        ]
        nc = len(self._cache)
        tab = self._ctables
        p_args = (
            jnp.asarray(p_tokens, jnp.int32),
            jnp.asarray(p_lengths, jnp.int32),
            jnp.asarray(p_starts, jnp.int32),
            jnp.asarray(is_pref, jnp.bool_),
            jnp.asarray(p_temps, jnp.float32),
            jnp.asarray(p_topps, jnp.float32),
            jnp.asarray(p_topks, jnp.int32),
            jnp.asarray(p_seeds, jnp.uint32),
            jnp.asarray(p_cinits, jnp.int32),
            jnp.asarray(p_cbudgets, jnp.int32),
        )
        if self._spec_draft:
            out = self._mixed_fns[t](
                self.params, *self._cache, self._hist, self._hlen,
                self._cur, self._pos, jnp.asarray(active), self._temps,
                self._topps, self._topks, self._seeds, self._counts,
                self._cstates, self._crem, *p_args, tab["next"],
                tab["need"], self._ptab,
            )
            self._cache = out[:nc]
            (self._hist, self._hlen, self._cur, self._pos, self._counts,
             self._cstates, self._crem, toks, n_emit, firsts) = out[nc:]
        else:
            out = self._mixed_fns[t](
                self.params, *self._cache, self._cur, self._pos,
                jnp.asarray(active), self._temps, self._topps, self._topks,
                self._seeds, self._counts, self._cstates, self._crem,
                *p_args, tab["next"], tab["need"], self._ptab,
            )
            self._cache = out[:nc]
            (self._cur, self._pos, self._counts, self._cstates, self._crem,
             toks, firsts) = out[nc:]
            n_emit = None
        # Both phases' analytic work attributes over THIS round's wall at
        # harvest (perfmodel.observe_mixed) — no note_prefill banking.
        avg_start = sum(p_starts[s] for s, _ in group) // len(group)
        mixed_meta = {
            "pre_rows": len(group),
            "pre_tokens": t,
            "pre_ctx": avg_start + t // 2,
        }

        # Host tail for the chunk rows: _prefill_step's, minus the
        # prefill-role handoff branch (ragged requires phase_role=mixed).
        for slot, req in group:
            chunk_start = req.prefilled
            req.prefilled += chunk_lens[slot]
            full = req.full_ids
            if self._prefix_cache_blocks:
                self._publish_blocks_paged(slot, req, chunk_start)
            if req.prefilled < len(full):
                self._prefill_q.append((slot, req))
                continue
            if req.resume_pref:
                self._resume_ready(slot, req)
                continue
            req.ready = True
            req.ready_at = time.perf_counter()
            self._ensure_writable(slot, len(req.ids), req.page_end)
            tok = firsts[slot : slot + 1]
            cinit = (req.constraint.init_state if req.constraint is not None
                     else 0)
            (self._cur, self._pos, self._temps, self._topps, self._topks,
             self._seeds, self._counts, self._cstates,
             self._crem) = self._ready_fn(
                self._cur, self._pos, self._temps, self._topps, self._topks,
                self._seeds, self._counts, self._cstates, self._crem,
                self._ctables["next"], jnp.int32(slot), tok,
                jnp.int32(len(req.ids)),
                jnp.float32(req.temperature), jnp.float32(req.top_p),
                jnp.int32(req.top_k), jnp.uint32(req.seed & 0xFFFFFFFF),
                jnp.int32(cinit), jnp.int32(req.max_new),
            )
            req.rng_count = 1
            if self._spec_draft:
                self._hist, self._hlen = self._spec_ready_fn(
                    self._hist, self._hlen, jnp.int32(slot), tok,
                    jnp.int32(len(req.ids)),
                )
            self._first_pending.append(
                (slot, req, tok, self._slot_epoch[slot])
            )
        self._pending.append((issue_reqs, list(self._slot_epoch), toks,
                              n_emit, self._first_pending,
                              time.perf_counter(), mixed_meta))
        self._first_pending = []
        return True

    def _retire(self, slot: int, req: _Request, result: List[int]) -> None:
        """Resolve a finished request, free its slot, and reset the slot's
        on-device sampling knobs (a lingering temperature > 0 would defeat
        sample_runtime's all-greedy fast path for every later round)."""
        self._record_service_time(req)
        self._observe_terminal(req)
        req.future.set_result(result)
        self._release_slot(slot)

    def _fail_slot(self, slot: int, req: _Request, exc: Exception) -> None:
        """Retire a slot with a typed FAILURE (deadline expiry): same slot
        release as _retire, but the future carries the error."""
        self._observe_terminal(req, error=type(exc).__name__)
        req.future.set_exception(exc)
        self._release_slot(slot)

    def _observe_terminal(self, req: _Request,
                          error: Optional[str] = None) -> None:
        """Per-request terminal bookkeeping BEFORE the future resolves
        (the client reads these right after result()): flush the trace's
        scheduler spans, stamp the measured queue wait on the future (the
        Completion/metrics seam), and log the rid as retired for this
        round's flight record."""
        now = time.perf_counter()
        req.flush_spans(now)
        if req.trace is not None and error is not None:
            req.trace.event("sched.error", error=error, rid=req.rid)
        if req.admitted_at and req.submitted_at:
            req.future._lsot_queue_wait = req.admitted_at - req.submitted_at
        elif req.submitted_at:
            # Never admitted (expired/cancelled while queued or parked on
            # pool pages): its whole life WAS queue wait — page-wait
            # starvation must show up in the queue-wait span + histogram,
            # not vanish because the request never reached a slot.
            req.future._lsot_queue_wait = now - req.submitted_at
        self._round_retired.append(req.rid)
        with self._submit_lock:
            self._pending_new_tokens = max(
                0, self._pending_new_tokens - req.max_new
            )
            self._pending_prompt_tokens = max(
                0, self._pending_prompt_tokens - len(req.ids)
            )

    def _release_slot(self, slot: int) -> None:
        self._slot_req[slot] = None
        self._slot_epoch[slot] += 1
        self._temps, self._topps, self._topks, self._cstates = self._retire_fn(
            self._temps, self._topps, self._topks, self._cstates,
            jnp.int32(slot)
        )
        if self._paged:
            # In-flight overshoot rounds still write through the page-table
            # version they were issued with; device program order puts
            # those writes BEFORE any new occupant's prefill of the freed
            # pages, so the garbage is overwritten before it can become
            # visible (the same invariant the contiguous layout relies
            # on for its per-row overshoot writes).
            self._free_slot_pages(slot)

    def _append_first(self, slot: int, req: _Request, first: int,
                      epoch: Optional[int] = None) -> int:
        """Apply a harvested prefill first-token: stop/budget checks run
        here, one round late (the slot may have decoded a garbage round
        meanwhile — absorbed by the visibility invariant and submit()'s
        overshoot bound). Returns tokens appended (0/1) so the harvest's
        flight record counts prefill firsts in its emitted tally."""
        if req is not self._slot_req[slot]:
            return 0  # cleared by shutdown/crash path meanwhile
        if epoch is not None and epoch != self._slot_epoch[slot]:
            return 0  # preempted + re-admitted: a fresh arm supersedes this
        if req.cancelled:
            self._retire(slot, req, req.generated)
            return 0
        if req.past_deadline():
            # In-flight expiry rides the cancel path's timing (next
            # harvest) but fails the future with the typed error.
            resilience.inc("deadline_expired")
            self._fail_slot(slot, req, req.deadline_error())
            return 0
        if first in self.stop_ids or req.max_new < 1:
            self._retire(slot, req, req.generated)
            return 0
        req.generated.append(first)
        req.emit(first)
        if len(req.generated) >= req.max_new:
            self._retire(slot, req, req.generated)
        return 1

    def _harvest_round(self) -> None:
        """Sync the OLDEST in-flight round: one device_get brings down its
        chunk tokens plus any prefill first-tokens attached to it; retire
        finished requests and free their slots."""
        # Chaos seam (utils/faults.py): `sched:crash` kills the loop
        # MID-BATCH — rounds issued, tokens possibly already streamed to
        # clients, slots occupied. The supervisor (serve/supervisor.py)
        # must restart the loop and replay every acknowledged request
        # without duplicating delivered tokens (chaos tests assert zero
        # lost, zero double-streamed).
        FAULTS.check("sched:crash")
        (issue_reqs, epochs, toks_dev, n_emit_dev, firsts, t_issue,
         mixed_meta) = self._pending.popleft()
        toks, n_emit, first_vals = jax.device_get(
            (toks_dev, n_emit_dev, [t for (_, _, t, _) in firsts])
        )
        toks = np.asarray(toks)
        t_harvest = time.perf_counter()
        occupancy = sum(1 for r in issue_reqs if r is not None)
        round_emitted = 0
        # Two independent splits of the same per-round emission totals:
        # constrained/unconstrained (grammar class) and greedy/sampled
        # (sampling class — the rejection-sampling path's acceptance is
        # separately observable in the flight recorder).
        spec_emitted = {"constrained": 0, "unconstrained": 0,
                        "greedy": 0, "sampled": 0}
        # Firsts precede the round's chunk tokens in every stream: their
        # ready-scatter was dispatched before the round was issued.
        for (slot, req, _, fep), fv in zip(firsts, first_vals):
            round_emitted += self._append_first(slot, req,
                                                int(np.asarray(fv)[0]),
                                                epoch=fep)
        # Per-slot progress this round: a slot "advanced" if it appended a
        # token or reached a terminal state. A slot that advanced nothing
        # in a HARVESTED round accrues a stall round (sweep after the
        # loop): reaching harvest accounting at all proves the loop is
        # alive — a genuinely wedged loop blocks inside a jax call and is
        # the watchdog's case (stale heartbeat), never this one. The
        # common signature is one frozen lane while its batch neighbours
        # advance; a LONE frozen slot must retire too, or it pins its
        # lane until the client's deadline burns.
        advanced: List[int] = []
        no_progress: List[Tuple[int, _Request]] = []
        for i, req in enumerate(issue_reqs):
            if req is None or req is not self._slot_req[i] \
                    or epochs[i] != self._slot_epoch[i]:
                continue  # inactive at issue, retired, or preempted since
            # Mirror the slot's on-device RNG stream advance for this
            # COMMITTED round (what a preemption resume restores): vanilla
            # rounds consume one fold index per chunk token for every
            # active slot; speculative rounds consume one per SAMPLED
            # round (greedy argmax draws nothing).
            if n_emit is None:
                req.rng_count += self.decode_chunk
            elif req.temperature > 0.0:
                req.rng_count += 1
            if req.cancelled:
                self._retire(i, req, req.generated)
                advanced.append(i)
                continue
            if req.past_deadline():
                resilience.inc("deadline_expired")
                self._fail_slot(i, req, req.deadline_error())
                advanced.append(i)
                continue
            # Speculative rounds emit a variable number of accepted tokens
            # per slot; vanilla rounds emit the whole chunk row.
            if n_emit is None:
                row = toks[i]
            else:
                ne = int(n_emit[i])
                row = toks[i][:ne]
                sampled_req = req.temperature > 0.0
                cls = ("constrained" if req.constraint is not None
                       else "unconstrained")
                spec_emitted[cls] += ne
                spec_emitted["sampled" if sampled_req else "greedy"] += ne
                if ne > 0:
                    # All counters move under the scheduler's lock so
                    # speculation_stats (HTTP/metrics threads) and
                    # bench.py's pre/post delta bracketing always read a
                    # COHERENT (rounds, tokens) pair — unlocked, a reader
                    # could see rounds bumped but tokens not yet
                    # (ADVICE.md r5 #2).
                    with self._submit_lock:
                        self._spec_rounds += 1
                        self._spec_tokens += ne
                        if req.constraint is not None:
                            # Per-class splits: each pair is the named
                            # subset of the totals (the complement class
                            # is total - subset).
                            self._spec_rounds_con += 1
                            self._spec_tokens_con += ne
                        if sampled_req:
                            self._spec_rounds_samp += 1
                            self._spec_tokens_samp += ne
            if req.stall_inject:
                # Injected lane wedge (`sched:slot_stall`): the device
                # "produced nothing useful" for this slot this round.
                row = row[:0]
            before = len(req.generated)
            done = False
            for tok in row:
                tok = int(tok)
                if tok in self.stop_ids:
                    done = True
                    break
                req.generated.append(tok)
                req.emit(tok)
                if len(req.generated) >= req.max_new:
                    done = True
                    break
            appended = len(req.generated) - before
            round_emitted += appended
            if req.trace is not None:
                # One span per harvested round for sampled requests: where
                # decode time went, round by round — with the speculation
                # acceptance and grammar-mask attrs a latency regression
                # investigation starts from.
                attrs = {"emitted": appended, "rid": req.rid}
                if req.constraint is not None:
                    attrs["grammar_mask"] = True
                if n_emit is not None:
                    attrs["spec_accepted"] = int(n_emit[i])
                req.trace.add_span("sched.round", t_issue, t_harvest,
                                   **attrs)
            if done:
                self._retire(i, req, req.generated)
                advanced.append(i)
            elif len(req.generated) > before:
                req.stall_rounds = 0
                advanced.append(i)
            else:
                no_progress.append((i, req))
        if self.slot_stall_rounds and no_progress:
            for i, req in no_progress:
                if req is not self._slot_req[i]:
                    continue
                req.stall_rounds += 1
                if req.stall_rounds >= self.slot_stall_rounds:
                    self._slot_stalls += 1
                    resilience.inc("slot_stalls")
                    _log.warning(
                        "slot %d made no progress for %d harvested rounds "
                        "(%d other slot(s) advanced this round); retiring "
                        "typed", i, req.stall_rounds, len(advanced),
                    )
                    self._fail_slot(i, req, SlotStalled(
                        f"slot {i} made no progress for {req.stall_rounds} "
                        f"harvested decode rounds while the loop stayed "
                        f"live ({len(req.generated)} of {req.max_new} "
                        f"tokens generated before the lane wedged)"
                    ))
        if self._paged:
            # Overcommit's safety valve: retirements above just freed
            # pages; extend every live slot's mapping past the committed
            # frontier + overshoot BEFORE the next round can write
            # through an unmapped entry. Allocation failure preempts here
            # (never silently drops KV).
            self._topup_pages()
        self.heartbeat.round_done()
        # Flight-recorder round record (the postmortem black box): what
        # this round DID — occupancy at issue, admission/retirement churn
        # since the last record, tokens emitted (speculation split by
        # class when on), round wall (issue→harvest, pipeline lag
        # included), and the heartbeat's measured cadence. One bounded
        # append; bench prices it.
        ewma = self.heartbeat.expected_round_s()
        round_wall = round(t_harvest - t_issue, 6)
        # Monotonic accepted-token counter (ISSUE 16): the per-model
        # tok/s feed — one int add on the harvest path, read by the
        # pool's model_stats() and the lsot_model_tokens_total family.
        self._tokens_emitted_total += round_emitted
        rec = {
            "round": self.heartbeat.rounds,
            "occupancy": occupancy,
            "queued": self._queue.qsize(),
            "admitted": self._round_admitted,
            "retired": self._round_retired,
            "emitted": round_emitted,
            "round_wall_s": round_wall,
            "cadence_s": round(ewma, 6) if ewma is not None else None,
        }
        if n_emit is not None:
            rec["spec_emitted"] = spec_emitted
        if self._round_prefix:
            # Per-request reuse attribution for admissions since the last
            # record (ISSUE 14): {rid, digest, reused, prefilled} per
            # admitted request with at least one full prompt block —
            # present only on rounds that admitted such requests, so
            # records elsewhere stay byte-identical to pre-telemetry.
            rec["prefix_reuse"] = self._round_prefix
            self._round_prefix = []
        # Roofline ledger columns (ISSUE 12): this round's achieved MFU /
        # HBM-bandwidth utilization / binding-roof verdict from the shared
        # analytic model — computed from the ROUNDED wall that lands in
        # the record, so a reader (and the tier-1 reconciliation test) can
        # recompute the exact same numbers from the record alone.
        # `rows` is num_slots: the device computes EVERY slot row, parked
        # lanes included (occupancy is the goodput column beside it);
        # `perf_ctx` is the active rows' mean committed context. Spec
        # rounds are the VERIFY phase (one T=D+1 forward); the draft
        # gather is ledgered separately into the phase EWMAs.
        phase = "decode" if n_emit is None else "verify"
        tokens = (self.decode_chunk if n_emit is None
                  else self._spec_draft + 1)
        ctx_sum = sum(
            len(r.ids) + len(r.generated)
            for r in issue_reqs if r is not None
        )
        perf_ctx = max(1, ctx_sum // max(1, occupancy))
        if mixed_meta is not None:
            # Unified ragged round (LSOT_RAGGED=1): one launch did both
            # phases' work, so ONE attribution covers decode/verify rows
            # AND the chunk rows over the same wall. The record keeps the
            # chunk-side inputs so the reconciliation test can recompute
            # the ledger columns from the record alone (ragged-off
            # records never carry these keys — byte-identical to the
            # alternating control).
            phase = "mixed"
            att = self.perf.observe_mixed(
                rows=self.num_slots, dec_tokens=tokens, dec_ctx=perf_ctx,
                pre_rows=mixed_meta["pre_rows"],
                pre_tokens=mixed_meta["pre_tokens"],
                pre_ctx=mixed_meta["pre_ctx"], wall_s=round_wall,
            )
            rec["pre_rows"] = mixed_meta["pre_rows"]
            rec["pre_tokens"] = mixed_meta["pre_tokens"]
            rec["pre_ctx"] = mixed_meta["pre_ctx"]
        else:
            att = self.perf.observe(phase, rows=self.num_slots,
                                    tokens=tokens, ctx=perf_ctx,
                                    wall_s=round_wall)
        rec["phase"] = phase
        rec["perf_ctx"] = perf_ctx
        rec["mfu"] = att["mfu"]
        rec["hbm_util"] = att["hbm_util"]
        rec["bound"] = att["bound"]
        if n_emit is not None and self._spec_draft:
            self.perf.observe("draft", rows=self.num_slots,
                              tokens=self._spec_draft,
                              ctx=int(self._hist.shape[1]),
                              wall_s=round_wall)
        # Prefill chunks dispatched since the last harvest attribute over
        # the inter-harvest wall (the live prefill-vs-decode asymmetry
        # signal the disaggregation ROADMAP item needs per replica).
        interval = round(
            t_harvest - (self._last_harvest_t
                         if self._last_harvest_t is not None else t_issue),
            6,
        )
        self._last_harvest_t = t_harvest
        pre = self.perf.flush_prefill(interval)
        if pre is not None:
            rec["prefill_mfu"] = pre["mfu"]
            rec["prefill_hbm_util"] = pre["hbm_util"]
        if self._paged:
            # Page-pool occupancy per round: the flight-recorder column a
            # leaked page shows up in (pages_in_use that never drains
            # while occupancy does). kv_pressure is the injected withheld
            # reserve (kv:pressure chaos site) — the column a preemption
            # storm postmortem reads next to the preempt/resume events.
            rec["kv_pages"] = self._page_alloc.pages_in_use
            rec["kv_pages_free"] = self._page_alloc.pages_free
            rec["kv_pressure"] = self._page_alloc.withheld
        if self._mig_pages:
            # Handoff columns (ISSUE 13 satellite): pages imported since
            # the last record and the decode-slot wait they carried —
            # present only on rounds that actually imported, so a mixed
            # replica's records stay byte-identical to pre-disagg.
            rec["pages_migrated"] = self._mig_pages
            rec["handoff_wait_s"] = round(self._mig_wait, 6)
            self._mig_pages = 0
            self._mig_wait = 0.0
        self.flight.record(**rec)
        self._round_admitted = []
        self._round_retired = []
        if self._profile_active is not None:
            self._profile_round_done()

    def _harvest_firsts(self) -> None:
        """Drain path: ready slots whose first token never rode a round."""
        if not self._first_pending:
            return
        firsts, self._first_pending = self._first_pending, []
        vals = jax.device_get([t for (_, _, t, _) in firsts])
        for (slot, req, _, fep), fv in zip(firsts, vals):
            self._append_first(slot, req, int(np.asarray(fv)[0]), epoch=fep)

    def _run(self) -> None:
        try:
            self._loop()
            self._close(RuntimeError("scheduler shut down mid-request"))
        except BaseException as exc:  # noqa: BLE001 — a dead loop must not hang clients
            # Fail everything with the TYPED crash error (original
            # traceback attached): callers distinguish "engine dead" (503,
            # breaker-relevant) from a per-request failure (500).
            wrapped = SchedulerCrashed.from_exception(exc)
            self._crash = wrapped
            # Black-box marker: the postmortem dump shows the crash beside
            # the rounds that led up to it.
            self.flight.event("crash", error=str(exc)[:200],
                              error_type=type(exc).__name__)
            self._close(wrapped)
            raise

    def _close(self, exc: BaseException) -> None:
        """Fail every in-flight and queued request; reject future submits."""
        # An armed/mid-flight /debug/profile capture must not leak the
        # fleet-wide guard past the loop that owned it.
        self._abort_profile(f"scheduler closed: {type(exc).__name__}")
        with self._submit_lock:
            self._closed = True
            self._pending_new_tokens = 0
            ready, self._ready = self._ready, []
        for req in ready:  # staged in the WFQ pool when the loop died
            req.future.set_exception(exc)
        self._prefill_q.clear()  # their requests fail via the slot sweep below
        self._pending.clear()    # in-flight rounds: futures fail below
        self._first_pending = []
        self._handoff_pending = []  # still slot-held: the sweep covers them
        for req in self._handoff:
            # Parked in the handoff queue when the loop died: the blob is
            # lost with this replica — fail typed so the supervisor's
            # journal re-prefills the request on a sibling.
            req.future.set_exception(exc)
        self._handoff.clear()
        for req in self._constraint_wait:  # waiting on a grammar swap
            req.future.set_exception(exc)
        self._constraint_wait.clear()
        if self._paged:
            for req in self._page_wait:  # waiting on pool pages
                req.future.set_exception(exc)
            self._page_wait.clear()
        for i, req in enumerate(self._slot_req):
            if req is not None:
                req.future.set_exception(exc)
                self._slot_req[i] = None
                if self._paged and self._slot_pages[i]:
                    # Host-side release only — no device work on a possibly
                    # wedged path. The device table rows go stale; start()
                    # re-syncs them before the loop serves again.
                    self._page_alloc.release(self._slot_pages[i])
                    self._slot_pages[i] = []
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.future.set_exception(exc)

    def _busy_now(self) -> bool:
        """Work anywhere in the pipeline: the busy flag the event loop
        stamps into the heartbeat each iteration. A method (not inlined
        in `_loop`) so bench's `_watchdog_overhead` can time the FULL
        per-iteration liveness cost — this scan plus the stamp — instead
        of the stamp alone."""
        return bool(
            self._prefill_q or self._pending or self._constraint_wait
            or self._handoff or self._handoff_pending
            or (self._paged and self._page_wait)
            or any(r is not None for r in self._slot_req)
            or not self._queue.empty()
            or self._ready
        )

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            # Liveness stamp FIRST, so a wedge anywhere below (a hung XLA
            # dispatch in prefill/decode, a stuck device_get in harvest)
            # leaves a stale busy stamp for the watchdog to age. Idle
            # iterations stamp busy=False every <=50ms (the queue.get
            # timeout below), so an idle loop never looks wedged.
            self.heartbeat.stamp(busy=self._busy_now())
            if self._paged:
                # Pressure-relief upkeep, every iteration (cheap int
                # math when nothing is happening): sample the
                # kv:pressure chaos site, evict prefix pages down to the
                # high watermark when free pages dip under the low one,
                # and fail page-starved waiters whose deadline burned
                # (they would otherwise wait forever while slots stay
                # busy).
                self._sample_pressure()
                self._watermark_sweep()
                self._sweep_page_wait()
            # Admit pending requests into every free slot, then issue one
            # prompt chunk and one decode round — all asynchronously — and
            # harvest the oldest round once the pipeline is `_harvest_lag`
            # deep. When fully idle, drain and block for work. Requests
            # whose grammar differs from the installed one wait in
            # `_constraint_wait` until the constrained slots drain (the
            # table swap must not move live FSM states between grammars),
            # then install and admit in arrival order. Fairness: while
            # waiters exist, NEW constrained requests also queue behind
            # them (even for the currently installed grammar) — otherwise
            # a steady same-grammar stream keeps _constrained_busy() true
            # forever and a different-grammar waiter starves. Waiters
            # matching the installed grammar admit immediately (no drain
            # needed); unconstrained traffic always flows directly.
            while self._free_slots():
                wait = self._constraint_wait
                if wait and self._grammar_matches(wait[0].constraint):
                    req = wait.popleft()
                elif wait and not self._constrained_busy():
                    req = wait.popleft()
                    self._install_constraint(req.constraint)
                else:
                    if self._paged and self._page_wait:
                        # Page-starved requests re-admit ahead of the
                        # queue the moment retirements free pages — FIFO
                        # with QoS off, WFQ order (victims first) with it
                        # on. They already passed grammar routing once,
                        # and re-routing below keeps them correct if the
                        # installed grammar changed meanwhile.
                        req = self._page_wait_pop()
                    elif self._qos:
                        # WFQ admission (ISSUE 18): stage every queued
                        # submit in the ready pool, serve the smallest
                        # virtual finish time. QoS off takes the exact
                        # pre-QoS get_nowait path below.
                        self._drain_ready()
                        req = self._ready_pop()
                        if req is None:
                            break
                    else:
                        try:
                            req = self._queue.get_nowait()
                        except queue.Empty:
                            break
                        if req is None:
                            continue
                    c = req.constraint
                    if c is not None and (not self._grammar_matches(c)
                                          or wait):
                        if self._constrained_busy() or wait:
                            wait.append(req)
                            continue
                        self._install_constraint(c)
                if not self._admit(self._free_slots()[0], req):
                    # Paged: the pool cannot hold this request's envelope
                    # until live slots retire — park it at the FRONT of
                    # the page-wait line (admission order preserved) and
                    # stop admitting; decode/harvest below keep the pipe
                    # moving and will free pages.
                    self._page_wait.appendleft(req)
                    break
            # Unified ragged round (LSOT_RAGGED=1, ISSUE 19): fold this
            # iteration's prompt chunks INTO the decode launch — one
            # compiled program, no phase alternation, the off-phase
            # never idles. Falls through to the alternating path when
            # every queued prefill entry was stale, so decode never
            # stalls behind an empty mix.
            if self._ragged and self._prefill_q:
                if self._profile_arm is not None:
                    self._maybe_start_profile()
                if self._issue_mixed():
                    if len(self._pending) > self._harvest_lag:
                        self._harvest_round()
                    continue
            # Fair interleave: at most one prompt chunk per decode round —
            # admission work is bounded, so active slots never wait longer
            # than one prompt_bucket forward.
            if self._prefill_q:
                self._prefill_step()
            if self._handoff_pending:
                # Prefill-role terminal step: commit first tokens, pack
                # blobs, wake the pool's placement pump (mixed/decode
                # replicas never queue anything here).
                self._pack_handoffs()
            if any(r is not None and r.ready for r in self._slot_req):
                if self._profile_arm is not None:
                    # Armed /debug/profile capture: start the device trace
                    # on THIS thread, bracketing the next N rounds.
                    self._maybe_start_profile()
                self._issue_decode()
                if len(self._pending) > self._harvest_lag:
                    self._harvest_round()
            elif not self._prefill_q:
                # Nothing left to issue: drain in-flight rounds and any
                # unridden first tokens, then wait for new requests.
                while self._pending:
                    self._harvest_round()
                self._harvest_firsts()
                if self._prefill_q or self._constraint_wait or any(
                    r is not None for r in self._slot_req
                ) or (self._paged and self._page_wait) or self._ready:
                    continue  # harvests freed work — go admit/issue again
                try:
                    req = self._queue.get(timeout=0.05)
                    if req is not None:
                        # Fully idle here (no slots, no waiters), so a new
                        # grammar can install immediately.
                        c = req.constraint
                        if c is not None and not self._grammar_matches(c):
                            self._install_constraint(c)
                        if not self._admit(self._free_slots()[0], req):
                            # Paged + fully idle: can only mean the pool
                            # itself is smaller than one request envelope
                            # after eviction — park it like the loop does.
                            self._page_wait.appendleft(req)
                except queue.Empty:
                    pass


@dataclasses.dataclass
class _ReplicaState:
    """One replica's supervision state inside a SchedulerPool fleet.

    `state` lifecycle: ready → (crash/stall) → restarting → ready |
    degraded | dead, plus the runtime-ops states draining (drain_replica
    in progress) / drained (drained, restartable) / removed
    (remove_replica: permanently out of the fleet). Placement considers
    only ready/degraded replicas; `degraded` means "restarted, not yet
    proven by a clean completion" and clears on the next success placed
    there."""

    label: str
    state: str = "ready"
    restarts: int = 0
    stalls: int = 0
    placements: int = 0
    restart_eta: Optional[float] = None
    last_crash: Optional[str] = None
    #: Multi-model axis (ISSUE 16) beside phase_role: which registered
    #: checkpoint this replica holds ("" = the single-model fleet).
    #: Captured at wiring time so placement can filter on it even while
    #: the scheduler object is mid-restart-swap.
    model_id: str = ""

    #: States a replica can take new work in.
    PLACEABLE = ("ready", "degraded")


class SchedulerPool:
    """dp>1 for continuous batching: a supervised FLEET of independent
    scheduler replicas behind one `submit()`.

    The slot axis can't shard over a mesh "dp" axis (slots are dynamically
    indexed per request), so data parallelism is request-level: each replica
    owns its own params placement — typically a disjoint tp-submesh of the
    same slice. This is the scale-out story SURVEY.md §2.4 calls "DP /
    request-level parallelism", played by scheduler replicas instead of
    Ollama instances.

    Fleet semantics (ISSUE 9 — what turns "a scheduler" into "a fleet"):

    - **Load-aware placement.** `submit()` routes each request to the
      least-loaded placeable replica, scored by the SAME queue-depth ×
      service-time EWMA the Retry-After hint quotes shed clients
      (`backlog_score()`: unclamped seconds estimate, token-weighted
      backlog as the tie-break). Replicas that are restarting, draining,
      dead, or crashed are skipped; replicas whose backlog estimate would
      blow the request's own deadline are skipped too. A request is shed
      typed — Overloaded/429 or DeadlineExceeded/504 — only when NO
      replica can serve it, with the honest minimum Retry-After across
      the fleet (one full replica no longer rejects while a sibling has
      room). `router="round_robin"` keeps the pre-fleet blind rotation
      (the bench's comparison baseline).
    - **Per-replica lifecycle.** With a `factory` (index → fresh replica),
      each replica carries its own supervision state (`_ReplicaState`):
      a crash or watchdog-flagged stall escalates to a TARGETED restart —
      bounded-backoff rebuild of that one replica under a per-replica
      restart budget — while siblings keep serving uninterrupted. Budget
      exhausted marks only that replica `dead`. The `on_replica_restart`/
      `on_replica_drained` callbacks are the supervisor's replay seam:
      a SupervisedScheduler wrapping this pool re-places ONLY the wedged
      replica's journaled requests (serve/supervisor.py), so one bad
      replica no longer restarts — and replays — the whole fleet.
    - **Runtime drain/remove.** `drain_replica()` takes one replica out
      of rotation at runtime: its queued-not-yet-admitted requests
      re-place onto the least-loaded siblings (never shed), in-flight
      work gets a bounded grace, then the replica shuts down. SIGTERM
      semantics at the POOL level are unchanged — `shutdown()`/the
      supervisor's drain still govern whole-process exit.
    - **Observable.** Placement decisions and replica lifecycle events
      land in a pool-level flight recorder (merged into
      `flight_snapshot()`), per-replica health in `health()` /
      `replica_loads()` (Prometheus picks the numeric fields up under
      the shared `r{i}` label vocabulary), and per-replica stall
      verdicts in `heartbeat.verdicts()` / `stalled_replicas()`.
    """

    #: Duck-typing flag the supervisor keys targeted restart/replay on.
    @property
    def supports_replica_restart(self) -> bool:
        return self._factory is not None

    def __init__(
        self,
        schedulers: Sequence[ContinuousBatchingScheduler],
        factory: Optional[Callable] = None,
        max_restarts: int = 5,
        restart_policy=None,
        rng=None,
        sleep: Callable[[float], None] = time.sleep,
        router: str = "least_loaded",
        replica_join_s: float = 1.0,
        # Cache-aware routing (ISSUE 15): consume `prefix_affinity` in
        # the placement order — affinity → pressure penalty → weighted
        # least-loaded tie-break. None reads LSOT_POOL_AFFINITY (default
        # ON); 0/False reproduces the pre-affinity order bit for bit
        # (no digest lookups, no affinity flight events).
        affinity_routing: Optional[bool] = None,
        # Heterogeneous replica weights: replica i's serving capacity
        # relative to its siblings (a tp=4 replica takes proportionally
        # more token mass than a tp=1 sibling — its backlog is DIVIDED
        # by its weight before comparison). None reads
        # LSOT_REPLICA_WEIGHTS ("4,1,1" by index); all-1.0 (the default)
        # is bit-identical to the unweighted order.
        weights: Optional[Sequence[float]] = None,
        # Remote-replica lease (serve/remote.py): ping every transport
        # replica each `lease_s`; `lease_misses` consecutive failures
        # expire the lease — the replica is declared unreachable and its
        # journaled work re-places on siblings. None reads LSOT_LEASE_S /
        # LSOT_LEASE_MISSES; lease_s <= 0 disables the monitor.
        lease_s: Optional[float] = None,
        lease_misses: Optional[int] = None,
        # Multi-model routing (ISSUE 16): requests naming a model_id are
        # placed only on replicas carrying that checkpoint (model →
        # affinity → pressure → weighted least-loaded). None reads
        # LSOT_POOL_MODELS (default ON); 0/False — or requests that
        # never name a model — reproduce the single-model placement
        # order bit for bit.
        model_routing: Optional[bool] = None,
    ):
        if not schedulers:
            raise ValueError("SchedulerPool needs at least one scheduler")
        if router not in ("least_loaded", "round_robin"):
            raise ValueError(
                f"router must be 'least_loaded' or 'round_robin', got "
                f"{router!r}"
            )
        import random as _random

        from .resilience import RetryPolicy

        self.schedulers = list(schedulers)
        self._rr = 0
        self._lock = threading.Lock()
        self._closed = False
        self.router = router
        # Targeted-restart machinery: `factory` builds replacement replica
        # i on demand — either `factory(i)` (per-replica meshes/placement)
        # or `factory()` when it takes no required argument. None disables
        # per-replica restart (a crashed replica is marked dead and
        # skipped, the pre-fleet behavior).
        self._factory = factory
        self._factory_takes_index = False
        if factory is not None:
            import inspect

            try:
                params = inspect.signature(factory).parameters.values()
                self._factory_takes_index = any(
                    p.default is inspect.Parameter.empty
                    and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                    for p in params
                )
            except (TypeError, ValueError):
                self._factory_takes_index = False
        self.max_restarts = int(max_restarts)
        self._restart_policy = restart_policy or RetryPolicy(
            max_attempts=self.max_restarts + 1, base_delay_s=0.1,
            max_delay_s=5.0,
        )
        self._rng = rng if rng is not None else _random.Random()
        self._sleep = sleep
        # Bounded join for a wedged replica's teardown: a targeted restart
        # must not block its driver for the length of the hang it is
        # recovering from (the abandoned daemon zombie exits when it
        # unwedges — same contract as the supervisor's teardown).
        self._replica_join_s = float(replica_join_s)
        # Replay seams for a wrapping SupervisedScheduler: called with the
        # replica LABEL after a targeted restart swap / a drain shutdown,
        # so the supervisor re-places exactly that replica's journaled
        # requests onto the (now current) fleet.
        self.on_replica_restart: Optional[Callable[[str], None]] = None
        self.on_replica_drained: Optional[Callable[[str], None]] = None
        # Attribute each replica's flight records: a pool's merged
        # postmortem/debug view must say WHICH replica's rounds these were
        # (the load-signal feed the multi-replica ROADMAP item needs).
        # "r{i}" matches the single-scheduler recorder default ("r0") and
        # the Prometheus exposition's per-replica label scheme, so the
        # histogram and serving-gauge families join on `replica`.
        self._states: List[_ReplicaState] = []
        for i, s in enumerate(self.schedulers):
            label = f"r{i}"
            fl = getattr(s, "flight", None)
            if fl is not None:
                fl.replica = label
            self._states.append(_ReplicaState(
                label=label, model_id=self._model_id(s)))
            # Disaggregation (ISSUE 13): a prefill-role replica's packed
            # handoffs drain through the pool's phase-aware placement
            # pump (re-wired after every restart swap).
            self._wire_handoff(i, s)
        # Pool-level black box: placement decisions + replica lifecycle
        # events (restart/drain/dead), merged into flight_snapshot() so
        # the postmortem timeline shows WHERE every request went and what
        # the fleet did about failures.
        self._pool_flight = FlightRecorder(capacity=256, replica="pool")
        # Cache-aware routing flip (ISSUE 15): ON by default — the PR-14
        # feed (resident digests + hit-rate EWMAs) is now consumed by
        # submit(); LSOT_POOL_AFFINITY=0 restores the pre-affinity
        # placement order bit for bit.
        if affinity_routing is None:
            affinity_routing = os.environ.get(
                "LSOT_POOL_AFFINITY", "1").strip().lower() not in (
                    "0", "false", "no", "off")
        self._affinity = bool(affinity_routing)
        self._aff_checked = 0
        self._aff_hits = 0
        # Multi-model routing flip (ISSUE 16): ON by default, but inert
        # until a request names a model_id — LSOT_POOL_MODELS=0 makes
        # even named requests fall through to the model-blind order.
        if model_routing is None:
            model_routing = os.environ.get(
                "LSOT_POOL_MODELS", "1").strip().lower() not in (
                    "0", "false", "no", "off")
        self._model_routing = bool(model_routing)
        # Per-model throughput attribution (model_stats): last observed
        # (wall, tokens_total) per model, so successive scrapes read a
        # live tok/s without a sampling thread.
        self._model_rate: Dict[str, Tuple[float, int]] = {}
        # Heterogeneous replica weights: capacity multipliers by index
        # (missing entries default 1.0; weights must be positive).
        if weights is None:
            self._weights = parse_replica_weights(
                os.environ.get("LSOT_REPLICA_WEIGHTS", ""),
                len(self.schedulers),
            )
        else:
            # Same pad/validate policy as the env-spec path — an
            # overlong explicit list raises instead of silently
            # truncating a misconfigured fleet.
            self._weights = normalize_replica_weights(
                list(weights), len(self.schedulers))
        # Remote-replica lease monitor (serve/remote.py): started lazily
        # at start() when any replica exposes the lease surface.
        self._lease_s = (float(os.environ.get("LSOT_LEASE_S", "2.0"))
                         if lease_s is None else float(lease_s))
        self._lease_misses = (int(os.environ.get("LSOT_LEASE_MISSES", "3"))
                              if lease_misses is None
                              else int(lease_misses))
        self._lease_stop = threading.Event()
        self._lease_thread: Optional[threading.Thread] = None
        # Live targeted-restart driver threads: shutdown() joins them so
        # a pool teardown racing a rebuild does not leave a daemon
        # thread inside an XLA compile when the process exits (a C++
        # abort at interpreter teardown, seen in the chaos suites).
        self._restart_threads: List[threading.Thread] = []
        # Elastic fleet membership (ISSUE 17): lifecycle counters +
        # push-handoff latency ledger behind fleet_stats()/lsot_fleet_*,
        # plus the constraint-resolver seam a pushed constrained handoff
        # needs when its target is a remote transport (the wire carries
        # the spec; the receiving client rebuilds the matcher).
        self.constraint_resolver: Optional[Callable] = None
        self._fleet_joins = 0
        self._fleet_retires = 0
        self._fleet_drain_s_sum = 0.0
        self._fleet_drain_count = 0
        self._push_lat = deque(maxlen=4096)
        # Indices the autoscaler added — only these are eligible for
        # scale-down, so an operator-configured replica never retires.
        self._elastic: set = set()
        # Startup handshake (ISSUE 17): a remote joiner whose page
        # geometry / model set cannot co-serve this fleet is marked dead
        # BEFORE placement can route a request into it.
        for i, s in enumerate(self.schedulers):
            self._validate_join(i, s)

    # Admission-arithmetic surface, so SchedulerBackend can wrap a pool the
    # same way it wraps one scheduler (replicas are homogeneous: same cfg,
    # window, chunking — submit() re-validates on the chosen replica).
    @property
    def cfg(self):
        return self.schedulers[0].cfg

    @property
    def max_seq(self) -> int:
        return self.schedulers[0].max_seq

    @property
    def decode_chunk(self) -> int:
        return self.schedulers[0].decode_chunk

    @property
    def stop_ids(self):
        return self.schedulers[0].stop_ids

    @property
    def _spec_draft(self) -> int:
        # Replicas are homogeneous; SchedulerBackend's constrain guard
        # reads this through the pool exactly like a single scheduler.
        return self.schedulers[0]._spec_draft

    @property
    def prompt_bucket(self) -> int:
        return self.schedulers[0].prompt_bucket

    @property
    def _harvest_lag(self) -> int:
        return self.schedulers[0]._harvest_lag

    @property
    def overshoot(self) -> int:
        return self.schedulers[0].overshoot

    def retry_after_hint(self) -> float:
        """Soonest-available replica's hint, restart-aware: min over
        PLACEABLE replicas' queue-drain estimates, with a RESTARTING
        replica contributing its restart-backoff remaining instead of
        its stale EWMA over a frozen queue (the per-replica twin of the
        PR-5 supervisor clamp — before this fix a restarting replica's
        frozen estimate could drive the pool-wide minimum). Draining,
        dead, and removed replicas contribute nothing: they are never
        coming back for this client."""
        now = time.monotonic()
        hints: List[float] = []
        for st, s in self._replica_items():
            if st.state in _ReplicaState.PLACEABLE:
                if getattr(s, "_crash", None) is not None:
                    continue
                hint = getattr(s, "retry_after_hint", None)
                try:
                    hints.append(hint() if callable(hint) else 1.0)
                except Exception:  # noqa: BLE001 — a dying replica mid-read
                    hints.append(1.0)
            elif st.state == "restarting":
                eta = st.restart_eta
                rem = (eta - now) if eta is not None else 1.0
                hints.append(float(min(60.0, max(1.0, rem))))
        if not hints:
            return 1.0
        # Same [1, 60] s clamp as the per-scheduler estimate, so a
        # duck-typed replica's raw hint can't quote sub-second retries.
        return float(min(60.0, max(1.0, min(hints))))

    def warmup(self, prompt_len=None) -> None:
        for s in self.schedulers:
            warm = getattr(s, "warmup", None)
            if callable(warm):
                warm(prompt_len)

    @property
    def heartbeat(self) -> CombinedHeartbeat:
        """Monitor view over the replicas' heartbeats: one wedged replica
        reads stale (oldest busy age) even while its siblings stamp, so
        the supervisor's watchdog covers pools with the same code path.
        Labeled with the replica vocabulary, so `verdicts()` (and the
        snapshot's replicas list) attribute staleness to the replica
        that went quiet — the targeted-restart feed."""
        hbs, labels = [], []
        for st, s in zip(self._states, self.schedulers):
            hb = getattr(s, "heartbeat", None)
            if hb is not None:
                hbs.append(hb)
                labels.append(st.label)
        if not hbs:
            # All-duck-typed fleet with no liveness stamps: None, so the
            # supervisor's `getattr(inner, "heartbeat", None)` callers
            # degrade to no-monitoring instead of a ValueError from an
            # empty CombinedHeartbeat.
            return None
        return CombinedHeartbeat(hbs, labels=labels)

    @property
    def watchdog_stats(self) -> Dict[str, object]:
        hb = self.heartbeat
        return {
            "heartbeat": hb.snapshot() if hb is not None else None,
            "slots_retired_stalled": sum(
                getattr(s, "_slot_stalls", 0) for s in self.schedulers
            ),
        }

    @property
    def page_stats(self) -> Optional[Dict[str, int]]:
        """Summed paged-KV pool stats across replicas (None when no
        replica is paged) — each replica owns an independent pool, so
        totals add."""
        per = [s.page_stats for s in self.schedulers
               if getattr(s, "page_stats", None)]
        if not per:
            return None
        out: Dict[str, int] = {}
        for st in per:
            for k, v in st.items():
                if isinstance(v, str):
                    continue  # non-numeric knobs (kv_quant) keep-first below
                out[k] = out.get(k, 0) + int(v)
        # Ratios/sizes/knobs/thresholds don't sum: keep the first
        # replica's values (homogeneous fleets; heterogeneous knobs show
        # per replica in replica_loads — a summed watermark compared
        # against summed free pages would misread per-pool pressure).
        for k in ("page_size", "overcommit", "spill", "kv_quant",
                  "page_bytes", "watermark_low_pages",
                  "watermark_high_pages"):
            if k in per[0]:
                out[k] = per[0][k]
        return out

    @property
    def perf_stats(self) -> Optional[Dict[str, object]]:
        """Per-replica roofline ledgers (utils/perfmodel.py), labeled —
        the Prometheus lsot_mfu/lsot_hbm_util gauges render phase ×
        replica from this list. None when no replica ledgers (duck-typed
        toy fleets)."""
        per = []
        for st, s in self._replica_items():
            p = getattr(s, "perf_stats", None)
            if isinstance(p, dict):
                rec = dict(p)
                rec["replica"] = st.label
                per.append(rec)
        return {"replicas": per} if per else None

    def profile_rounds(self, rounds: Optional[int] = None,
                       out_dir: Optional[str] = None,
                       replica: Optional[str] = None) -> Dict[str, object]:
        """Arm an on-demand device capture on ONE replica (the named one,
        else the first placeable) — the process-wide guard in
        utils/traceprof already enforces at most one capture in flight
        across the whole fleet."""
        for st, s in self._replica_items():
            if replica is not None and st.label != replica:
                continue
            fn = getattr(s, "profile_rounds", None)
            if callable(fn) and (replica is not None
                                 or st.state in _ReplicaState.PLACEABLE):
                return fn(rounds, out_dir)
        raise ValueError(
            f"no {'replica ' + replica if replica else 'placeable replica'}"
            f" exposes device profiling"
        )

    def profile_status(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for st, s in self._replica_items():
            fn = getattr(s, "profile_status", None)
            if callable(fn):
                out[st.label] = fn()
        return out

    @property
    def flight(self):
        """First replica's recorder (single-scheduler duck typing);
        flight_snapshot() is the merged pool view."""
        return self.schedulers[0].flight

    def flight_snapshot(self, last: Optional[int] = None) -> List[Dict]:
        """All replicas' flight records merged in time order — each
        record carries its replica label, so the pool view attributes
        every round to the replica that ran it. The pool's own recorder
        (placement decisions, replica restart/drain/dead lifecycle) rides
        the merge under the "pool" label."""
        return merge_snapshots([self._pool_flight, *self.schedulers], last)

    def flight_stats(self) -> Dict[str, Dict]:
        """Per-replica ring occupancy for /metrics: without this seam the
        backend's duck-typed `.flight` read would surface replica 0's
        counters only, hiding r1..rN's fill/overwrite on a dp>1 pool."""
        out: Dict[str, Dict] = {}
        for i, s in enumerate(self.schedulers):
            fl = getattr(s, "flight", None)
            if fl is not None:
                out[getattr(fl, "replica", f"r{i}")] = fl.stats()
        return out

    def replica_loads(self) -> List[Dict[str, object]]:
        """Per-replica load + lifecycle attribution (queue depth, live
        slots, round cadence, supervision state, restart/stall/placement
        counters, the live placement score): the feed the least-loaded
        router consumes, exported per replica under the shared `r{i}`
        label vocabulary (numeric fields become Prometheus gauges)."""
        out = []
        for st, s in self._replica_items():
            hb = getattr(s, "heartbeat", None)
            hb_snap = hb.snapshot() if hb is not None else {}
            secs, toks = self._score(s)
            q = getattr(s, "_queue", None)
            slot_req = getattr(s, "_slot_req", None) or []
            rec: Dict[str, object] = {
                "replica": st.label,
                "state": st.state,
                "num_slots": getattr(s, "num_slots", 0),
                "expected_round_s": hb_snap.get("expected_round_s"),
                "crashed": getattr(s, "_crash", None) is not None,
                "restarts": st.restarts,
                "stalls": st.stalls,
                "placements": st.placements,
                "backlog_s": round(secs, 4),
                "pending_new_tokens": toks,
            }
            # Queue depth / live slots: read in-process when the replica
            # is local; a socket transport has neither attribute, so the
            # keys stay unset here and the loads-digest merge below fills
            # them from the worker's piggybacked numbers — the elastic
            # autoscaler's queue-EWMA signal (serve/elastic.py) must see
            # REMOTE decode backlog, not a shadowing local zero.
            if q is not None:
                rec["queued"] = q.qsize()
            if slot_req:
                rec["active_slots"] = sum(
                    1 for r in slot_req if r is not None)
            hint = getattr(s, "retry_after_hint", None)
            if callable(hint) and st.state in _ReplicaState.PLACEABLE:
                try:
                    rec["retry_after_s"] = round(hint(), 3)
                except Exception:  # noqa: BLE001 — a dying replica mid-read
                    pass
            # Paged-KV pressure gauges under the shared r{i} label
            # vocabulary (numeric fields become per-replica Prometheus
            # gauges): which replica is preempting/evicting, and how
            # close each pool is to its watermarks.
            pstats = getattr(s, "page_stats", None)
            if pstats:
                rec["kv_pages_free"] = pstats["pages_free"]
                rec["kv_pages_withheld"] = pstats["pages_withheld"]
                rec["kv_preemptions"] = pstats["preemptions"]
                rec["kv_evictions"] = pstats["evictions"]
                rec["kv_spilled_pages"] = pstats["spilled_pages"]
                rec["kv_watermark_low_pages"] = \
                    pstats["watermark_low_pages"]
                rec["kv_watermark_high_pages"] = \
                    pstats["watermark_high_pages"]
            # Prefix-cache residency feed (ISSUE 14): the replica's live
            # hit-rate EWMA (a numeric gauge under the shared r{i} label
            # vocabulary) and its hottest-K resident digest set (JSON
            # only — strings never become Prometheus samples). This is
            # the per-replica half of the cache-aware routing feed;
            # prefix_affinity() is the lookup over it.
            ptel = getattr(s, "prefix_telemetry", None)
            if isinstance(ptel, dict):
                rec["prefix_hit_rate"] = ptel.get("hit_rate_ewma", 0.0)
                rec["prefix_resident_entries"] = \
                    ptel.get("resident_entries", 0)
            digs = getattr(s, "resident_digests", None)
            if callable(digs):
                try:
                    # No explicit limit: the replica's own configured
                    # top-K bound applies, so this export and
                    # prefix_affinity() see the SAME resident set.
                    rec["resident_digests"] = digs()
                except Exception:  # noqa: BLE001 — a dying replica mid-read
                    pass
            # Disaggregation (ISSUE 13): which phase this replica serves
            # and its handoff traffic — the router's placement feed and
            # the per-replica lsot_serving_* gauges.
            rec["phase_role"] = self._phase_role(s)
            # Multi-model axis (ISSUE 16): which checkpoint the replica
            # holds — the model router's placement feed, carried beside
            # phase_role in loads/health views (and across the remote
            # transport via describe_scheduler's digest).
            rec["model_id"] = st.model_id or self._model_id(s)
            ho = getattr(s, "handoff_stats", None)
            if isinstance(ho, dict):
                rec["handoff_exports"] = ho["exports"]
                rec["handoff_imports"] = ho["imports"]
                rec["handoff_queued"] = ho["queued_handoffs"]
            # Roofline + SLO placement signals (ISSUE 12): the replica's
            # live decode roofline position and whether its rolling SLO
            # is burning — the columns the phase-aware router consumes
            # (decode_hbm_util is _decode_pressure's feed), exported per
            # replica like every other numeric field here.
            perf = getattr(s, "perf_stats", None)
            if isinstance(perf, dict):
                dec = (perf.get("phases") or {}).get("decode")
                if dec:
                    rec["decode_mfu"] = dec.get("mfu")
                    rec["decode_hbm_util"] = dec.get("hbm_util")
            try:
                from ..utils import slo as _slo

                if _slo.ENGINE.enabled:
                    rec["slo_burning"] = bool(
                        _slo.ENGINE.replica_burning(st.label)
                    )
            except Exception:  # noqa: BLE001 — placement view best-effort
                pass
            # Remote replicas (ISSUE 15): a socket transport has no
            # in-process attributes to read — merge its cached loads
            # digest (refreshed by every lease ping / rpc ack) without
            # overwriting anything read directly above.
            ld = getattr(s, "loads_digest", None)
            if callable(ld):
                try:
                    for k, v in ld().items():
                        rec.setdefault(k, v)
                except Exception:  # noqa: BLE001 — a dying replica mid-read
                    pass
            # Key-presence contract: every record carries the load pair
            # even when neither the local read nor the digest had it.
            rec.setdefault("queued", 0)
            rec.setdefault("active_slots", 0)
            # Transport attribution: which wire this replica is behind
            # and how it is behaving (rpc/retry/timeout totals, lease
            # state) — the per-replica half of serving.transport.
            ts = getattr(s, "transport_stats", None)
            if callable(ts):
                try:
                    rec["transport"] = self._transport_summary(ts())
                except Exception:  # noqa: BLE001 — a dying replica mid-read
                    pass
            idx = next((j for j, x in enumerate(self._states) if x is st),
                       -1)
            if 0 <= idx < len(self._weights) \
                    and self._weights[idx] != 1.0:
                rec["weight"] = self._weights[idx]
            out.append(rec)
        return out

    @staticmethod
    def _transport_summary(t: Dict[str, object]) -> Dict[str, object]:
        """Flatten one transport's stats into the compact per-replica
        block replica_loads()/replica_health()//healthz carry."""
        eps = t.get("endpoints") or {}
        total = {"rpcs": 0, "retries": 0, "timeouts": 0, "errors": 0}
        for rec in eps.values():
            for k in total:
                total[k] += int(rec.get(k, 0))
        return {
            "kind": t.get("kind", "transport"),
            "unreachable": bool(t.get("unreachable", False)),
            "lease_misses": int(t.get("lease_misses", 0)),
            "lease_expiries": int(t.get("lease_expiries", 0)),
            "reconnects": int(t.get("reconnects", 0)),
            **total,
        }

    @property
    def transport_stats(self) -> Optional[Dict[str, object]]:
        """Per-replica transport counters, labeled (the serving.transport
        payload the lsot_transport_* Prometheus families render). None
        when no replica is behind a transport — in-process fleets pay
        nothing."""
        per = []
        for st, s in self._replica_items():
            fn = getattr(s, "transport_stats", None)
            if not callable(fn):
                continue
            try:
                rec = dict(fn())
            except Exception:  # noqa: BLE001 — a dying replica mid-read
                continue
            rec["replica"] = st.label
            per.append(rec)
        return {"replicas": per} if per else None

    def start(self) -> "SchedulerPool":
        with self._lock:
            self._closed = False
        for st, s in zip(self._states, self.schedulers):
            if st.state != "removed":
                s.start()
        self._maybe_start_lease()
        return self

    # ------------------------------------------------ remote-replica lease

    @staticmethod
    def _leaseable(s) -> bool:
        return bool(getattr(s, "supports_lease", False)) and callable(
            getattr(s, "ping", None))

    def _maybe_start_lease(self) -> None:
        """Spawn the lease monitor iff any replica is a transport
        (serve/remote.py): in-process scheduler fleets have the
        watchdog's heartbeat as their liveness authority and pay
        nothing here."""
        if self._lease_s <= 0 or self._lease_thread is not None:
            return
        if not any(self._leaseable(s) for s in self.schedulers):
            return
        self._lease_stop = threading.Event()
        self._lease_thread = threading.Thread(
            target=self._lease_loop, daemon=True, name="lsot-pool-lease",
        )
        self._lease_thread.start()

    def _lease_loop(self) -> None:
        """Per-replica heartbeat LEASE over the transports: ping each
        placeable transport replica every `lease_s`; `lease_misses`
        consecutive failures expire the lease — the transport is marked
        unreachable (pending futures fail typed, streams gate shut) and
        `_note_replica_crash` kicks the targeted restart, whose
        `on_replica_restart` callback re-places the journaled work on
        siblings via the supervisor's existing fleet replay. A dead or
        partitioned host loses zero acknowledged requests."""
        while not self._lease_stop.wait(self._lease_s):
            with self._lock:
                if self._closed:
                    return
                items = [(i, st, self.schedulers[i])
                         for i, st in enumerate(self._states)
                         if st.state in _ReplicaState.PLACEABLE]
            for i, st, s in items:
                if not self._leaseable(s):
                    continue
                try:
                    s.ping(timeout=self._lease_s)
                except Exception as e:  # noqa: BLE001 — any failure is a miss
                    miss_fn = getattr(s, "lease_miss", None)
                    misses = (miss_fn() if callable(miss_fn)
                              else self._lease_misses)
                    self._pool_flight.event("lease_miss", replica=st.label,
                                            misses=misses)
                    if misses < self._lease_misses:
                        continue
                    exc = None
                    mark = getattr(s, "mark_unreachable", None)
                    if callable(mark):
                        exc = mark(
                            f"lease expired after {misses} missed "
                            f"beat(s): {e}"
                        )
                    if exc is None:
                        from .remote import ReplicaUnreachable

                        exc = ReplicaUnreachable(
                            f"replica {st.label} lease expired after "
                            f"{misses} missed beat(s): {e}"
                        )
                    resilience.inc("lease_expiries")
                    self._pool_flight.event("lease_expired",
                                            replica=st.label,
                                            misses=misses)
                    _log.warning("replica %s lease expired (%d misses)",
                                 st.label, misses)
                    self._note_replica_crash(i, exc)
                else:
                    ok_fn = getattr(s, "lease_ok", None)
                    if callable(ok_fn):
                        ok_fn()

    def shutdown(self, timeout: Optional[float] = None) -> None:
        # _closed stops any in-flight replica-restart driver from swapping
        # a fresh replica into a pool that is going away.
        with self._lock:
            self._closed = True
        self._lease_stop.set()
        for st, s in zip(self._states, self.schedulers):
            if s is None:
                continue
            try:
                s.shutdown(timeout=timeout)
            except Exception:  # noqa: BLE001 — one corpse must not wedge the rest
                _log.exception("replica %s shutdown failed", st.label)
        # Join in-flight restart drivers: `_closed` makes each exit at
        # its next checkpoint (discarding any fresh replica it built),
        # but a driver can be seconds deep in a rebuild's XLA compiles —
        # abandoning it leaves a daemon thread inside native code when a
        # short-lived process (tests, the chaos harness) exits. The same
        # `timeout` bound callers pass for replica teardown applies; a
        # driver that cannot finish inside it is abandoned like a wedged
        # replica join.
        with self._lock:
            drivers = list(self._restart_threads)
        for t in drivers:
            if t is not threading.current_thread():
                t.join(timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()

    # ------------------------------------------------------------ placement

    @staticmethod
    def _score(s) -> Tuple[float, int]:
        """A replica's placement score `(backlog seconds, pending
        tokens)` — the scheduler's own Retry-After math via
        `backlog_score()`, with a queue-depth-only fallback for
        duck-typed replicas (the chaos harness's toy)."""
        fn = getattr(s, "backlog_score", None)
        if callable(fn):
            try:
                secs, toks = fn()
                return float(secs), int(toks)
            except Exception:  # noqa: BLE001 — a dying replica mid-read
                return 0.0, 0
        q = getattr(s, "_queue", None)
        return 0.0, (q.qsize() if q is not None else 0)

    def _wscore(self, i: int, s) -> Tuple[float, float]:
        """Weighted placement ORDERING score: replica i's backlog
        divided by its capacity weight, so a tp=4 replica weighted 4
        looks a quarter as loaded per unit of capacity and takes
        proportionally more token mass. Ordering only — deadline
        feasibility and Retry-After hints always compare the RAW
        seconds estimate (a replica's real backlog is wall-clock no
        matter its capacity weight). Weight 1.0 (the default) returns
        `_score` UNCHANGED — same types, same values — keeping the
        unweighted placement order bit for bit."""
        secs, toks = self._score(s)
        return self._wkey(i, secs, toks)

    def _wkey(self, i: int, secs: float, toks):
        w = self._weights[i] if i < len(self._weights) else 1.0
        if w == 1.0:
            return secs, toks
        return secs / w, toks / w

    def _affinity_scores(self, ids, tenant: str = "") -> Dict[str, int]:
        """The cache-aware routing lookup for one submit (ISSUE 15):
        the request's chain-prefix digests scored against every
        placeable replica's resident set via `prefix_affinity`. Empty
        when routing is off, the prompt is shorter than one block, or
        nobody holds anything — every one of which leaves the placement
        sort exactly where it was. With per-tenant prefix namespacing on
        (ISSUE 18), the lookup salts its digests with the request's
        tenant exactly as replica admission salts its cache keys —
        affinity keeps matching, within one tenant only."""
        block = int(getattr(self.schedulers[0], "_pblock", 0) or 0)
        if not block:
            return {}
        ns: Tuple[int, ...] = ()
        if tenant:
            from .qos import (prefix_tenant_ns_enabled, qos_enabled,
                              tenant_salt)
            if qos_enabled() and prefix_tenant_ns_enabled():
                ns = tenant_salt(tenant)
        digests = prefix_chain_digests(ids, block, ns)
        if not digests:
            return {}
        scored = self.prefix_affinity(digests)
        if not scored:
            return {}
        with self._lock:
            self._aff_checked += 1
        return {str(r["replica"]): int(r["score"]) for r in scored}

    def routing_stats(self) -> Dict[str, object]:
        """The placement layer's own counters (the bench `fleet_routing`
        affinity pass cites these): how many submits had a non-empty
        affinity lookup and how many landed on a best-affinity holder."""
        with self._lock:
            return {
                "router": self.router,
                "affinity_routing": self._affinity,
                "model_routing": self._model_routing,
                "weights": list(self._weights),
                "placements": sum(st.placements for st in self._states),
                "affinity_checked": self._aff_checked,
                "affinity_hits": self._aff_hits,
            }

    def model_stats(self) -> Optional[Dict[str, object]]:
        """Per-model serving aggregation (ISSUE 16): queue depth, live
        slots, accepted-token throughput and KV pages held, summed over
        every replica carrying each model_id — the `serving.models`
        payload behind the `lsot_model_*` Prometheus families. None for
        single-model fleets (no replica carries an id), which keeps the
        pre-multi-model /metrics byte-identical."""
        per: Dict[str, Dict[str, object]] = {}
        for st, s in self._replica_items():
            mid = st.model_id or self._model_id(s)
            if not mid:
                continue
            rec = per.setdefault(mid, {
                "model": mid, "replicas": 0, "placeable": 0,
                "queued": 0, "active_slots": 0,
                "pending_new_tokens": 0, "backlog_s": 0.0,
                "placements": 0, "tokens_total": 0,
                "kv_pages_total": 0, "kv_pages_in_use": 0,
            })
            rec["replicas"] += 1
            if st.state in _ReplicaState.PLACEABLE:
                rec["placeable"] += 1
            secs, toks = self._score(s)
            rec["backlog_s"] = round(rec["backlog_s"] + secs, 4)
            rec["pending_new_tokens"] += toks
            q = getattr(s, "_queue", None)
            rec["queued"] += q.qsize() if q is not None else 0
            slot_req = getattr(s, "_slot_req", None) or []
            rec["active_slots"] += sum(
                1 for r in slot_req if r is not None)
            rec["placements"] += st.placements
            rec["tokens_total"] += int(
                getattr(s, "_tokens_emitted_total", 0) or 0)
            pstats = getattr(s, "page_stats", None)
            if isinstance(pstats, dict):
                rec["kv_pages_total"] += int(
                    pstats.get("pages_total", 0) or 0)
                rec["kv_pages_in_use"] += int(
                    pstats.get("pages_in_use", 0) or 0)
            # Remote carriers: the cached loads digest stands in for
            # the attribute reads a socket transport cannot offer.
            ld = getattr(s, "loads_digest", None)
            if callable(ld):
                try:
                    d = ld()
                    rec["queued"] += int(d.get("queued", 0) or 0)
                    rec["active_slots"] += int(
                        d.get("active_slots", 0) or 0)
                    rec["tokens_total"] += int(
                        d.get("tokens_total", 0) or 0)
                except Exception:  # noqa: BLE001 — a dying replica
                    pass
        if not per:
            return None
        # Scrape-to-scrape tok/s: delta of the monotonic accepted-token
        # counter over the wall between calls (first call reports 0.0).
        now = time.monotonic()
        with self._lock:
            for mid, rec in per.items():
                prev = self._model_rate.get(mid)
                total = int(rec["tokens_total"])
                tok_s = 0.0
                if prev is not None and now > prev[0]:
                    tok_s = max(0.0, (total - prev[1]) / (now - prev[0]))
                self._model_rate[mid] = (now, total)
                rec["tok_s"] = round(tok_s, 3)
        return {"models": sorted(per.values(),
                                 key=lambda r: r["model"])}

    def _replica_items(self, states: Optional[Sequence[str]] = None
                       ) -> List[Tuple["_ReplicaState", object]]:
        """Locked (state, scheduler) snapshot of the fleet, optionally
        filtered by lifecycle state — the ONE place the
        iterate-the-fleet lock discipline lives (retry_after_hint,
        replica_loads, stalled_replicas, replica_health)."""
        with self._lock:
            return [(st, self.schedulers[i])
                    for i, st in enumerate(self._states)
                    if states is None or st.state in states]

    def _placeable(self, exclude: Optional[set] = None) -> List[Tuple[int, "_ReplicaState", object]]:
        """Replicas that can take new work right now: ready/degraded and
        not crashed. Observing a crash here kicks the replica's targeted
        restart (or marks it dead when the pool has no factory) — the
        bare-pool self-healing path; under a supervisor the inner-future
        failure notices it too."""
        out = []
        with self._lock:
            items = [(i, st) for i, st in enumerate(self._states)
                     if st.state in _ReplicaState.PLACEABLE
                     and (exclude is None or i not in exclude)]
            scheds = list(self.schedulers)
        for i, st in items:
            s = scheds[i]
            crash = getattr(s, "_crash", None)
            if crash is not None:
                self._note_replica_crash(i, crash)
                continue
            out.append((i, st, s))
        return out

    @staticmethod
    def _phase_role(s) -> str:
        return getattr(s, "phase_role", "mixed") or "mixed"

    @staticmethod
    def _model_id(s) -> str:
        return str(getattr(s, "model_id", "") or "")

    #: Duck-typing flag: callers (SchedulerBackend, the supervisor) only
    #: forward a model_id to schedulers that understand the axis.
    supports_model_routing = True
    #: Same duck-typing for the tenant/qos axis (ISSUE 18).
    supports_qos = True

    def _wire_handoff(self, idx: int, s) -> None:
        """Point a prefill-role replica's handoff queue at the pool's
        placement pump (idempotent; called at construction and after
        every targeted-restart swap)."""
        if self._phase_role(s) == "prefill" and hasattr(s, "on_handoff"):
            s.on_handoff = partial(self._pump_handoffs, idx)
        # Pushed constrained handoffs (ISSUE 17): the wire carries only
        # the constraint SPEC — the receiving transport rebuilds the
        # matcher through the pool's resolver seam (set by
        # SchedulerBackend; raw fleets may set pool.constraint_resolver
        # directly).
        if (getattr(s, "is_remote", False)
                and getattr(s, "constraint_resolver", "absent") is None):
            s.constraint_resolver = self._fleet_constraint

    def _fleet_constraint(self, spec):
        """Resolver seam for constrained requests re-materialized from
        the wire (pushed handoffs): delegates to whatever the owning
        backend installed, failing typed when nothing did."""
        fn = self.constraint_resolver
        if fn is None:
            raise ValueError(
                "pushed constrained handoff needs a constraint resolver: "
                "set pool.constraint_resolver (SchedulerBackend does this "
                "automatically)"
            )
        return fn(spec)

    def _join_compat(self, s) -> Optional[str]:
        """Startup-handshake compatibility check for a REMOTE joiner
        (ISSUE 17): a pushed KV blob's pages must be importable by every
        decode target, so a joiner whose page geometry disagrees with
        the fleet's — or whose checkpoint no local sibling carries —
        cannot be made placeable. Returns a reason string, or None when
        compatible. Local replicas are trusted: they were built by the
        same factory that built the fleet."""
        if not getattr(s, "is_remote", False):
            return None
        try:
            ref = None
            for other in self.schedulers:
                if other is not s and not getattr(other, "is_remote",
                                                  False):
                    ref = other
                    break
            if ref is None:
                return None  # all-remote fleet: nothing to disagree with
            r_paged = bool(getattr(s, "_paged", False))
            l_paged = bool(getattr(ref, "_paged", False))
            if r_paged != l_paged:
                return (f"paged={r_paged} vs fleet paged={l_paged}")
            r_ps = int(getattr(s, "_page_size", 0) or 0)
            l_ps = int(getattr(ref, "_page_size", 0) or 0)
            if r_paged and r_ps and l_ps and r_ps != l_ps:
                return f"page_size={r_ps} vs fleet page_size={l_ps}"
            want = str(getattr(s, "model_id", "") or "")
            have = {str(self._model_id(other) or "")
                    for other in self.schedulers if other is not s}
            have.discard("")
            if want and have and want not in have:
                return (f"model_id={want!r} not served by this fleet "
                        f"({sorted(have)})")
        except Exception as e:  # noqa: BLE001 — unreachable joiner
            return f"handshake read failed: {e!r}"
        return None

    def _validate_join(self, idx: int, s) -> bool:
        """Run the join handshake for replica `idx`; an incompatible
        joiner is marked dead (never placeable) with the reason in its
        crash slot and a flight event — the pool keeps serving on the
        rest of the fleet."""
        reason = self._join_compat(s)
        if reason is None:
            return True
        st = self._states[idx]
        with self._lock:
            st.state = "dead"
            st.last_crash = f"join rejected: {reason}"
        self._pool_flight.event(
            "replica_join_rejected", replica=st.label, reason=reason)
        return False

    def _penalty(self, st: "_ReplicaState", s) -> int:
        """Pressure-aware placement (ISSUE 13 satellite): deprioritize a
        replica mid-KV-pressure-storm (withheld pool pages — PR-10's
        `kv_pressure` signal) or mid-SLO-burn BEFORE the least-loaded
        tie-break — backlog scores say nothing about a replica that is
        busy preempting victims or already blowing its latency budget.
        Additive, so a replica with both problems sorts after one with
        either; 0 everywhere in a healthy fleet, which keeps the
        pre-disagg placement order bit for bit."""
        pen = 0
        try:
            pstats = getattr(s, "page_stats", None)
            if pstats and int(pstats.get("pages_withheld", 0) or 0) > 0:
                pen += 1
        except Exception:  # noqa: BLE001 — a dying replica mid-read
            pass
        try:
            from ..utils import slo as _slo

            if _slo.ENGINE.enabled \
                    and _slo.ENGINE.replica_burning(st.label):
                pen += 1
        except Exception:  # noqa: BLE001 — placement view best-effort
            pass
        return pen

    @staticmethod
    def _decode_pressure(s) -> float:
        """The live decode-side placement signal (ISSUE 13): the
        replica's decode-phase HBM-bandwidth utilization EWMA from the
        per-round roofline ledger (PR 12) — the closer to the roof, the
        less headroom a migrated request's decode leg has there. 0.0
        for replicas without a ledger (duck-typed fakes)."""
        try:
            perf = getattr(s, "perf_stats", None)
            if isinstance(perf, dict):
                dec = (perf.get("phases") or {}).get("decode")
                if dec and dec.get("hbm_util") is not None:
                    return float(dec["hbm_util"])
        except Exception:  # noqa: BLE001 — a dying replica mid-read
            pass
        return 0.0

    def _pump_handoffs(self, src_idx: int) -> None:
        """Drain one prefill replica's packed handoffs and place each
        onto a decode-capable sibling. Runs on the prefill replica's
        worker thread the moment a blob is packed — placement is a lock
        plus a queue put, so the pump costs the prefill loop
        microseconds, and there is no polling thread to fall behind."""
        src = self.schedulers[src_idx]
        # One drain path (ISSUE 17): a push-capable transport buffers
        # blobs the worker streamed to us — drain that buffer directly.
        # extract_handoffs survives only as the legacy pull RPC for
        # pre-push workers and the drain/reconcile sweep.
        ex = getattr(src, "drain_pushed_handoffs", None)
        if not callable(ex):
            ex = getattr(src, "extract_handoffs", None)
        if not callable(ex):
            return
        for req in ex():
            self._place_handoff(req, src_idx)

    def _place_handoff(self, req, src_idx: int) -> None:
        """Phase-aware placement of ONE migrated request: decode
        replicas first — ordered by the pressure penalty, the live
        decode-phase HBM utilization, then backlog — mixed siblings
        next, the originating prefill replica last (the documented
        "fall back to decoding in place" rule: a decode-side placement
        failure must never lose the request). The remaining deadline is
        budgeted across the handoff: a target whose backlog estimate
        already exceeds it is skipped, so TTFT accounting spans both
        legs."""
        src = self.schedulers[src_idx]
        remaining = (req.deadline.remaining()
                     if req.deadline is not None else None)
        cands = self._placeable()
        # Multi-model fleets (ISSUE 16): a migrated request's KV pages
        # were written by the SOURCE model's weights — a cross-model
        # sibling would decode them into garbage. Same-model targets
        # only; the in-place fallback (the source itself) always
        # matches.
        src_model = self._model_id(src)
        if self._model_routing and src_model:
            cands = [c for c in cands
                     if (c[1].model_id or self._model_id(c[2]))
                     == src_model]

        def ordered(role):
            # Score once per candidate (decorate-sort): backlog_score /
            # penalty reads run on the prefill worker thread, and the
            # sort key must not re-invoke them per comparison pass.
            decorated = []
            for (i, st, s) in cands:
                if self._phase_role(s) != role or s is src:
                    continue
                secs, toks = self._wscore(i, s)
                decorated.append((self._penalty(st, s),
                                  self._decode_pressure(s),
                                  secs, toks, i, st, s))
            decorated.sort(key=lambda t: t[:5])
            return [(i, st, s) for (*_k, i, st, s) in decorated]

        targets = ordered("decode") + ordered("mixed") + [
            (src_idx, self._states[src_idx], src)
        ]
        # Snapshot the event fields BEFORE the target can race us: the
        # importing replica's worker may restore the blob (clearing
        # req.handoff) and requeue reassigns rid the moment rq(req)
        # returns.
        pages = (req.handoff or {}).get("pages", 0)
        t_recv = (req.handoff or {}).get("t_recv")
        rid = req.rid
        starved = 0
        for i, st, s in targets:
            if remaining is not None and s is not src:
                secs, _ = self._score(s)
                if secs >= remaining:
                    continue  # its backlog alone would burn the deadline
            if s is not src:
                # Page-starved targets (ISSUE 17): a decode sibling with
                # zero free pages would park this blob in its page-wait
                # queue — behind the very storm that starved it. Skip it;
                # if EVERY target is starved the failure below is typed
                # Overloaded, not a crash.
                try:
                    pstats = getattr(s, "page_stats", None)
                    if (pstats
                            and int(pstats.get("pages_free", 1) or 0) <= 0):
                        starved += 1
                        continue
                except Exception:  # noqa: BLE001 — dying replica mid-read
                    pass
            rq = getattr(s, "requeue", None)
            if not callable(rq):
                continue
            try:
                rq(req)
            except Exception:  # noqa: BLE001 — crashed/incompatible target
                continue
            with self._lock:
                st.placements += 1
                # Pushed-handoff latency ledger (ISSUE 17): the receiving
                # transport stamps t_recv the moment the blob leaves the
                # wire; placement closes the window lsot_fleet_push
                # latency summaries render.
                if t_recv is not None:
                    try:
                        self._push_lat.append(
                            max(0.0, time.perf_counter() - float(t_recv)))
                    except (TypeError, ValueError):
                        pass
            self._pool_flight.event(
                "handoff_place", to=st.label,
                src=self._states[src_idx].label, rid=rid,
                pages=pages, inplace=s is src,
            )
            return
        if starved:
            # Capacity exhaustion, not a crash: every decode target is
            # page-waiting AND the source could not take it back. Typed
            # backpressure tells the client to retry after the storm.
            req.future.set_exception(Overloaded(
                "every decode target is page-waiting; prefill→decode "
                "handoff rejected under KV pressure"
            ))
            return
        # Not even the (live — we are on its worker thread) source could
        # take it back: fail typed so the supervisor's journal replays it
        # instead of a client hanging on a parked future.
        req.future.set_exception(SchedulerCrashed(
            "no replica could accept a prefill→decode handoff"
        ))

    @property
    def handoff_stats(self) -> Optional[Dict[str, object]]:
        """Per-replica handoff counters (None when no replica has any) —
        the pool-level serving.handoff payload the lsot_handoff_*
        Prometheus families render."""
        per = []
        for st, s in self._replica_items():
            h = getattr(s, "handoff_stats", None)
            if isinstance(h, dict):
                rec = dict(h)
                rec["replica"] = st.label
                per.append(rec)
        return {"replicas": per} if per else None

    def qos_stats(self) -> Optional[Dict[str, object]]:
        """Per-replica WFQ/admission counters (ISSUE 18): None when no
        replica runs QoS — the pre-QoS payload byte-for-byte."""
        per = []
        for st, s in self._replica_items():
            fn = getattr(s, "qos_stats", None)
            if not callable(fn):
                continue
            try:
                qs = fn()
            except Exception:  # noqa: BLE001 — a churning fleet mid-read
                continue
            if qs:
                rec = dict(qs)
                rec["replica"] = st.label
                per.append(rec)
        return {"replicas": per} if per else None

    def submit(self, ids, max_new_tokens: int = 256,
               sampling: SamplingParams = SamplingParams(), seed: int = 0,
               on_token=None, constraint=None, deadline_s=None, trace=None,
               model_id: str = "", tenant: str = "", qos: str = ""):
        """Least-loaded, deadline-aware placement (router="round_robin"
        keeps the pre-fleet rotation): score every placeable replica,
        skip the ones whose backlog would blow this request's deadline,
        and fail over on Overloaded/crash races. A request is shed typed
        only when NO replica can serve it — Overloaded (429) with the
        fleet's minimum Retry-After when placeable replicas are all at
        capacity, DeadlineExceeded (504) when every placeable replica's
        backlog exceeds the deadline, Overloaded-with-backoff when the
        whole fleet is mid-restart, and SchedulerCrashed only when the
        fleet is truly gone.

        Multi-model placement (ISSUE 16): a request naming `model_id`
        considers ONLY replicas carrying that checkpoint — ahead of the
        phase filter, the affinity sort and the load tie-break. Naming a
        model nobody registered fails typed `UnknownModel` (ValueError →
        a 4xx at the API layer, never a scheduler crash); a model whose
        replicas are all mid-drain/restart sheds retryable Overloaded.
        `model_id=""` (all pre-existing callers) or LSOT_POOL_MODELS=0
        skips every model check — the single-model placement order, bit
        for bit."""
        want_model = model_id if (self._model_routing and model_id) else ""
        if want_model:
            with self._lock:
                carriers = [st.state for st in self._states
                            if st.model_id == want_model]
            if not carriers:
                from .modelpool import UnknownModel

                raise UnknownModel(
                    f"no replica in the fleet serves model "
                    f"{want_model!r} (models: "
                    f"{sorted({st.model_id for st in self._states if st.model_id}) or ['<unset>']})"
                )
        last_overloaded: Optional[Overloaded] = None
        deadline_blocked: Optional[float] = None
        tried: set = set()
        while True:
            cands = self._placeable(exclude=tried)
            if want_model:
                cands = [c for c in cands
                         if (c[1].model_id or self._model_id(c[2]))
                         == want_model]
            if not cands:
                break
            # Phase-aware routing (ISSUE 13): NEW requests are prefill
            # work — keep them off decode-role replicas while any
            # prefill/mixed replica can take them (all-decode leftovers
            # still serve rather than shed: roles are routing policy,
            # not capability). All-mixed fleets filter nothing. The
            # filtered-out decode replicas are kept as the deadline
            # spillover tier below.
            spill: List = []
            front = [c for c in cands if self._phase_role(c[2]) != "decode"]
            if front and len(front) < len(cands):
                spill = [c for c in cands
                         if self._phase_role(c[2]) == "decode"]
                cands = front
            if self.router == "round_robin":
                aff: Dict[str, int] = {}
                with self._lock:
                    pick = self._rr % len(cands)
                    self._rr += 1
                order = cands[pick:] + cands[:pick]
                scored = [(self._score(s), i, st, s)
                          for (i, st, s) in order]
            else:
                # Cache-aware, pressure-aware, weighted least-loaded
                # (ISSUE 15): a replica already holding the request's
                # schema-prefix pages sorts FIRST (zero-copy hit instead
                # of a re-prefill — at fleet scale the schema-prefix
                # working set IS the traffic shape), then replicas
                # mid-KV-pressure-storm or mid-SLO-burn sort after
                # healthy ones, then the weighted backlog tie-break.
                # With LSOT_POOL_AFFINITY=0 (no lookup, no events) and
                # all-1.0 weights this is the pre-affinity order bit
                # for bit.
                aff = (self._affinity_scores(ids, tenant)
                       if self._affinity else {})
                # Scores stay RAW (deadline feasibility + the 504 hint
                # below compare wall-clock backlog); the capacity weight
                # applies only inside the ordering key.
                scored = sorted(
                    ((self._score(s), i, st, s) for (i, st, s) in cands),
                    key=lambda t: (-aff.get(t[2].label, 0),
                                   self._penalty(t[2], t[3]),
                                   *self._wkey(t[1], t[0][0], t[0][1]),
                                   t[1]),
                )
            if deadline_s is not None:
                feasible = [t for t in scored if t[0][0] < deadline_s]
                if not feasible and spill:
                    # The prefill/mixed tier can't meet the deadline, but
                    # the decode-role replicas the phase filter set aside
                    # are FULL-capability — serving there beats shedding
                    # a request that still fits its budget somewhere.
                    spilled = sorted(
                        ((self._score(s), i, st, s)
                         for (i, st, s) in spill),
                        key=lambda t: (-aff.get(t[2].label, 0),
                                       self._penalty(t[2], t[3]),
                                       *self._wkey(t[1], t[0][0],
                                                   t[0][1]),
                                       t[1]),
                    )
                    feasible = [t for t in spilled if t[0][0] < deadline_s]
                    scored = scored + spilled
                if not feasible:
                    # Every placeable replica's backlog estimate already
                    # exceeds the budget: admitting anywhere would burn
                    # the deadline in queue. Shed 504 below (unless a
                    # not-yet-tried replica frees up — there is none:
                    # the estimate only grows with this submit).
                    deadline_blocked = min(t[0][0] for t in scored)
                    break
                scored = feasible
            (secs, toks), i, st, sched = scored[0]
            try:
                # The model kwarg rides only model-named submits: every
                # pre-existing replica (and the test fleet's duck-typed
                # fakes) keeps its exact signature on the "" path. Same
                # for the tenant/qos axis (ISSUE 18): forwarded only to
                # replicas that declare `supports_qos`.
                extra = {"model_id": want_model} if want_model else {}
                if (tenant or qos) and getattr(sched, "supports_qos",
                                               False):
                    extra["tenant"] = tenant
                    extra["qos"] = qos
                fut = sched.submit(
                    ids, max_new_tokens=max_new_tokens, sampling=sampling,
                    seed=seed, on_token=on_token, constraint=constraint,
                    deadline_s=deadline_s, trace=trace, **extra,
                )
            except ValueError:
                # Request-shape rejection (oversize prompt): identical on
                # every replica — re-raise rather than spinning the ring.
                raise
            except Overloaded as e:
                # This replica's queue is full; another may have room. Shed
                # (429) only when EVERY placeable replica is at capacity.
                if (last_overloaded is None
                        or e.retry_after_s < last_overloaded.retry_after_s):
                    last_overloaded = e
                tried.add(i)
                continue
            except RuntimeError:
                # Failover only for genuine crashes that landed between the
                # placeable check and submit(); lifecycle misuse ("not
                # started", "has shut down" without a crash) is the
                # caller's bug and its accurate error must propagate.
                crash = getattr(sched, "_crash", None)
                if crash is None:
                    raise
                self._note_replica_crash(i, crash)
                tried.add(i)
                continue
            # Replica attribution for the metrics label set: which
            # replica actually served this submit. Real schedulers
            # already stamped their own label under the submit lock —
            # only fill the gap for duck-typed replicas, so a handoff
            # requeue that migrated the request in the microseconds
            # since submit() returned is never overwritten with the
            # prefill replica's label.
            if getattr(fut, "_lsot_replica", None) is None:
                fut._lsot_replica = st.label
            with self._lock:
                st.placements += 1
                if aff and aff.get(st.label, 0) > 0 \
                        and aff[st.label] == max(aff.values()):
                    # The request landed on a best-affinity holder: the
                    # zero-copy prefix hit the router was built to buy.
                    self._aff_hits += 1
            if st.state == "degraded":
                # A clean completion proves the restarted replica serves.
                def _prove(f, st=st):
                    if f.exception() is None:
                        with self._lock:
                            if st.state == "degraded":
                                st.state = "ready"
                fut.add_done_callback(_prove)
            # Placement decision into the pool black box: where the
            # request went and what the router saw (bounded ring append).
            ev: Dict[str, object] = dict(
                to=st.label, router=self.router,
                backlog_s=round(secs, 4), pending_new_tokens=toks,
                considered=len(cands),
            )
            if aff:
                ev["affinity"] = aff.get(st.label, 0)
            if want_model:
                ev["model"] = want_model
            self._pool_flight.event("placement", **ev)
            return fut
        if want_model and last_overloaded is None \
                and deadline_blocked is None:
            # The model IS registered (the pre-loop check passed) but no
            # carrier is placeable right now: a drain/restart in flight
            # is retryable backpressure; all-dead is the model-scoped
            # fleet death. Re-snapshot — the loop's crash handling may
            # have moved carriers since the pre-loop check.
            with self._lock:
                carriers = [st.state for st in self._states
                            if st.model_id == want_model]
            if any(s in ("restarting", "draining", "drained")
                   for s in carriers):
                raise Overloaded(
                    f"every replica serving model {want_model!r} is "
                    f"draining or restarting",
                    retry_after_s=self.retry_after_hint(),
                )
            raise SchedulerCrashed(
                f"every replica serving model {want_model!r} has "
                f"crashed or left the fleet"
            )
        if last_overloaded is not None:
            # Min Retry-After across the full fleet (restart-aware), not
            # whichever replica happened to shed last.
            raise Overloaded(
                "every scheduler replica is at capacity",
                retry_after_s=min(last_overloaded.retry_after_s,
                                  self.retry_after_hint()),
            )
        if deadline_blocked is not None:
            resilience.inc("deadline_infeasible")
            raise DeadlineExceeded(
                f"no replica can serve within the {deadline_s:.3f}s "
                f"deadline: minimum fleet backlog estimate "
                f"{deadline_blocked:.3f}s"
            )
        with self._lock:
            restarting = any(st.state == "restarting" for st in self._states)
        if restarting:
            # The fleet is mid-restart with nothing placeable: retryable
            # backpressure (the hint carries the backoff remaining), NOT a
            # crash — a supervisor must not tear the whole pool down while
            # its replicas are already being rebuilt.
            raise Overloaded(
                "every scheduler replica is restarting",
                retry_after_s=self.retry_after_hint(),
            )
        # Typed (not a bare RuntimeError): every replica holds a
        # SchedulerCrashed (or is dead/removed), the pool just summarizes
        # — and the supervisor classifies crashes by TYPE, so the
        # fleet-wide death must carry it (a message-string contract would
        # silently break recovery on rewording). Subclasses RuntimeError:
        # existing handlers keep working.
        raise SchedulerCrashed(
            "all scheduler replicas have crashed or left the fleet"
        )

    cancel = staticmethod(ContinuousBatchingScheduler.cancel)

    # --------------------------------------------------- replica lifecycle

    def _resolve_idx(self, replica) -> int:
        if isinstance(replica, int):
            if not 0 <= replica < len(self._states):
                raise ValueError(f"no replica index {replica}")
            return replica
        for i, st in enumerate(self._states):
            if st.label == replica:
                return i
        raise ValueError(f"unknown replica {replica!r}")

    def _note_replica_crash(self, idx: int, exc: BaseException) -> None:
        """A replica's loop died: kick its targeted restart (factory
        pools), or mark it dead and skip it forever (factory-less pools —
        the pre-fleet behavior, now visible in health()). Idempotent per
        episode."""
        with self._lock:
            st = self._states[idx]
            if self._closed or st.state not in _ReplicaState.PLACEABLE:
                return
            st.last_crash = str(exc)[:200]
            if self._factory is None:
                st.state = "dead"
                self._pool_flight.event("replica_dead", replica=st.label,
                                        error=st.last_crash)
                return
            st.state = "restarting"
        resilience.inc("replica_crashes")
        self._pool_flight.event("replica_crash", replica=st.label,
                                error=st.last_crash)
        _log.warning("replica %s crashed; pool restarting it: %s",
                     st.label, exc)
        self._spawn_restart(idx)

    def notice_replica_crash(self, replica, exc: BaseException) -> None:
        """Public crash-notice seam (the supervisor calls it when one of
        its journaled requests' inner futures fails typed with a crash):
        kicks the replica's targeted restart, idempotent per episode."""
        try:
            idx = self._resolve_idx(replica)
        except ValueError:
            return
        self._note_replica_crash(idx, exc)

    def restart_replica(self, replica, reason: str = "manual") -> bool:
        """Targeted restart of ONE replica (the watchdog's stall
        escalation and the operator's manual kick): tear it down with a
        bounded join — a WEDGED loop never joins; the zombie daemon is
        abandoned — and rebuild it from the factory under the replica's
        own bounded-backoff restart budget, while every sibling keeps
        serving untouched. A `drained` replica restarts back into the
        fleet (the re-add path). Returns False when the replica is
        already restarting, mid-drain (the drain owns its fate),
        removed, the pool is closed, or there is no factory."""
        idx = self._resolve_idx(replica)
        with self._lock:
            st = self._states[idx]
            if (self._closed or self._factory is None
                    or st.state in ("restarting", "draining", "removed")):
                return False
            st.state = "restarting"
            if reason == "stalled":
                st.stalls += 1
            st.last_crash = reason
        if reason == "stalled":
            resilience.inc("replica_stalls")
        self._pool_flight.event("replica_restart_requested",
                                replica=st.label, reason=reason)
        _log.warning("replica %s restart requested (%s)", st.label, reason)
        self._spawn_restart(idx)
        return True

    def _spawn_restart(self, idx: int) -> None:
        t = threading.Thread(
            target=self._restart_driver, args=(idx,), daemon=True,
            name=f"lsot-pool-restart-{self._states[idx].label}",
        )
        with self._lock:
            # Prune finished episodes so the list tracks live drivers.
            self._restart_threads = [
                x for x in self._restart_threads if x.is_alive()
            ]
            self._restart_threads.append(t)
        t.start()

    def _build_replica(self, idx: int):
        return (self._factory(idx) if self._factory_takes_index
                else self._factory())

    def _restart_driver(self, idx: int) -> None:
        """One thread per replica restart episode: bounded teardown of
        the corpse, backoff under the per-replica budget, rebuild + warm
        + swap. Budget exhausted (or rebuild failures burning it) marks
        only THIS replica dead — siblings carry the fleet."""
        st = self._states[idx]
        while True:
            old = self.schedulers[idx]
            try:
                if old is not None:
                    old.shutdown(timeout=self._replica_join_s)
            except Exception:  # noqa: BLE001 — a broken corpse must not stop the rebuild
                _log.exception("replica %s teardown failed; continuing",
                               st.label)
            with self._lock:
                if self._closed:
                    return
                if st.restarts >= self.max_restarts:
                    st.state = "dead"
                    st.restart_eta = None
                    self._pool_flight.event("replica_dead",
                                            replica=st.label,
                                            restarts=st.restarts)
                    _log.error(
                        "replica %s dead: restart budget exhausted "
                        "(%d/%d)", st.label, st.restarts, self.max_restarts,
                    )
                    return
                attempt = st.restarts
                st.restarts += 1
            resilience.inc("replica_restarts")
            delay = self._restart_policy.delay_s(attempt, self._rng)
            with self._lock:
                # Published for retry_after_hint: hints quoted while this
                # replica is down promise at least the backoff remaining.
                st.restart_eta = time.monotonic() + delay
            self._sleep(delay)
            with self._lock:
                if self._closed:
                    # The pool died during the backoff: don't start a
                    # rebuild nobody will use (shutdown() is joining us).
                    return
            try:
                fresh = self._build_replica(idx)
                # Warm BEFORE serving, like the supervisor's restart
                # driver: a rebuilt scheduler's cold XLA compiles block
                # its loop exactly like the wedge this restart may be
                # recovering from.
                warm = getattr(fresh, "warmup", None)
                if callable(warm):
                    warm()
                fresh.start()
            except Exception:  # noqa: BLE001 — rebuild failure burns one credit
                _log.exception("replica %s rebuild failed (restart %d/%d)",
                               st.label, attempt + 1, self.max_restarts)
                continue
            with self._lock:
                if self._closed or st.state != "restarting":
                    # Pool going away, or a drain/remove raced the
                    # rebuild and owns the replica now: don't swap a
                    # fresh scheduler into a slot someone else decided
                    # the fate of.
                    fresh.shutdown()
                    return
                fl = getattr(fresh, "flight", None)
                if fl is not None:
                    fl.replica = st.label
                self.schedulers[idx] = fresh
                # A rebuilt prefill-role replica needs its handoff pump
                # re-pointed at the pool (the corpse took the wiring).
                self._wire_handoff(idx, fresh)
                # Re-capture the model axis: the factory may rebuild the
                # replica with (or without) a checkpoint id, and stale
                # model routing would misplace every named request.
                st.model_id = self._model_id(fresh)
                # Degraded until a clean completion lands on it (the
                # submit-path done-callback promotes it back to ready).
                st.state = "degraded"
                st.restart_eta = None
            self._pool_flight.event("replica_restart", replica=st.label,
                                    attempt=st.restarts)
            _log.info("replica %s restarted (%d/%d)", st.label,
                      st.restarts, self.max_restarts)
            cb = self.on_replica_restart
            if cb is not None:
                try:
                    cb(st.label)
                except Exception:  # noqa: BLE001 — replay hook must not kill the driver
                    _log.exception("on_replica_restart(%s) failed", st.label)
            return

    def drain_replica(self, replica, deadline_s: Optional[float] = None,
                      remove: bool = False) -> Dict[str, object]:
        """Runtime drain of ONE replica: stop placing on it, RE-PLACE its
        queued-not-yet-admitted requests onto the least-loaded siblings
        (acknowledged work is never shed by a drain), give in-flight
        work up to `deadline_s` to finish (None = wait; <= 0 = none),
        then shut the replica down with a bounded join. `remove=True`
        marks it permanently out of the fleet; otherwise it parks as
        `drained` and `restart_replica()` can bring it back. SIGTERM
        semantics at the pool level are untouched — this is the
        one-replica twin of the supervisor's drain."""
        idx = self._resolve_idx(replica)
        t_drain0 = time.perf_counter()
        with self._lock:
            st = self._states[idx]
            if st.state in ("draining", "removed"):
                return {"replica": st.label, "state": st.state,
                        "replaced": 0}
            st.state = "draining"
            sched = self.schedulers[idx]
        self._pool_flight.event("replica_drain", replica=st.label,
                                deadline_s=deadline_s, remove=remove)
        # Re-place queued work BEFORE waiting on in-flight: the queue
        # would otherwise drain into the replica we are emptying.
        replaced = 0
        pulls = []
        extract = getattr(sched, "extract_queued", None)
        if callable(extract):
            pulls.extend(extract())
        # Packed handoffs waiting on this replica drain too: each carries
        # its portable KV blob, so a sibling restores and decodes it
        # without a re-prefill (acknowledged work never sheds).
        exh = getattr(sched, "extract_handoffs", None)
        if callable(exh):
            pulls.extend(exh())
        if pulls:
            # Multi-model fleets (ISSUE 16): a draining replica's queued
            # work can only re-place onto siblings holding the SAME
            # checkpoint — a cross-model sibling would decode with the
            # wrong weights. Draining the ONLY replica of a model keeps
            # the lone-replica degenerate path below: the work stays on
            # the draining replica and serves out inside the grace.
            drain_model = st.model_id or self._model_id(sched)
            for req in pulls:
                target = None
                cands = self._placeable()
                if self._model_routing and drain_model:
                    cands = [c for c in cands
                             if (c[1].model_id or self._model_id(c[2]))
                             == drain_model]
                if cands:
                    target = min(
                        ((self._wscore(i, s), self._penalty(_st, s), i, s)
                         for (i, _st, s) in cands),
                        key=lambda t: (t[1], t[0][0], t[0][1], t[2]),
                    )[3]
                if target is not None and callable(
                        getattr(target, "requeue", None)):
                    try:
                        target.requeue(req)
                        replaced += 1
                        continue
                    except Exception:  # noqa: BLE001 — incompatible/racing target
                        pass
                # No sibling can take it: leave it on the draining
                # replica — it serves out its queue inside the grace
                # (a lone-replica drain degenerates to a plain drain).
                sched.requeue(req)
        if replaced:
            self._pool_flight.event("replica_drain_replaced",
                                    replica=st.label, replaced=replaced)
        # Bounded grace for in-flight + whatever stayed queued.
        busy = getattr(sched, "_busy_now", None)
        deadline = (Deadline.after(deadline_s)
                    if deadline_s is not None and deadline_s > 0 else None)
        wait_all = deadline_s is None
        finished = True
        while callable(busy):
            try:
                if not busy():
                    break
            except Exception:  # noqa: BLE001 — a dying replica mid-read
                break
            if not wait_all and (deadline is None
                                 or deadline.remaining() <= 0):
                finished = False
                break
            time.sleep(0.01)
        try:
            sched.shutdown(timeout=self._replica_join_s)
        except Exception:  # noqa: BLE001 — a wedged corpse must not fail the drain
            _log.exception("replica %s drain shutdown failed", st.label)
        with self._lock:
            # Only finalize if the drain still owns the slot: a racing
            # restart_replica is refused while state == "draining", so
            # anything else here means someone else took over — don't
            # mark a live replica drained out from under them.
            if st.state == "draining":
                st.state = "removed" if remove else "drained"
            # Fleet drain ledger (ISSUE 17): scale-down rides this path,
            # so its cost shows up as lsot_fleet_drain_seconds.
            self._fleet_drain_s_sum += time.perf_counter() - t_drain0
            self._fleet_drain_count += 1
        self._pool_flight.event("replica_drained", replica=st.label,
                                replaced=replaced, finished=finished,
                                removed=remove)
        cb = self.on_replica_drained
        if cb is not None:
            try:
                # The supervisor's re-placement seam: journaled requests
                # still attributed to this replica (in-flight work the
                # grace did not finish) re-place onto the fleet.
                cb(st.label)
            except Exception:  # noqa: BLE001 — replay hook best-effort
                _log.exception("on_replica_drained(%s) failed", st.label)
        return {"replica": st.label,
                "state": "removed" if remove else "drained",
                "replaced": replaced, "finished": finished}

    def remove_replica(self, replica,
                       deadline_s: Optional[float] = None) -> Dict[str, object]:
        """Drain + permanently remove one replica from the fleet."""
        return self.drain_replica(replica, deadline_s=deadline_s,
                                  remove=True)

    # ------------------------------------------- elastic membership (17)

    def add_replica(self, scheduler, label: Optional[str] = None,
                    weight: float = 1.0, elastic: bool = True) -> str:
        """Join ONE replica to a LIVE fleet: append + wire the handoff
        pump and constraint seam, run the startup handshake, and (if the
        joiner brought a lease surface) make sure the lease monitor is
        running. Returns the new replica's label. A joiner failing the
        page-geometry/model handshake stays visible in /healthz as dead
        with the reason — it is never placeable, and the fleet keeps
        serving. `elastic=True` marks it retirable by scale-down;
        operator-configured replicas never retire."""
        with self._lock:
            if self._closed:
                raise RuntimeError("cannot add a replica to a closed pool")
            idx = len(self.schedulers)
            lbl = label or f"r{idx}"
            fl = getattr(scheduler, "flight", None)
            if fl is not None:
                fl.replica = lbl
            self.schedulers.append(scheduler)
            self._states.append(_ReplicaState(
                label=lbl, model_id=self._model_id(scheduler)))
            self._weights.append(max(1e-9, float(weight)))
            if elastic:
                self._elastic.add(idx)
            self._fleet_joins += 1
        self._wire_handoff(idx, scheduler)
        ok = self._validate_join(idx, scheduler)
        self._pool_flight.event(
            "replica_join", replica=lbl, elastic=bool(elastic),
            accepted=ok, phase_role=self._phase_role(scheduler))
        # The lease monitor tolerates list growth (it snapshots the state
        # list under the lock each tick) — (re)arm it in case the joiner
        # is the fleet's first remote.
        self._maybe_start_lease()
        return lbl

    def retire_replica(self, replica=None,
                       deadline_s: Optional[float] = None
                       ) -> Optional[Dict[str, object]]:
        """Scale-down: drain-and-remove ONE autoscaler-added replica —
        drain → re-place → remove rides drain_replica, so acknowledged
        work re-places onto siblings and ZERO requests are lost. With
        `replica=None`, picks the least-loaded serving elastic replica.
        Returns the drain report, or None when nothing is retirable
        (operator-configured replicas are never eligible)."""
        if replica is not None:
            idx = self._resolve_idx(replica)
            if idx not in self._elastic:
                return None
        else:
            with self._lock:
                cands = [i for i in self._elastic
                         if self._states[i].state
                         in _ReplicaState.PLACEABLE]
            if not cands:
                return None
            idx = min(cands, key=lambda i: (
                self._wscore(i, self.schedulers[i]), i))
        out = self.drain_replica(idx, deadline_s=deadline_s, remove=True)
        with self._lock:
            self._elastic.discard(idx)
            self._fleet_retires += 1
        self._pool_flight.event("replica_retire",
                                replica=out.get("replica"),
                                replaced=out.get("replaced"))
        return out

    def fleet_stats(self) -> Dict[str, object]:
        """The `fleet` block in /healthz and /metrics (lsot_fleet_*):
        live membership, join/retire/drain lifecycle counters, and the
        pushed-handoff ledger (depth, bytes, wire→placement latency)."""
        with self._lock:
            states = [st.state for st in self._states]
            out: Dict[str, object] = {
                "size": len(states),
                "serving": sum(1 for s in states
                               if s in _ReplicaState.PLACEABLE),
                "elastic": len(self._elastic),
                "joins": self._fleet_joins,
                "retires": self._fleet_retires,
                "drain_s_sum": round(self._fleet_drain_s_sum, 6),
                "drain_count": self._fleet_drain_count,
            }
            lat = sorted(self._push_lat)
        out.update({"pushed": 0, "push_bytes": 0, "pump_depth": 0,
                    "push_placed": len(lat)})
        if lat:
            out["push_place_p50_ms"] = round(
                lat[int(0.50 * (len(lat) - 1))] * 1e3, 3)
            out["push_place_p95_ms"] = round(
                lat[int(0.95 * (len(lat) - 1))] * 1e3, 3)
        for s in self.schedulers:
            pp = getattr(s, "push_pump_stats", None)
            if isinstance(pp, dict):
                out["pushed"] += int(pp.get("pushed", 0) or 0)
                out["push_bytes"] += int(pp.get("push_bytes", 0) or 0)
                out["pump_depth"] += int(pp.get("depth", 0) or 0)
                w = pp.get("worker")
                if isinstance(w, dict):
                    out["pump_depth"] += int(w.get("window", 0) or 0)
        return out

    def stalled_replicas(self, factor: float, floor_s: float) -> List[str]:
        """Labels of SERVING replicas whose busy heartbeat has gone stale
        past their own stall threshold — the supervisor's watchdog feed
        for targeted restarts. Replicas already restarting/draining/dead
        are excluded (their stale corpses are being handled)."""
        from .watchdog import stall_threshold

        out: List[str] = []
        for st, s in self._replica_items(_ReplicaState.PLACEABLE):
            hb = getattr(s, "heartbeat", None)
            if hb is None or not hb.busy:
                continue
            if hb.age() > stall_threshold(hb, factor, floor_s):
                out.append(st.label)
        return out

    # ----------------------------------------------------------- health

    def replica_health(self) -> List[Dict[str, object]]:
        """Per-replica lifecycle for /healthz + /readyz + /metrics:
        state, restart/stall budgets, crash flag, restart ETA."""
        now = time.monotonic()
        out = []
        for st, s in self._replica_items():
            rec: Dict[str, object] = {
                "replica": st.label,
                "state": st.state,
                "phase_role": self._phase_role(s),
                "model_id": st.model_id or self._model_id(s),
                "restarts": st.restarts,
                "max_restarts": self.max_restarts,
                "stalls": st.stalls,
                "crashed": getattr(s, "_crash", None) is not None,
            }
            # Transport-backed replicas (ISSUE 15): the /healthz fleet
            # view says which wire the replica is behind and whether its
            # lease is healthy — one probe answers "is r2 down or just
            # partitioned from us".
            ts = getattr(s, "transport_stats", None)
            if callable(ts):
                try:
                    rec["transport"] = self._transport_summary(ts())
                except Exception:  # noqa: BLE001 — a dying replica mid-read
                    pass
            if st.last_crash:
                rec["last_crash"] = st.last_crash
            if st.restart_eta is not None:
                rec["restart_eta_s"] = round(max(0.0, st.restart_eta - now),
                                             3)
            out.append(rec)
        return out

    def health(self) -> Dict[str, object]:
        """Aggregate fleet state, shaped like the supervisor's health()
        payload (/readyz consumes either): `ready` — every replica
        serving clean; `degraded` — serving, but some replica is
        restarting/drained/dead or not yet proven after a restart;
        `restarting` — NO replica serving but at least one rebuild in
        flight; `dead` — the fleet is gone. Plus the per-replica list."""
        reps = self.replica_health()
        # Removed replicas LEFT the fleet (a deliberate scale-down): they
        # stay visible in the replicas list but must not degrade the
        # aggregate forever.
        states = [r["state"] for r in reps if r["state"] != "removed"]
        serving = [s for s in states if s in _ReplicaState.PLACEABLE]
        if serving:
            state = ("ready" if all(s == "ready" for s in states)
                     else "degraded")
        elif "restarting" in states:
            state = "restarting"
        else:
            state = "dead"
        return {
            "state": state,
            "replicas": reps,
            "restarts": sum(int(r["restarts"]) for r in reps),
            "stalls": sum(int(r["stalls"]) for r in reps),
            # Elastic membership view (ISSUE 17): size/joins/retires/
            # drain ledger + the pushed-handoff pump depth, so one
            # /healthz probe answers "did the fleet actually scale".
            "fleet": self.fleet_stats(),
        }

    @property
    def prefix_stats(self) -> Dict[str, object]:
        """Summed prefix-cache stats across replicas (SchedulerBackend
        duck typing — each replica owns an independent cache). Counters
        sum; `hit_rate` is DERIVED from the summed hits/misses — summing
        or averaging per-replica ratios would misweight replicas with
        different traffic shares."""
        out: Dict[str, object] = {
            "hits": 0, "misses": 0, "blocks_reused": 0,
            "reused_tokens": 0, "evictions": 0, "cached_blocks": 0,
        }
        for s in self.schedulers:
            st = getattr(s, "prefix_stats", None)
            if isinstance(st, dict):
                for k in out:
                    out[k] += int(st.get(k, 0))
        total = out["hits"] + out["misses"]
        out["hit_rate"] = (round(out["hits"] / total, 4) if total
                           else 0.0)
        return out

    @property
    def prefix_telemetry(self) -> Optional[Dict[str, object]]:
        """Per-replica prefix-cache telemetry, labeled (the serving.prefix
        payload the lsot_prefix_* Prometheus families render). None when
        no replica has an enabled cache."""
        per = []
        for st, s in self._replica_items():
            t = getattr(s, "prefix_telemetry", None)
            if isinstance(t, dict):
                rec = dict(t)
                rec["replica"] = st.label
                per.append(rec)
        return {"replicas": per} if per else None

    def prefix_registry(self, top_k: Optional[int] = None
                        ) -> Dict[str, object]:
        """Per-replica content-addressed registries (the
        /debug/prefixcache payload for a fleet), labeled with the pool's
        replica vocabulary."""
        per = []
        for st, s in self._replica_items():
            fn = getattr(s, "prefix_registry", None)
            if not callable(fn):
                continue
            try:
                reg = fn(top_k)
            except Exception:  # noqa: BLE001 — a dying replica mid-read
                continue
            if isinstance(reg, dict):
                reg = dict(reg)
                reg["replica"] = st.label
                per.append(reg)
        return {"replicas": per}

    def prefix_affinity(self, digests: Sequence[str]
                        ) -> List[Dict[str, object]]:
        """Cache-aware routing feed (ISSUE 14): score every placeable
        replica by how many of `digests` (a request's chain-prefix
        digests — `prefix_chain_digests(ids, block)`) it currently holds
        resident. Returns [{replica, score}] sorted best-first, scoring
        replicas only (no score-0 noise); empty when nobody holds any.
        CONSUMED BY PLACEMENT (ISSUE 15): submit() sorts candidates by
        this lookup's scores ahead of the pressure penalty and the
        weighted least-loaded tie-break whenever affinity routing is on
        (the default; LSOT_POOL_AFFINITY=0 restores the pure
        observability role) — changing the scoring here changes where
        requests LAND. Each non-empty lookup drops a `prefix_affinity`
        event into the pool flight ring so placement postmortems can
        see what the router knew."""
        want = {d for d in digests if d}
        if not want:
            return []
        scored: List[Dict[str, object]] = []
        for _i, st, s in self._placeable():
            fn = getattr(s, "resident_digests", None)
            if not callable(fn):
                continue
            try:
                score = len(want & set(fn()))
            except Exception:  # noqa: BLE001 — a dying replica mid-read
                continue
            if score:
                scored.append({"replica": st.label, "score": score})
        scored.sort(key=lambda r: -int(r["score"]))
        if scored:
            self._pool_flight.event(
                "prefix_affinity", best=scored[0]["replica"],
                score=scored[0]["score"], digests=len(want),
                holders=len(scored),
            )
        return scored

    @property
    def speculation_stats(self) -> Optional[Dict[str, float]]:
        """First replica's acceptance view (replicas are homogeneous;
        None when speculation is off) — SchedulerBackend duck typing."""
        return getattr(self.schedulers[0], "speculation_stats", None)

    def generate(self, prompts, max_new_tokens: int = 256,
                 sampling: SamplingParams = SamplingParams(), seed: int = 0):
        futs = [
            self.submit(p, max_new_tokens=max_new_tokens, sampling=sampling,
                        seed=seed)
            for p in prompts
        ]
        return [f.result() for f in futs]


class SchedulerBackend:
    """`serve.GenerationService`-compatible backend over the scheduler.

    Drop-in for `EngineBackend` (same `.complete()` seam, backends.py): N
    HTTP handler threads calling `complete()` concurrently share one decode
    batch instead of serializing on a lock.
    """

    #: GenerationService checks this before forwarding a `constrain=` spec.
    supports_constrain = True
    #: GenerationService checks this before forwarding a `deadline_s`: the
    #: scheduler can actually retire an in-flight request at harvest time,
    #: unlike the one-XLA-program engine.
    supports_deadline = True

    def __init__(
        self,
        scheduler: ContinuousBatchingScheduler,
        tokenizer,
        max_new_tokens: int = 256,
        sampling: SamplingParams = SamplingParams(),
        stop_texts: Sequence[str] = (),
        add_bos: bool = True,
        deadline_s: Optional[float] = None,
        model_id: str = "",
    ):
        self.scheduler = scheduler.start()
        self.tokenizer = tokenizer
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.stop_texts = tuple(stop_texts)
        self.add_bos = add_bos
        # Default per-request deadline (None = no deadline); a request's
        # own deadline_s overrides it.
        self.deadline_s = deadline_s
        # Multi-model serving (ISSUE 16): every submit through this
        # backend names its registered model so a model-aware pool
        # routes it to the right co-resident checkpoint. "" (the
        # default) submits model-blind — the single-model fleet's exact
        # call shape — and the kwarg is forwarded only to schedulers
        # that understand the axis (duck-typed, like idempotency).
        self.model_id = str(model_id or "")
        self._routes_models = bool(
            getattr(scheduler, "supports_model_routing", False)
        ) and bool(self.model_id)
        # Idempotency keys need a journal to dedupe against — only the
        # supervised wrapper (serve/supervisor.py) has one.
        self.supports_idempotency = bool(
            getattr(scheduler, "supports_idempotency", False)
        )
        # Multi-tenant QoS (ISSUE 18): tenant/qos kwargs are forwarded
        # only to schedulers that understand the axis — duck-typed like
        # model routing, so fakes and older signatures stay untouched.
        self.supports_qos = bool(getattr(scheduler, "supports_qos", False))
        # Journal-spill recovery happens HERE, the one seam every
        # deployment path (tiny, HF, GGUF, dp pool) funnels through: a
        # previous process's drained-but-unfinished requests resubmit so
        # retried idempotency keys find their results. The backend owns
        # the tokenizer, so it is also the one that can recompile a
        # spilled constraint SPEC back into device tables — point the
        # supervisor's resolver here BEFORE recovery runs.
        if hasattr(scheduler, "constraint_resolver"):
            scheduler.constraint_resolver = self._resolve_constraint
        recover = getattr(scheduler, "recover", None)
        if callable(recover) and getattr(scheduler, "spill_path", None):
            recover()

    def shutdown(self) -> None:
        """Stop the scheduler's event loop (idempotent; safe on shared
        schedulers — GenerationService.close() dedupes by backend, and
        ContinuousBatchingScheduler.shutdown is itself idempotent)."""
        self.scheduler.shutdown()

    def health(self) -> Optional[Dict[str, object]]:
        """Supervisor lifecycle state (ready/restarting/degraded/dead +
        restart counters) for /readyz; None for a bare scheduler (always
        'ready or crashed' — the crash already answers 503 per request)."""
        h = getattr(self.scheduler, "health", None)
        return h() if callable(h) else None

    def drain(self, deadline_s: Optional[float] = None) -> None:
        """Graceful-shutdown seam (SIGTERM path): supervised schedulers
        stop admitting, finish in-flight up to the deadline, and journal
        the rest; bare schedulers just stop."""
        d = getattr(self.scheduler, "drain", None)
        if callable(d):
            d(deadline_s)
        else:
            self.scheduler.shutdown()

    def retry_after_hint(self) -> float:
        hint = getattr(self.scheduler, "retry_after_hint", None)
        return hint() if callable(hint) else 1.0

    def stats(self) -> Dict[str, object]:
        """Serving-layer observability beyond per-request metrics: prefix
        cache reuse, (when --speculative is on) draft acceptance, and
        (when supervised) the crash-recovery lifecycle — merged into the
        app's /metrics payload per model."""
        out: Dict[str, object] = {"prefix_cache": self.scheduler.prefix_stats}
        # Prefix-cache telemetry (ISSUE 14): the per-replica counter/
        # residency/priced-savings block the lsot_prefix_* Prometheus
        # families render — beside (not replacing) the flat prefix_cache
        # sums above, whose lsot_serving_prefix_cache_* gauges dashboards
        # already scrape.
        ptel = getattr(self.scheduler, "prefix_telemetry", None)
        if ptel:
            out["prefix"] = ptel
        spec = self.scheduler.speculation_stats
        if spec is not None:
            out["speculation"] = spec
        # Paged-KV pool occupancy + sharing counters (kv_layout="paged"):
        # pages_total/pages_free/pages_shared become Prometheus gauges via
        # the nested-serving-stats renderer (utils/prometheus.py), so a
        # leaked page is a flat-lining pages_free on a dashboard.
        pages = getattr(self.scheduler, "page_stats", None)
        if pages:
            out["kv_pages"] = pages
        # Per-round roofline ledger (ISSUE 12, utils/perfmodel.py): the
        # live per-phase MFU / HBM-util / binding-roof view under
        # `serving.perf` — the Prometheus renderer turns it into the
        # lsot_mfu / lsot_hbm_util gauges labeled phase × replica.
        perf = getattr(self.scheduler, "perf_stats", None)
        if perf:
            out["perf"] = perf
        # Prefill→decode handoff traffic (ISSUE 13): exports/imports/
        # fallbacks, page+byte volume, decode-slot wait — rendered as
        # the lsot_handoff_* Prometheus families (utils/prometheus.py).
        ho = getattr(self.scheduler, "handoff_stats", None)
        if ho:
            out["handoff"] = ho
        # Replica-transport traffic (ISSUE 15): per-replica rpc/retry/
        # timeout counters + lease state for remote fleets — rendered as
        # the lsot_transport_* families (utils/prometheus.py).
        tr = getattr(self.scheduler, "transport_stats", None)
        if tr:
            out["transport"] = tr
        # Cache-aware placement counters (ISSUE 15): how often affinity
        # had an opinion and how often the router took it.
        rt = getattr(self.scheduler, "routing_stats", None)
        if callable(rt):
            try:
                routing = rt()
            except Exception:  # noqa: BLE001 — a churning fleet mid-read
                routing = None
            if routing:
                out["routing"] = routing
        # Liveness view (serve/watchdog.py): heartbeat age/cadence, slots
        # retired for per-lane stalls, and — when supervised — whole-loop
        # stalls detected + the active stall threshold.
        wd = getattr(self.scheduler, "watchdog_stats", None)
        if wd is not None:
            out["watchdog"] = wd
        # Flight-recorder occupancy (counts only — the records themselves
        # live at /debug/flightrecorder, too hot-path-adjacent for every
        # /metrics scrape to serialize). Prefer the flight_stats() seam:
        # a SupervisedScheduler's own `.flight` is the sparse lifecycle
        # ring, not the per-round ring an operator monitors.
        fs = getattr(self.scheduler, "flight_stats", None)
        if callable(fs):
            out["flight_recorder"] = fs()
        else:
            fl = getattr(self.scheduler, "flight", None)
            if fl is not None:
                out["flight_recorder"] = fl.stats()
        # Per-replica load attribution (SchedulerPool): queue depth ×
        # cadence per replica, the placement-score feed.
        loads = getattr(self.scheduler, "replica_loads", None)
        if callable(loads):
            out["replicas"] = loads()
        # Per-model serving aggregation (ISSUE 16): queue depth, tok/s
        # and KV pages held per co-resident checkpoint — the
        # lsot_model_* Prometheus families. None (single-model fleets)
        # adds nothing, keeping the pre-multi-model payload intact.
        ms = getattr(self.scheduler, "model_stats", None)
        if callable(ms):
            try:
                models = ms()
            except Exception:  # noqa: BLE001 — a churning fleet mid-read
                models = None
            if models:
                out["models"] = models
        # Multi-tenant QoS (ISSUE 18): per-tenant WFQ/admission counters
        # — the lsot_tenant_* families. None (QoS off, or a scheduler
        # without the seam) adds nothing: the pre-QoS payload intact.
        qs = getattr(self.scheduler, "qos_stats", None)
        if callable(qs):
            try:
                qos_block = qs()
            except Exception:  # noqa: BLE001 — a churning fleet mid-read
                qos_block = None
            if qos_block:
                out["qos"] = qos_block
        # Elastic fleet membership (ISSUE 17): size/joins/retires/drain
        # ledger + pushed-handoff depth/bytes/latency — rendered as the
        # lsot_fleet_* families (utils/prometheus.py).
        fs2 = getattr(self.scheduler, "fleet_stats", None)
        if callable(fs2):
            try:
                fleet = fs2()
            except Exception:  # noqa: BLE001 — a churning fleet mid-read
                fleet = None
            if fleet:
                out["fleet"] = fleet
        sup = self.health()
        if sup is not None:
            out["supervisor"] = sup
        return out

    @classmethod
    def from_hf_checkpoint(
        cls,
        ckpt_dir: str,
        tokenizer,
        mesh=None,
        dtype=None,
        num_slots: int = 8,
        prompt_bucket: int = 128,
        stop_ids: Optional[Sequence[int]] = None,
        quantize_int8: bool = False,
        quantize_int4: bool = False,
        quantize_unembed8: bool = False,
        kv_quant: Optional[str] = None,
        kv_layout: str = "contiguous",
        kv_page_size: Optional[int] = None,
        kv_pages: Optional[int] = None,
        kv_hbm_budget_bytes: Optional[int] = None,
        kv_overcommit: Optional[float] = None,
        kv_spill: Optional[bool] = None,
        kv_watermark_low: Optional[float] = None,
        kv_watermark_high: Optional[float] = None,
        max_seq: Optional[int] = None,
        decode_chunk: int = 8,
        speculative_draft: int = 0,
        max_queue_depth: int = 0,
        supervise: bool = False,
        max_restarts: int = 5,
        max_entry_replays: int = 0,
        journal_spill: Optional[str] = None,
        stall_factor: float = 16.0,
        stall_min_s: float = 10.0,
        stall_warmup_s: float = 0.0,
        **kwargs,
    ) -> "SchedulerBackend":
        """Deployment path for concurrent serving: HF checkpoint straight
        into a continuous-batching scheduler (the product's `--scheduler`
        flag, app/__main__.py). Mirrors `EngineBackend.from_hf_checkpoint`
        incl. int8 weight-only quantization (and `kv_quant="int8"` for the
        persistent KV cache — halves the serving window's HBM footprint
        and decode streaming); the mesh (if any) must be dp=1 — request
        parallelism comes from slots. With `supervise=True` the scheduler
        runs under a crash supervisor (serve/supervisor.py): the params
        stay loaded, and a decode-loop crash tears down + rebuilds the
        scheduler and replays journaled requests instead of 503ing until
        a human restarts the process."""
        import jax.numpy as jnp

        from ..checkpoint import load_hf_checkpoint
        from .backends import resolve_stop_ids

        if quantize_int8 and quantize_int4:
            raise ValueError("pick one of quantize_int8 / quantize_int4")
        if quantize_int8 or quantize_int4 or quantize_unembed8:
            from ..ops.quant import (
                quantize_params,
                quantize_params_int4,
                quantize_unembed,
            )

            cfg, params = load_hf_checkpoint(
                ckpt_dir, dtype=dtype or jnp.bfloat16, mesh=None
            )
            if quantize_int4:
                params = quantize_params_int4(params)
            elif quantize_int8:
                params = quantize_params(params)
            if quantize_unembed8:
                params = quantize_unembed(params)
            # Placement happens in the scheduler __init__ (shard_params).
            sched_mesh = mesh
        else:
            cfg, params = load_hf_checkpoint(
                ckpt_dir, dtype=dtype or jnp.bfloat16, mesh=mesh
            )
            sched_mesh = mesh
        def make_sched():
            # Factory, not instance: the supervisor rebuilds from the SAME
            # loaded (and possibly quantized/sharded) params after a crash
            # — one disk read per process, not per restart.
            return ContinuousBatchingScheduler(
                cfg, params, num_slots=num_slots, max_seq=max_seq,
                decode_chunk=decode_chunk, prompt_bucket=prompt_bucket,
                stop_ids=stop_ids if stop_ids is not None
                else resolve_stop_ids(cfg, tokenizer),
                mesh=sched_mesh, kv_quant=kv_quant,
                kv_layout=kv_layout, kv_page_size=kv_page_size,
                kv_pages=kv_pages,
                kv_hbm_budget_bytes=kv_hbm_budget_bytes,
                kv_overcommit=kv_overcommit, kv_spill=kv_spill,
                kv_watermark_low=kv_watermark_low,
                kv_watermark_high=kv_watermark_high,
                speculative_draft=speculative_draft,
                max_queue_depth=max_queue_depth,
            )

        if supervise:
            import os

            from .supervisor import SupervisedScheduler

            return cls(SupervisedScheduler(
                make_sched, max_restarts=max_restarts,
                max_entry_replays=max_entry_replays,
                spill_path=journal_spill,
                stall_factor=stall_factor, stall_min_s=stall_min_s,
                warmup_grace_s=stall_warmup_s,
                name=f"scheduler:{os.path.basename(ckpt_dir.rstrip('/'))}",
            ), tokenizer, **kwargs)
        return cls(make_sched(), tokenizer, **kwargs)

    @classmethod
    def from_gguf(
        cls,
        gguf_path: str,
        tokenizer,
        cfg=None,
        mesh=None,
        dtype=None,
        num_slots: int = 8,
        prompt_bucket: int = 128,
        stop_ids: Optional[Sequence[int]] = None,
        quantize_int8: bool = False,
        quantize_int4: bool = False,
        quantize_unembed8: bool = False,
        kv_quant: Optional[str] = None,
        kv_layout: str = "contiguous",
        kv_page_size: Optional[int] = None,
        kv_pages: Optional[int] = None,
        kv_hbm_budget_bytes: Optional[int] = None,
        kv_overcommit: Optional[float] = None,
        kv_spill: Optional[bool] = None,
        kv_watermark_low: Optional[float] = None,
        kv_watermark_high: Optional[float] = None,
        max_seq: Optional[int] = None,
        decode_chunk: int = 8,
        speculative_draft: int = 0,
        max_queue_depth: int = 0,
        supervise: bool = False,
        max_restarts: int = 5,
        max_entry_replays: int = 0,
        journal_spill: Optional[str] = None,
        stall_factor: float = 16.0,
        stall_min_s: float = 10.0,
        stall_warmup_s: float = 0.0,
        **kwargs,
    ) -> "SchedulerBackend":
        """GGUF blob -> continuous-batching scheduler (C++ parse + dequant,
        native/src/gguf.cpp). `quantize_int8`/`quantize_int4` re-quantize
        the dequantized blob into the in-tree serving formats (a Q4 blob
        served with quantize_int4 stays 4-bit end to end). `supervise=True`
        wraps the scheduler in the crash supervisor, exactly like
        `from_hf_checkpoint`."""
        from ..checkpoint import load_gguf_checkpoint
        from .backends import resolve_stop_ids

        if quantize_int8 and quantize_int4:
            raise ValueError("pick one of quantize_int8 / quantize_int4")
        if quantize_int8 or quantize_int4 or quantize_unembed8:
            from ..ops.quant import (
                quantize_params,
                quantize_params_int4,
                quantize_unembed,
            )

            cfg, params = load_gguf_checkpoint(
                gguf_path, cfg=cfg, dtype=dtype, mesh=None
            )
            if quantize_int4:
                params = quantize_params_int4(params)
            elif quantize_int8:
                params = quantize_params(params)
            if quantize_unembed8:
                params = quantize_unembed(params)
            # Placement happens in the scheduler __init__ (shard_params).
        else:
            cfg, params = load_gguf_checkpoint(
                gguf_path, cfg=cfg, dtype=dtype, mesh=mesh
            )
        def make_sched():
            return ContinuousBatchingScheduler(
                cfg, params, num_slots=num_slots, max_seq=max_seq,
                decode_chunk=decode_chunk, prompt_bucket=prompt_bucket,
                stop_ids=stop_ids if stop_ids is not None
                else resolve_stop_ids(cfg, tokenizer),
                mesh=mesh, kv_quant=kv_quant,
                kv_layout=kv_layout, kv_page_size=kv_page_size,
                kv_pages=kv_pages,
                kv_hbm_budget_bytes=kv_hbm_budget_bytes,
                kv_overcommit=kv_overcommit, kv_spill=kv_spill,
                kv_watermark_low=kv_watermark_low,
                kv_watermark_high=kv_watermark_high,
                speculative_draft=speculative_draft,
                max_queue_depth=max_queue_depth,
            )

        if supervise:
            import os

            from .supervisor import SupervisedScheduler

            return cls(SupervisedScheduler(
                make_sched, max_restarts=max_restarts,
                max_entry_replays=max_entry_replays,
                spill_path=journal_spill,
                stall_factor=stall_factor, stall_min_s=stall_min_s,
                warmup_grace_s=stall_warmup_s,
                name=f"scheduler:{os.path.basename(gguf_path)}",
            ), tokenizer, **kwargs)
        return cls(make_sched(), tokenizer, **kwargs)

    def _rclass(self, constrain) -> str:
        """The request-class label for the metrics histograms: grammar
        constraining and speculation have distinct latency profiles, and
        an operator pricing the NL→SQL hot path needs ITS numbers."""
        parts = []
        if constrain is not None:
            parts.append("constrained")
        if getattr(self.scheduler, "_spec_draft", 0):
            parts.append("speculative")
        return "+".join(parts)

    def flight_snapshot(self, last: Optional[int] = None):
        """Live flight-recorder view (per-round records; pool-merged and
        replica-labeled for dp>1) — the /debug/flightrecorder payload."""
        return merge_snapshots([self.scheduler], last)

    def prefix_registry(self, top_k: Optional[int] = None
                        ) -> Optional[Dict[str, object]]:
        """Content-addressed prefix-cache registry (ISSUE 14) — the
        /debug/prefixcache payload: top-K resident digests with live
        metadata, reuse-distance histogram, churn counters; pool-shaped
        ({"replicas": [...]}) for fleets. None for schedulers without
        the seam (duck-typed fakes)."""
        fn = getattr(self.scheduler, "prefix_registry", None)
        return fn(top_k) if callable(fn) else None

    def profile_rounds(self, rounds: Optional[int] = None,
                       out_dir: Optional[str] = None) -> Dict[str, object]:
        """On-demand device capture seam (the /debug/profile POST body):
        arm a bounded jax.profiler trace around the scheduler's next N
        rounds. Raises ValueError for backends whose scheduler has no
        profiling seam (duck-typed fakes)."""
        fn = getattr(self.scheduler, "profile_rounds", None)
        if not callable(fn):
            raise ValueError("backend scheduler does not support device "
                             "profiling")
        return fn(rounds, out_dir)

    def profile_status(self) -> Optional[Dict[str, object]]:
        fn = getattr(self.scheduler, "profile_status", None)
        return fn() if callable(fn) else None

    def check_budget(self, prompt: str,
                     max_new_tokens: Optional[int] = None,
                     constraint=None) -> None:
        """Raise ValueError if `prompt` leaves no decode room in the serving
        window — the same rejection complete()/complete_stream() would make,
        runnable BEFORE a streaming handler puts 200 headers on the wire
        (after which a request-shape error can only be a mid-stream line).
        With a compiled `constraint`, also checks that the CLAMPED budget
        (what submit() will actually receive after the decode-room clamp,
        not the raw requested value) can hold a complete parse."""
        ids = self.tokenizer.encode(prompt, add_bos=self.add_bos)
        budget = self._budget(len(ids), max_new_tokens)
        if constraint is not None and budget < constraint.min_new_tokens:
            raise ValueError(
                f"decode budget {budget} (after the serving-window clamp) "
                f"cannot hold a complete constrained parse (grammar needs "
                f">= {constraint.min_new_tokens} tokens incl. the stop id)"
            )

    def _resolve_constraint(self, constrain):
        # Constrained requests ride the speculative scheduler too: the
        # verify window evaluates the grammar mask at every draft position
        # (scheduler._build_spec_decode), so there is nothing to reject
        # here anymore — the resolver's only job is compiling the spec.
        from .backends import resolve_constraint

        return resolve_constraint(constrain, self.tokenizer,
                                  self.scheduler.stop_ids)

    def _constraint_kwargs(self, constrain) -> Dict[str, object]:
        """submit() kwargs for a constraint: the compiled tables always,
        plus the raw serializable SPEC when the scheduler is supervised
        (its journal spill writes the spec and recompiles it at
        recovery — serve/supervisor.py; a bare scheduler has no journal
        and no constraint_spec parameter)."""
        kwargs: Dict[str, object] = {
            "constraint": self._resolve_constraint(constrain)
        }
        if constrain is not None and hasattr(self.scheduler,
                                             "constraint_resolver"):
            kwargs["constraint_spec"] = constrain
        return kwargs

    def _model_kwargs(self) -> Dict[str, object]:
        """submit() kwargs for the model axis: present only when this
        backend is model-scoped AND the scheduler routes on models —
        bare schedulers and test fakes keep their exact signatures."""
        return {"model_id": self.model_id} if self._routes_models else {}

    def _qos_kwargs(self, tenant: str, qos: str) -> Dict[str, object]:
        """submit() kwargs for the tenant/qos axis (ISSUE 18): present
        only for labeled requests on a QoS-capable scheduler — the
        unlabeled path keeps the exact pre-QoS call shape."""
        if (tenant or qos) and self.supports_qos:
            return {"tenant": tenant, "qos": qos}
        return {}

    def _budget(self, n_prompt_tokens: int, max_new_tokens: Optional[int]) -> int:
        sched = self.scheduler
        overshoot = sched.overshoot
        room = sched.max_seq - 1 - overshoot - bucket_len(
            n_prompt_tokens, sched.prompt_bucket
        )
        if room < 1:
            raise ValueError(
                f"prompt ({n_prompt_tokens} tokens) leaves no room in the "
                f"{sched.max_seq}-token scheduler window of {sched.cfg.name}"
            )
        return min(max_new_tokens or self.max_new_tokens, room)

    def complete_stream(self, prompt: str,
                        max_new_tokens: Optional[int] = None,
                        sampling: Optional[SamplingParams] = None,
                        seed: int = 0,
                        stats_out: Optional[dict] = None,
                        constrain=None,
                        deadline_s: Optional[float] = None,
                        tenant: str = "", qos: str = ""):
        """Stream the completion as text chunks while it decodes — the
        capability Ollama's `stream=true` API exposes and the reference
        never used. Token ids arrive from the scheduler's per-request
        callback; text is re-decoded incrementally and emitted as clean
        deltas (a chunk is held back while the byte-level decode of a
        partial multi-byte sequence would surface U+FFFD, and the last
        `longest stop text - 1` chars stay held so a stop spanning chunk
        boundaries never leaks — streamed text equals blocking text).

        Each token re-decodes the accumulated ids (O(n^2) over the
        completion) ON PURPOSE: prefix-decode is not compositional for
        BPE/sentencepiece boundaries, the cost is host-side microseconds
        per token against human-reading-rate output, and exactness vs the
        blocking path is the contract the tests pin."""
        from ..utils import tracing
        from .backends import trim_stop_texts

        ids = self.tokenizer.encode(prompt, add_bos=self.add_bos)
        if stats_out is not None:
            # Accounting seam for GenerationService.generate_stream: the
            # prompt is tokenized here anyway, and chunk counts are not
            # token counts (holdbacks merge many tokens into one chunk).
            stats_out["prompt_tokens"] = len(ids)
        toks: "queue.Queue[int]" = queue.Queue()
        trace = tracing.current()
        t_submit = time.perf_counter()
        on_tok, first_at = _first_token_timer(toks.put)
        fut = self.scheduler.submit(
            ids, max_new_tokens=self._budget(len(ids), max_new_tokens),
            sampling=sampling or self.sampling, seed=seed,
            on_token=on_tok, **self._constraint_kwargs(constrain),
            deadline_s=deadline_s if deadline_s is not None
            else self.deadline_s,
            trace=trace, **self._model_kwargs(),
            **self._qos_kwargs(tenant, qos),
        )
        out_ids: List[int] = []
        emitted = ""
        hold = max((len(s) for s in self.stop_texts), default=1) - 1

        try:
            done = False
            while not done:
                try:
                    out_ids.append(toks.get(timeout=0.05))
                except queue.Empty:
                    done = fut.done()
                    continue
                text = self.tokenizer.decode(out_ids)
                trimmed = trim_stop_texts(text, self.stop_texts)
                if trimmed != text:  # a stop text landed: flush and end
                    if len(trimmed) > len(emitted):
                        yield trimmed[len(emitted):]
                    # Stop texts are host-side only (the scheduler knows stop
                    # IDS, not stop strings): without a cancel the slot keeps
                    # decoding the full remaining budget for text that is
                    # already final, delaying the terminal chunk and the
                    # slot's release. Cancel retires it at the next harvest;
                    # the future then resolves with what was generated, so
                    # result() still surfaces scheduler errors.
                    self.scheduler.cancel(fut)
                    fut.result()
                    return
                # Emit up to the holdback horizon, minus any trailing
                # partial multi-byte replacement char.
                safe = text[: len(text) - hold if hold else len(text)]
                delta = safe[len(emitted):]
                if delta and not delta.endswith("�"):
                    emitted += delta
                    yield delta
            fut.result()  # propagate errors; also syncs the token list
            while not toks.empty():
                out_ids.append(toks.get_nowait())
            text = trim_stop_texts(
                self.tokenizer.decode(out_ids), self.stop_texts
            )
            if len(text) > len(emitted):
                yield text[len(emitted):]
        finally:
            # Consumer gone mid-stream (GeneratorExit lands on a yield):
            # cancel so the slot stops decoding an abandoned request.
            if not fut.done():
                self.scheduler.cancel(fut)
                if trace is not None:
                    # Traced abandon: the worker flushes the sched.* spans
                    # at the retiring harvest, but the HTTP layer exports
                    # the trace the moment this generator closes — without
                    # a bounded wait the artifact for exactly the
                    # abandoned/stuck streams being diagnosed would carry
                    # stream.deliver and zero scheduler spans. One harvest
                    # normally lands in milliseconds; the cap keeps a
                    # wedged loop from hanging disconnect cleanup.
                    try:
                        fut.result(timeout=2.0)
                    except Exception:  # noqa: BLE001 — export best-effort
                        pass
            if trace is not None:
                # The delivery window: first submit to last chunk handed
                # to the consumer — what the CLIENT experienced, beside
                # the scheduler-side decode spans.
                trace.add_span("stream.deliver", t_submit,
                               time.perf_counter(), chunks=len(out_ids))
            if stats_out is not None:
                stats_out["output_tokens"] = len(out_ids)
                if first_at:
                    stats_out["ttft_s"] = first_at[0] - t_submit
                qw = getattr(fut, "_lsot_queue_wait", 0.0)
                if qw:
                    stats_out["queue_wait_s"] = qw
                stats_out["rclass"] = self._rclass(constrain)
                stats_out["replica"] = getattr(fut, "_lsot_replica", "")

    def complete(self, prompt: str, max_new_tokens: Optional[int] = None,
                 sampling: Optional[SamplingParams] = None, seed: int = 0,
                 constrain=None, deadline_s: Optional[float] = None,
                 idempotency_key: Optional[str] = None,
                 tenant: str = "", qos: str = ""):
        from .backends import Completion, trim_stop_texts

        from ..utils import tracing

        ids = self.tokenizer.encode(prompt, add_bos=self.add_bos)
        t_submit = time.perf_counter()
        on_tok, first_at = _first_token_timer()
        kwargs = {}
        if idempotency_key is not None:
            # Only the supervised scheduler takes the key (journal dedup);
            # GenerationService gates on supports_idempotency before
            # forwarding, so a bare scheduler never sees the kwarg.
            kwargs["idempotency_key"] = idempotency_key
        fut = self.scheduler.submit(
            ids, max_new_tokens=self._budget(len(ids), max_new_tokens),
            sampling=sampling or self.sampling, seed=seed, on_token=on_tok,
            **self._constraint_kwargs(constrain),
            deadline_s=deadline_s if deadline_s is not None
            else self.deadline_s,
            trace=tracing.current(),
            **kwargs, **self._model_kwargs(),
            **self._qos_kwargs(tenant, qos),
        )
        out = fut.result()
        text = trim_stop_texts(self.tokenizer.decode(out), self.stop_texts)
        return Completion(text=text, output_tokens=len(out),
                          prompt_tokens=len(ids),
                          ttft_s=(first_at[0] - t_submit) if first_at else 0.0,
                          queue_wait_s=getattr(fut, "_lsot_queue_wait", 0.0),
                          rclass=self._rclass(constrain),
                          replica=getattr(fut, "_lsot_replica", ""))

    def complete_batch(
        self, prompts: Sequence[str], max_new_tokens: Optional[int] = None,
        sampling: Optional[SamplingParams] = None, seed: int = 0,
        constrain=None, deadline_s: Optional[float] = None,
        tenant: str = "", qos: str = "",
    ):
        """Submit the whole batch at once: the scheduler interleaves the
        prompts through its slot pool, so this IS continuous batching —
        unlike EngineBackend's single padded program, raggedness costs
        nothing beyond bucketing."""
        from .backends import Completion, trim_stop_texts

        constraint_kwargs = self._constraint_kwargs(constrain)
        effective_deadline = (deadline_s if deadline_s is not None
                              else self.deadline_s)
        ids_list = [
            self.tokenizer.encode(p, add_bos=self.add_bos) for p in prompts
        ]
        t_submit = time.perf_counter()
        timers = [_first_token_timer() for _ in ids_list]
        futs = [
            self.scheduler.submit(
                ids, max_new_tokens=self._budget(len(ids), max_new_tokens),
                sampling=sampling or self.sampling, seed=seed,
                on_token=on_tok, **constraint_kwargs,
                deadline_s=effective_deadline, **self._model_kwargs(),
                **self._qos_kwargs(tenant, qos),
            )
            for ids, (on_tok, _) in zip(ids_list, timers)
        ]
        firsts = [fl for _, fl in timers]
        completions = []
        for ids, fut, fl in zip(ids_list, futs, firsts):
            out = fut.result()
            text = trim_stop_texts(self.tokenizer.decode(out), self.stop_texts)
            completions.append(Completion(
                text=text, output_tokens=len(out), prompt_tokens=len(ids),
                ttft_s=(fl[0] - t_submit) if fl else 0.0,
                queue_wait_s=getattr(fut, "_lsot_queue_wait", 0.0),
                rclass=self._rclass(constrain),
                replica=getattr(fut, "_lsot_replica", ""),
            ))
        return completions
