"""Per-model prompt templating: (system, prompt) → the string the model sees.

Ollama applies a model-family-specific template before llama.cpp tokenizes
(the reference relies on this implicitly at every `ollama.generate(system=...,
prompt=...)` call site — reference `FastAPI/app.py:85-90,105-111`). Getting
the template wrong silently degrades SQL quality (SURVEY.md §7 "hard parts"),
so templates are explicit, named, and unit-tested here.
"""

from __future__ import annotations

from typing import Callable, Dict

Template = Callable[[str, str], str]


def completion_template(system: str, prompt: str) -> str:
    """Plain system+prompt concatenation — the duckdb-nsql / base-model shape
    (a completion model fine-tuned to continue schema+question with SQL)."""
    if not system:
        return prompt
    return f"{system}\n\n{prompt}"


def llama3_chat_template(system: str, prompt: str) -> str:
    """Llama-3 instruct chat format (header/eot special-token strings; the
    HF tokenizer maps them to their special ids)."""
    parts = ["<|begin_of_text|>"]
    if system:
        parts.append(
            f"<|start_header_id|>system<|end_header_id|>\n\n{system}<|eot_id|>"
        )
    parts.append(
        f"<|start_header_id|>user<|end_header_id|>\n\n{prompt}<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )
    return "".join(parts)


def mistral_instruct_template(system: str, prompt: str) -> str:
    """Mistral [INST] format; system folds into the first instruction."""
    body = f"{system}\n\n{prompt}" if system else prompt
    return f"[INST] {body} [/INST]"


TEMPLATES: Dict[str, Template] = {
    "completion": completion_template,
    "llama3-chat": llama3_chat_template,
    "mistral-instruct": mistral_instruct_template,
}
