"""Client adapter for a LIVE Ollama server — score the reference's own
engine with the in-tree instrument.

The reference measures its models by calling `ollama.generate(...)` against
a local Ollama daemon (reference `Model_Evaluation_&_Comparision.py:69,83`;
`Flask/app.py:102-107`). This adapter exposes that daemon through the same
duck-typed service surface the eval harness and BASELINE configs consume
(`generate` / `generate_batch` / `models` — serve/service.py), so an
operator with the reference's exact setup can run

    python -m llm_based_apache_spark_optimization_tpu.evalh \
        --backend ollama --ollama-url http://127.0.0.1:11434

and get the reference engine's quality/latency in the SAME report tables as
the in-tree TPU engine — the apples-to-apples comparison the reference's
DOCX tables could never offer its readers.

Wire protocol (the subset ollama-python uses): POST /api/generate with
{model, prompt, system, stream: false, options:{num_predict, temperature,
top_p, top_k, seed}}; GET /api/tags for the model list. stdlib urllib only
— no client library needed, and the in-tree WSGI fake in the tests speaks
the same two routes.

`generate_batch` loops sequentially on purpose: Ollama serializes requests
(the reference's own serving behavior — `FastAPI/app.py:85-90` notes), and
reporting a fake batched wall-clock would flatter it.
"""

from __future__ import annotations

import json
import random
import time
import urllib.request
from typing import List, Optional

from ..utils.faults import FAULTS, InjectedFault
from .resilience import CircuitBreaker, RetryPolicy
from .service import GenerateResult


class OllamaClientService:
    """Duck-typed GenerationService over a live Ollama HTTP endpoint.

    Fault tolerance (serve/resilience.py): connect-phase failures — the
    request never reached the daemon, so replaying cannot double-generate —
    retry with capped exponential backoff + full jitter; repeated failures
    open a per-service circuit breaker so a down daemon sheds calls
    instantly (CircuitOpen) instead of burning a connect timeout per
    request. HTTP error responses (the daemon answered: model not found,
    bad request) are NEVER retried and count as breaker successes — the
    dependency is up. Chaos seam: `ollama:connect` (utils/faults.py)."""

    def __init__(self, base_url: str = "http://127.0.0.1:11434",
                 timeout_s: float = 300.0,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
        )
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            f"ollama {self.base_url}", failure_threshold=5, reset_after_s=10.0,
        )
        self._rng = random.Random()  # backoff jitter only
        # Kept for surface parity with GenerationService consumers that
        # read .stats (the /models route); remote requests are accounted
        # by the harness itself.
        self.stats: dict = {}

    # ----------------------------------------------------------- plumbing

    @staticmethod
    def _connect_phase(e: BaseException) -> bool:
        """Safe to retry: the request never reached the daemon. HTTPError
        subclasses URLError but IS a server response — excluded."""
        import urllib.error

        return isinstance(
            e, (urllib.error.URLError, InjectedFault, OSError)
        ) and not isinstance(e, urllib.error.HTTPError)

    def _open(self, req) -> dict:
        # Surface the server's JSON error body ("model 'x' not found",
        # overload, ...) instead of a bare HTTPError traceback that aborts
        # a multi-model report with no explanation.
        import urllib.error

        if not self._breaker.allow():
            raise self._breaker.shed()

        def attempt() -> dict:
            # Duration-valued stall seam (`ollama:stall:p:secs`): a daemon
            # that accepts the connection and answers SLOWLY — the check
            # sleeps, then the request proceeds, so deadline/timeout
            # handling above this call is exercised against real elapsed
            # time instead of an instant error.
            FAULTS.check("ollama:stall")
            FAULTS.check("ollama:connect")
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.load(r)

        try:
            out = self._retry.call(
                attempt, retryable=self._connect_phase, rng=self._rng,
            )
        except urllib.error.HTTPError as e:
            # The daemon responded: it is UP (breaker-wise), the request
            # itself is bad.
            self._breaker.record_success()
            body = e.read().decode(errors="replace")[:500]
            raise RuntimeError(
                f"ollama server returned {e.code} for "
                f"{getattr(req, 'full_url', req)}: {body}"
            ) from e
        except (urllib.error.URLError, OSError) as e:
            self._breaker.record_failure()
            reason = getattr(e, "reason", e)
            raise RuntimeError(
                f"cannot reach ollama at {self.base_url}: {reason} — is "
                f"the daemon running (`ollama serve`)?"
            ) from e
        except Exception:
            # Anything else (e.g. a 200 with a non-JSON body: proxy error
            # page, truncated response) is still an unhealthy dependency —
            # and EVERY outcome must be recorded: a half-open probe that
            # escaped both clauses above would otherwise keep its permit
            # (_probing) forever and wedge the breaker open.
            self._breaker.record_failure()
            raise
        self._breaker.record_success()
        return out

    def _get(self, path: str) -> dict:
        return self._open(self.base_url + path)

    def _post(self, path: str, payload: dict) -> dict:
        return self._open(urllib.request.Request(
            self.base_url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        ))

    # ------------------------------------------------------------ surface

    def models(self) -> List[str]:
        return sorted(m["name"] for m in self._get("/api/tags")
                      .get("models", []))

    def generate(
        self,
        model: str,
        prompt: str,
        system: str = "",
        max_new_tokens: Optional[int] = None,
        sampling=None,
        seed: int = 0,
    ) -> GenerateResult:
        # sampling=None means GREEDY everywhere in-tree (SamplingParams
        # defaults temperature=0) — send that explicitly: Ollama's own
        # default is ~0.8, and letting it sample while the in-tree rows
        # decode greedily would make the side-by-side table stochastic
        # and skewed.
        options: dict = {"seed": seed, "temperature": 0.0}
        if max_new_tokens is not None:
            options["num_predict"] = int(max_new_tokens)
        if sampling is not None:
            options["temperature"] = float(sampling.temperature)
            options["top_p"] = float(sampling.top_p)
            if sampling.top_k:
                options["top_k"] = int(sampling.top_k)
        t0 = time.perf_counter()
        data = self._post("/api/generate", {
            "model": model,
            "prompt": prompt,
            "system": system,
            "stream": False,
            "options": options,
        })
        latency = time.perf_counter() - t0
        # eval_count is Ollama's own output-token count; fall back to a
        # whitespace estimate for servers that omit it.
        toks = int(data.get("eval_count") or
                   max(1, len(str(data.get("response", "")).split())))
        return GenerateResult(
            response=str(data.get("response", "")),
            model=model,
            latency_s=latency,
            output_tokens=toks,
        )

    def generate_batch(
        self,
        model: str,
        prompts: List[str],
        system: str = "",
        max_new_tokens: Optional[int] = None,
        sampling=None,
        seed: int = 0,
    ) -> List[GenerateResult]:
        # Sequential on purpose (module docstring): the measured wall IS
        # the reference engine's serialized serving behavior. Request i's
        # submitted-together latency is therefore the CUMULATIVE wall
        # through i (it waited for requests 0..i-1 first), not the whole
        # chunk's sum — stamping every member with the total inflated the
        # reference engine's avg_latency_s ~batch/2x in the side-by-side
        # tables this adapter exists to keep honest (ADVICE.md r5 #1).
        # Contract the harness reads: results[-1].latency_s IS the chunk
        # wall (equals the shared batch wall GenerationService stamps on
        # every member), which evaluate_model_batched sums for
        # aggregate tok/s.
        results: List[GenerateResult] = []
        wall = 0.0
        for p in prompts:
            r = self.generate(model, p, system, max_new_tokens, sampling,
                              seed)
            wall += r.latency_s
            results.append(GenerateResult(
                response=r.response, model=r.model, latency_s=wall,
                output_tokens=r.output_tokens,
            ))
        return results

    def close(self) -> None:  # surface parity; nothing to shut down
        pass
