"""Model registry + multi-model serving assembly (ISSUE 16).

The source paper runs TWO models — `duckdb-nsql` writes the SQL and
`llama3.2` explains Spark stack traces — but until this subsystem the
fleet served exactly one checkpoint and the explainer leg aliased the
SQL model's weights. This module owns:

- `ModelSpec` / `parse_models_spec`: the `LSOT_MODELS` env spec — which
  checkpoints are co-resident, where each loads from (`tiny` random
  weights for tests, `hf` safetensors, `gguf`), what share of the paged
  KV arena each may hold, and which chat template wraps its prompts.
- `partition_pages`: split ONE page budget between co-resident
  checkpoints proportional to their `hbm` fractions — the two models
  live in one process and must not size their arenas independently
  against the same HBM.
- `ModelRegistry`: id → spec lookup with the typed `UnknownModel`
  error the scheduler pool raises when a request names a model no
  replica carries (api.py maps ValueError → 400, so a bad model id is
  a client error, never a scheduler crash).
- `build_tiny_model_service`: the proof-harness assembly — one
  scheduler per registered tiny model, all in ONE `SchedulerPool`
  routing on `model_id`, one `SchedulerBackend` per model sharing that
  pool. Tests, `scripts/multimodel_smoke.sh` and the bench
  `multi_model` leg all stand their fleets up through this.

Routing itself lives in `scheduler.SchedulerPool` (the `model_id` axis
beside `phase_role`, flag-gated by `LSOT_POOL_MODELS`); this module is
the registry + assembly layer above it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence


class UnknownModel(ValueError):
    """A request named a model_id no replica in the fleet carries.

    Subclasses ValueError so the API layer's existing `except ValueError
    → 400` mapping turns it into a typed client error instead of a 500
    (or worse, a SchedulerCrashed shed) — the "unregistered model"
    failure mode is the requester's bug, not the fleet's.
    """


_SOURCES = ("tiny", "hf", "gguf")


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """One registered model: identity, checkpoint source, HBM share."""

    model_id: str
    #: "tiny" (random test weights, no path), "hf" (safetensors dir),
    #: "gguf" (single-file checkpoint).
    source: str = "tiny"
    #: Checkpoint location; required for hf/gguf, ignored for tiny.
    path: str = ""
    #: Share of the co-resident paged KV arena (0 < f <= 1). Specs in a
    #: fleet are normalized together — see `partition_pages`.
    hbm_fraction: float = 0.0
    #: Chat template name for GenerationService.register ("" = raw
    #: completion prompt — the duckdb-nsql shape).
    template: str = ""
    #: Replicas of this model in the pool.
    replicas: int = 1
    #: Whether the backend prepends BOS (llama3-chat renders its own).
    add_bos: bool = True

    def validate(self) -> "ModelSpec":
        if not self.model_id:
            raise ValueError("model spec needs a non-empty model id")
        if self.source not in _SOURCES:
            raise ValueError(
                f"model {self.model_id!r}: unknown source "
                f"{self.source!r} (expected one of {_SOURCES})"
            )
        if self.source in ("hf", "gguf") and not self.path:
            raise ValueError(
                f"model {self.model_id!r}: source {self.source!r} "
                f"needs a checkpoint path (model=source:/path)"
            )
        if self.hbm_fraction < 0 or self.hbm_fraction > 1:
            raise ValueError(
                f"model {self.model_id!r}: hbm fraction must be in "
                f"(0, 1], got {self.hbm_fraction}"
            )
        if self.replicas < 1:
            raise ValueError(
                f"model {self.model_id!r}: replicas must be >= 1, "
                f"got {self.replicas}"
            )
        return self


def parse_models_spec(spec: str) -> List[ModelSpec]:
    """Parse `LSOT_MODELS` — the multi-model fleet description.

    Format: `;`-separated entries, each
    `model_id=source[:path][,hbm=F][,template=T][,replicas=N][,add_bos=B]`

        LSOT_MODELS="duckdb-nsql=tiny,hbm=0.75;llama3.2=tiny,hbm=0.25,template=llama3-chat,add_bos=0"
        LSOT_MODELS="sql=hf:/ckpts/nsql,hbm=0.8;explainer=gguf:/ckpts/tiny.gguf,hbm=0.2"

    `tiny` needs no path. Omitted `hbm` fractions split whatever the
    explicit ones left over, equally. Explicit fractions summing past
    1.0 are a config error (two models cannot both hold 80% of one
    arena). Duplicate ids are a config error.
    """
    out: List[ModelSpec] = []
    seen: set = set()
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if "=" not in raw:
            raise ValueError(
                f"LSOT_MODELS entry {raw!r}: expected "
                f"model_id=source[:path][,k=v...]"
            )
        model_id, rest = raw.split("=", 1)
        model_id = model_id.strip()
        parts = [p.strip() for p in rest.split(",")]
        src = parts[0]
        source, _, path = src.partition(":")
        source = source.strip().lower()
        kw: Dict[str, object] = {}
        for opt in parts[1:]:
            if not opt:
                continue
            if "=" not in opt:
                raise ValueError(
                    f"LSOT_MODELS entry {model_id!r}: option {opt!r} "
                    f"is not k=v"
                )
            k, v = (x.strip() for x in opt.split("=", 1))
            if k == "hbm":
                kw["hbm_fraction"] = float(v)
            elif k == "template":
                kw["template"] = v
            elif k == "replicas":
                kw["replicas"] = int(v)
            elif k == "add_bos":
                kw["add_bos"] = v.lower() in ("1", "true", "yes", "on")
            else:
                raise ValueError(
                    f"LSOT_MODELS entry {model_id!r}: unknown option "
                    f"{k!r} (expected hbm/template/replicas/add_bos)"
                )
        ms = ModelSpec(model_id=model_id, source=source,
                       path=path.strip(), **kw).validate()
        if ms.model_id in seen:
            raise ValueError(
                f"LSOT_MODELS: duplicate model id {ms.model_id!r}"
            )
        seen.add(ms.model_id)
        out.append(ms)
    if not out:
        return out
    # Normalize the HBM shares: explicit fractions must leave room for
    # every unspecified model; the leftovers split equally.
    explicit = sum(m.hbm_fraction for m in out if m.hbm_fraction > 0)
    free = [m for m in out if m.hbm_fraction <= 0]
    if explicit > 1.0 + 1e-9:
        raise ValueError(
            f"LSOT_MODELS: hbm fractions sum to {explicit:.3f} > 1.0"
        )
    if free:
        remaining = max(0.0, 1.0 - explicit)
        if remaining <= 1e-9:
            raise ValueError(
                "LSOT_MODELS: explicit hbm fractions leave no arena "
                f"for {[m.model_id for m in free]}"
            )
        share = remaining / len(free)
        out = [dataclasses.replace(m, hbm_fraction=share)
               if m.hbm_fraction <= 0 else m for m in out]
    return out


def partition_pages(total_pages: int,
                    specs: Sequence[ModelSpec]) -> Dict[str, int]:
    """Split one paged-KV arena budget between co-resident models.

    Proportional to `hbm_fraction`, floored, remainder to the largest
    share — and every model gets at least one page when the budget can
    hold one per model (a 5%-share explainer beside a 6-slot SQL model
    must still be able to admit a request).
    """
    if total_pages < len(specs):
        raise ValueError(
            f"page budget {total_pages} cannot hold one page per "
            f"model ({len(specs)} registered)"
        )
    shares = {m.model_id: int(total_pages * m.hbm_fraction)
              for m in specs}
    for mid in shares:
        shares[mid] = max(1, shares[mid])
    # Hand the rounding remainder (or claw back an over-allocation from
    # the minimum-1 floor) to/from the largest-share models.
    order = sorted(specs, key=lambda m: -m.hbm_fraction)
    spare = total_pages - sum(shares.values())
    i = 0
    while spare != 0 and order:
        mid = order[i % len(order)].model_id
        if spare > 0:
            shares[mid] += 1
            spare -= 1
        elif shares[mid] > 1:
            shares[mid] -= 1
            spare += 1
        i += 1
        if i > 4 * len(order) * max(1, abs(spare)):
            break  # degenerate budget; shares are as close as they get
    return shares


class ModelRegistry:
    """id → ModelSpec lookup for one fleet."""

    def __init__(self, specs: Sequence[ModelSpec] = ()):
        self._specs: Dict[str, ModelSpec] = {}
        for m in specs:
            if m.model_id in self._specs:
                raise ValueError(f"duplicate model id {m.model_id!r}")
            self._specs[m.model_id] = m.validate()

    def __contains__(self, model_id: str) -> bool:
        return model_id in self._specs

    def __len__(self) -> int:
        return len(self._specs)

    def ids(self) -> List[str]:
        return list(self._specs)

    def get(self, model_id: str) -> ModelSpec:
        try:
            return self._specs[model_id]
        except KeyError:
            raise UnknownModel(
                f"model {model_id!r} is not registered "
                f"(registered: {sorted(self._specs)})"
            ) from None

    def specs(self) -> List[ModelSpec]:
        return list(self._specs.values())


def build_tiny_model_service(
    specs: Sequence[ModelSpec],
    *,
    num_slots: int = 2,
    max_seq: int = 512,
    decode_chunk: int = 4,
    prompt_bucket: int = 8,
    kv_page_size: int = 8,
    total_pages: int = 0,
    max_new_tokens: int = 48,
    supervise: bool = False,
    seed: int = 0,
):
    """Stand up a co-resident multi-model fleet on tiny random weights.

    One paged `ContinuousBatchingScheduler` per (model, replica) — each
    stamped with its `model_id` and sized to its `partition_pages`
    share of ONE arena budget — all in ONE `SchedulerPool` that routes
    on model, plus one `SchedulerBackend` per model submitting through
    that shared pool. Returns `(service, pool, registry)`; shutting
    down the pool shuts down every scheduler.

    This is the test/smoke/bench harness for the multi-model subsystem:
    production fleets assemble through `app/__main__.py`'s checkpoint
    path with real `hf`/`gguf` sources instead.
    """
    import jax
    import jax.numpy as jnp

    from ..models import TINY, init_params
    from ..tokenizer import ByteTokenizer
    from .scheduler import (
        ContinuousBatchingScheduler,
        SchedulerBackend,
        SchedulerPool,
    )
    from .service import GenerationService

    specs = [m.validate() for m in specs]
    if not specs:
        raise ValueError("need at least one model spec")
    for m in specs:
        if m.source != "tiny":
            raise ValueError(
                f"build_tiny_model_service only builds tiny sources; "
                f"{m.model_id!r} is {m.source!r}"
            )
    n_replicas = sum(m.replicas for m in specs)
    # Default arena: enough for every replica to hold a full slot
    # complement, partitioned by the models' HBM fractions.
    pages_per_slot = -(-max_seq // kv_page_size)
    if total_pages <= 0:
        total_pages = n_replicas * num_slots * pages_per_slot
    shares = partition_pages(total_pages, specs)

    # TINY's CI context is smaller than a schema prompt; a longer
    # context costs nothing (rope tables are computed on the fly).
    cfg = dataclasses.replace(TINY, max_seq_len=max(TINY.max_seq_len,
                                                    2 * max_seq))
    tok = ByteTokenizer()
    scheds = []
    for idx, m in enumerate(specs):
        # Distinct seed per model: two checkpoints, not one aliased.
        # Derived from the spec POSITION, never hash(model_id) — str
        # hashing is salted per process (PYTHONHASHSEED), which made
        # fleet weights differ run to run and could even collide two
        # models onto ONE set of weights.
        params = init_params(
            cfg, jax.random.key(seed + idx + 1), dtype=jnp.float32,
        )
        per_replica = max(1, shares[m.model_id] // m.replicas)
        for _ in range(m.replicas):
            scheds.append(ContinuousBatchingScheduler(
                cfg, params, num_slots=num_slots,
                decode_chunk=decode_chunk, prompt_bucket=prompt_bucket,
                stop_ids=(2,), max_seq=max_seq,
                kv_layout="paged", kv_page_size=kv_page_size,
                kv_pages=per_replica,
                model_id=m.model_id,
            ))
    pool = SchedulerPool(scheds)
    sched_like = pool
    if supervise:
        from .supervisor import SupervisedScheduler

        sched_like = SupervisedScheduler(pool)
    svc = GenerationService()
    for m in specs:
        backend = SchedulerBackend(
            sched_like, tok, max_new_tokens=max_new_tokens,
            add_bos=m.add_bos, model_id=m.model_id,
        )
        svc.register(m.model_id, backend,
                     template=m.template or "completion")
    return svc, sched_like, ModelRegistry(specs)
