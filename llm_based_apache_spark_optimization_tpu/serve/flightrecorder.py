"""Scheduler flight recorder: a lock-light ring of per-round records.

When a decode loop dies — crash, watchdog-escalated stall, SIGTERM — the
journal says WHAT was in flight, but nothing says what the scheduler was
DOING in the rounds before it died: occupancy, admission/retirement
churn, speculation acceptance, round cadence. BENCH_r04/r05's rc=124
deaths left exactly that hole. The flight recorder is the black box:

- `FlightRecorder.record(**fields)` — one append per HARVESTED round
  (the scheduler's natural bookkeeping instant), into a bounded deque
  under a tiny lock: O(1), no I/O, no serialization on the hot path.
  Capacity defaults from `LSOT_FLIGHT_ROUNDS` (256). Since PR 12 each
  round record also carries the roofline-ledger columns
  (`phase`/`perf_ctx`/`mfu`/`hbm_util`/`bound`, plus
  `prefill_mfu`/`prefill_hbm_util` on rounds that flushed prefill
  chunks) — computed by utils/perfmodel.py from the SAME rounded
  `round_wall_s` that lands in the record, so a reader can recompute
  every utilization figure from the record alone. Disaggregated
  serving (ISSUE 13) adds `pages_migrated`/`handoff_wait_s` columns on
  decode-side rounds that imported a prefill→decode handoff, a
  `handoffs` column on prefill-role pack records, and the
  `handoff_export`/`handoff_import`/`handoff_inplace`/`handoff_place`
  lifecycle events — so a migrated request's timeline explains the gap
  between prefill and its first decode token. Prefix-cache telemetry
  (ISSUE 14) adds a `prefix_reuse` column on rounds that admitted
  requests with at least one full prompt block: one {rid, digest,
  reused, prefilled} row per admission, the per-request attribution the
  tier-1 reconciliation test sums against the scheduler's locked
  counters — and the pool ring gains `prefix_affinity` lookup events.
- `event(kind, **fields)` — sparse lifecycle markers (crash, stall
  escalation, restart, drain, grammar swap) ride the same ring with
  `"kind"` set, so the postmortem shows rounds and lifecycle interleaved
  in time order.
- `snapshot(last=N)` — the live view behind `/debug/flightrecorder`.
- `dump(path)` / module-level `append_jsonl(path, records)` — the
  postmortem JSONL write path; the supervisor routes its merged
  header+rounds+traces dump through `append_jsonl` next to the journal
  spill on crash/stall/SIGTERM.

Every record carries the recorder's `replica` label, so a
`SchedulerPool`'s merged view attributes load to the replica that bore
it — the placement-signal feed the ROADMAP's load-aware multi-replica
item needs.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["FlightRecorder", "append_jsonl", "default_capacity",
           "merge_snapshots"]


#: App-startup override (AppConfig.flight_rounds → reconfigure()); None
#: falls through to the LSOT_FLIGHT_ROUNDS env read below.
_DEFAULT_ROUNDS: Optional[int] = None


def reconfigure(rounds: Optional[int]) -> None:
    """App-startup wiring seam (AppConfig.flight_rounds): set the default
    ring size recorders constructed AFTER this call will use — the same
    pattern as `tracing.TRACER.reconfigure`, so `AppConfig(flight_rounds=
    1024)` is honored, not a silent no-op."""
    global _DEFAULT_ROUNDS
    _DEFAULT_ROUNDS = int(rounds) if rounds else None


def default_capacity() -> int:
    """Ring size: AppConfig.flight_rounds when wired via `reconfigure()`,
    else LSOT_FLIGHT_ROUNDS (default 256 rounds ≈ a few seconds of
    context at serving cadence, a few KB of host memory)."""
    if _DEFAULT_ROUNDS is not None:
        return max(8, _DEFAULT_ROUNDS)
    try:
        n = int(os.environ.get("LSOT_FLIGHT_ROUNDS", "256"))
    except ValueError:
        n = 256
    return max(8, n)


def merge_snapshots(sources, last: Optional[int] = None) -> List[Dict]:
    """Merge several sources' flight records in time order — THE merge
    contract (ts ordering, trailing last-N slice), shared by
    SupervisedScheduler, SchedulerPool, and SchedulerBackend instead of
    three hand-rolled copies. A source may be a FlightRecorder, expose
    `flight_snapshot(last)` (nested merged views compose), or carry a
    `.flight` recorder; None sources are skipped."""
    merged: List[Dict] = []
    for src in sources:
        if src is None:
            continue
        if isinstance(src, FlightRecorder):
            merged.extend(src.snapshot(last))
            continue
        snap = getattr(src, "flight_snapshot", None)
        if callable(snap):
            merged.extend(snap(last))
            continue
        fl = getattr(src, "flight", None)
        if fl is not None:
            merged.extend(fl.snapshot(last))
    merged.sort(key=lambda r: r.get("ts", 0.0))
    return merged[-last:] if last else merged


def append_jsonl(path: str, records: List[Dict]) -> int:
    """Append dict records to a JSONL file: makedirs, append mode, never
    raises. THE postmortem write path — `FlightRecorder.dump` and the
    supervisor's merged header+rounds+traces dump both go through here,
    so hardening it (fsync, rotation, redaction) lands everywhere at
    once, and a write failure can never turn a crash into a second
    crash. Returns records written (0 on failure)."""
    lines = [json.dumps(r) for r in records]
    if not lines:
        return 0
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
    except OSError:
        return 0
    return len(lines)


class FlightRecorder:
    """Bounded in-memory ring of per-round + lifecycle records."""

    def __init__(self, capacity: Optional[int] = None, replica: str = "r0"):
        self.replica = replica
        self._lock = threading.Lock()
        self._ring: "deque[Dict]" = deque(
            maxlen=capacity if capacity else default_capacity()
        )
        self._seq = 0
        self._dropped = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def record(self, **fields) -> None:
        """Append one per-round record. Hot path: one lock, one dict, one
        deque append — bench's scheduler leg prices it (`observability`
        key) so the recorder's tax is a number, not an assumption."""
        rec = {"ts": time.time(), "replica": self.replica, **fields}
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(rec)

    def event(self, kind: str, **fields) -> None:
        """Lifecycle marker (crash/stall/restart/drain/...): same ring,
        tagged, so postmortems read rounds and lifecycle in one timeline."""
        self.record(kind=kind, **fields)

    def snapshot(self, last: Optional[int] = None) -> List[Dict]:
        """Newest-last copy of the ring (optionally only the last N)."""
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last else out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "records": len(self._ring),
                "capacity": self._ring.maxlen or 0,
                "total": self._seq,
                "overwritten": self._dropped,
            }

    def dump(self, path: str, last: Optional[int] = None) -> int:
        """Write the ring as JSONL via `append_jsonl` (append mode: a
        postmortem may merge several recorders — supervisor lifecycle +
        inner rounds — into one file)."""
        return append_jsonl(path, self.snapshot(last))
