"""Liveness primitives: decode-loop heartbeats and stall thresholds.

The supervisor (serve/supervisor.py) made the serving stack crash-only —
but only for failures that *raise*. A wedged decode loop (hung XLA
dispatch, a stuck device tunnel, a dependency that accepts the connection
and never answers) is invisible to exception-based recovery: queued
requests sit until their deadlines burn, streams go silent, and `/readyz`
keeps reporting `ready`. BENCH_r04/r05 died exactly this way (rc=124 on a
hung chip tunnel), and the drain path's deadline exists precisely because
"an unbounded wait on a wedged loop is exactly the hang".

This module is the detection half of the fix:

- `Heartbeat` — a tiny thread-safe stamp the scheduler's decode loop
  touches at the top of every event-loop iteration (`stamp(busy=...)`),
  plus a `round_done()` tick per harvested decode round that feeds an
  EWMA of round intervals. `age()` is the time since the loop last proved
  it was alive; `expected_round_s()` is the loop's own measured cadence.
  A wedge inside a jax call stops the stamping, so age grows while the
  EWMA remembers what a healthy round cost — which is what makes the
  stall threshold workload-relative instead of a magic constant.
- `stall_threshold(hb, factor, floor_s)` — the escalation bar:
  `max(floor_s, factor × expected_round_s)`. The floor keeps cold loops
  (no EWMA yet) and sub-millisecond CPU rounds from tripping on scheduler
  jitter; the factor scales with the measured round time so a 7B chip
  deployment is judged by ITS cadence, not a laptop's.
- `CombinedHeartbeat` — a read-only view over several heartbeats (the
  `SchedulerPool` case): `busy` if ANY replica is busy, `age()` is the
  oldest busy replica's age — one wedged replica must trip the monitor
  even while its siblings stay fresh.

The enforcement half lives in `SupervisedScheduler`: a monitor thread
compares heartbeat age against the threshold and escalates a wedge to a
synthetic `SchedulerStalled` (serve/resilience.py), tripping the existing
restart/journal/replay machinery. Stamping cost is measured by bench.py's
scheduler leg (`watchdog_overhead`) so the liveness tax on the hot path
is a number, not an assumption.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

__all__ = ["CombinedHeartbeat", "Heartbeat", "stall_threshold"]


class Heartbeat:
    """Progress stamp for one event loop. `stamp(busy)` at the top of
    every loop iteration (cheap: a lock + three stores); `round_done()`
    once per harvested decode round to feed the round-interval EWMA.
    Readers (the supervisor's monitor thread, /metrics) see a coherent
    (time, busy) pair."""

    __slots__ = ("_lock", "_last", "_busy", "_beats", "_rounds",
                 "_last_round", "_round_ewma", "_alpha")

    def __init__(self, alpha: float = 0.2):
        self._lock = threading.Lock()
        self._last = time.monotonic()
        self._busy = False
        self._beats = 0
        self._rounds = 0
        self._last_round: Optional[float] = None
        self._round_ewma: Optional[float] = None
        self._alpha = alpha

    def stamp(self, busy: bool) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._busy = busy
            self._beats += 1
            if not busy:
                # Idle gap: the next harvested round's interval must not
                # feed the cadence EWMA — one hour of quiet before a
                # request would otherwise inflate expected_round_s (and
                # with it the stall threshold) by orders of magnitude,
                # silently disabling detection for the burst that follows.
                # The EWMA itself persists: it remembers what a healthy
                # round cost in the last busy period.
                self._last_round = None

    def round_done(self) -> None:
        """One decode round harvested: progress, and a cadence sample."""
        now = time.monotonic()
        with self._lock:
            if self._last_round is not None:
                dt = now - self._last_round
                self._round_ewma = (
                    dt if self._round_ewma is None
                    else self._alpha * dt + (1 - self._alpha) * self._round_ewma
                )
            self._last_round = now
            self._rounds += 1
            # A harvested round is also a liveness proof in its own right.
            self._last = now
            self._beats += 1

    def age(self) -> float:
        with self._lock:
            return time.monotonic() - self._last

    @property
    def busy(self) -> bool:
        with self._lock:
            return self._busy

    @property
    def rounds(self) -> int:
        with self._lock:
            return self._rounds

    def expected_round_s(self) -> Optional[float]:
        """EWMA of intervals between harvested rounds (None until two
        rounds have completed) — the loop's own measured cadence, the
        base the stall threshold scales from."""
        with self._lock:
            return self._round_ewma

    def snapshot(self) -> Dict[str, object]:
        """/metrics payload: age, busy flag, round count, cadence."""
        with self._lock:
            ewma = self._round_ewma
            return {
                "age_s": round(time.monotonic() - self._last, 3),
                "busy": self._busy,
                "rounds": self._rounds,
                "expected_round_s": (round(ewma, 4)
                                     if ewma is not None else None),
            }


class CombinedHeartbeat:
    """Read-only monitor view over several replicas' heartbeats
    (SchedulerPool): one wedged replica must look stale even while its
    siblings keep stamping, so `age()` is the OLDEST busy replica's age
    (falling back to the oldest overall when none is busy) and `busy` is
    any-replica-busy. `expected_round_s` is the slowest replica's cadence
    — the threshold must tolerate the pool's worst healthy round.

    `labels` attributes each heartbeat to its replica ("r{i}" by
    default, the pool's label vocabulary): `snapshot()` carries them,
    and `verdicts(factor, floor_s)` turns the combined view into a
    per-replica stall judgment — the fleet supervisor needs to know
    WHICH replica went stale, not just that the oldest busy one did."""

    def __init__(self, heartbeats: Sequence[Heartbeat],
                 labels: Optional[Sequence[str]] = None):
        if not heartbeats:
            raise ValueError("CombinedHeartbeat needs at least one heartbeat")
        self._hbs = list(heartbeats)
        if labels is not None and len(labels) != len(self._hbs):
            raise ValueError(
                f"got {len(labels)} labels for {len(self._hbs)} heartbeats"
            )
        self.labels = (list(labels) if labels is not None
                       else [f"r{i}" for i in range(len(self._hbs))])

    @property
    def busy(self) -> bool:
        return any(h.busy for h in self._hbs)

    def age(self) -> float:
        busy_ages = [h.age() for h in self._hbs if h.busy]
        return max(busy_ages) if busy_ages else max(
            h.age() for h in self._hbs
        )

    @property
    def rounds(self) -> int:
        return sum(h.rounds for h in self._hbs)

    @property
    def cold(self) -> bool:
        """ANY replica still at zero harvested rounds: the pool is still
        in first-boot compile territory. The summed `rounds` cannot gate
        a warmup grace window — one warmed replica would end the grace
        for siblings whose first cold XLA compile is still blocking
        their loop (and reading as a wedge)."""
        return any(h.rounds == 0 for h in self._hbs)

    def expected_round_s(self) -> Optional[float]:
        vals = [v for v in (h.expected_round_s() for h in self._hbs)
                if v is not None]
        return max(vals) if vals else None

    def verdicts(self, factor: float, floor_s: float) -> List[Dict[str, object]]:
        """Per-replica stall judgment: for each heartbeat, its label, its
        own age/busy, its OWN threshold (each replica is judged by its
        own measured cadence — a slow replica must not lower the bar for
        a fast sibling, nor vice versa), and the verdict: `stalled` is
        True only for a BUSY replica whose age exceeds its threshold.
        This is what makes a pool stall attributable: the combined
        `age()` can say the oldest busy replica is stale, but only the
        verdict list says WHICH — the targeted-restart feed."""
        out = []
        for label, h in zip(self.labels, self._hbs):
            age = h.age()
            busy = h.busy
            threshold = stall_threshold(h, factor, floor_s)
            out.append({
                "replica": label,
                "busy": busy,
                "age_s": round(age, 3),
                "stall_threshold_s": round(threshold, 3),
                "stalled": bool(busy and age > threshold),
            })
        return out

    def snapshot(self) -> Dict[str, object]:
        ewma = self.expected_round_s()
        return {
            "age_s": round(self.age(), 3),
            "busy": self.busy,
            "rounds": self.rounds,
            "expected_round_s": round(ewma, 4) if ewma is not None else None,
            "replicas": [
                {"replica": label, **h.snapshot()}
                for label, h in zip(self.labels, self._hbs)
            ],
        }


def stall_threshold(hb, factor: float, floor_s: float) -> float:
    """Heartbeat age beyond which a BUSY loop counts as wedged:
    `max(floor_s, factor × expected_round_s)`. Both knobs surface as
    LSOT_STALL_FACTOR / LSOT_STALL_MIN_S (app/config.py). The floor must
    sit above the worst LEGITIMATE host-thread occupation — a cold XLA
    compile of an unwarmed prefill bucket blocks the loop exactly like a
    wedge does (run warmup(), or raise LSOT_STALL_MIN_S past the compile
    wall, before tightening it)."""
    ewma = hb.expected_round_s()
    return max(float(floor_s), float(factor) * (ewma or 0.0))
