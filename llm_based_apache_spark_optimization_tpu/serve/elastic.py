"""Elastic fleet membership (ISSUE 17): scale the decode tier from the
signals the fleet already exports.

`FleetAutoscaler` watches three live signals — queue-depth EWMA per
serving replica, SLO burn (`utils/slo.ENGINE`), and KV pressure
(`pages_withheld` in the pool's page stats) — and grows/shrinks the
fleet through `SchedulerPool.add_replica` / `retire_replica` within
[`LSOT_FLEET_MIN`, `LSOT_FLEET_MAX`]. Hysteresis is explicit: a scale
signal must hold continuously for `LSOT_SCALE_HOLD_S` before the
autoscaler acts, and consecutive actions are separated by at least
`LSOT_SCALE_INTERVAL_S` — a bursty queue cannot flap the fleet.

Scale-UP pops a replica from the `spawn` callable (the app wires it to
standby worker addresses — `serve/factory.py:standby_spawner` — or an
in-process factory in tests); a spawn attempt consults the fault
registry's `fleet:spawn` site first, so chaos can prove a failed spawn
degrades to "keep serving on the current fleet" rather than wedging the
control loop. Scale-DOWN rides `drain_replica` (drain → re-place →
remove): zero acknowledged requests are lost, and only replicas the
autoscaler itself added are eligible — an operator-configured replica
never retires.

`step(now)` is deterministic (explicit clock) for tests and chaos;
`run()`/`stop()` wrap it in a daemon thread for the app.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, Optional

from ..utils.faults import FAULTS, InjectedFault

_log = logging.getLogger("lsot.elastic")


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FleetAutoscaler:
    """Signal-driven membership control for a `SchedulerPool` (or a
    `SupervisedScheduler` wrapping one — the fleet passthroughs make the
    two interchangeable here)."""

    def __init__(
        self,
        pool,
        spawn: Callable[[], object],
        *,
        fleet_min: Optional[int] = None,
        fleet_max: Optional[int] = None,
        scale_up_q: Optional[float] = None,
        scale_down_q: Optional[float] = None,
        hold_s: Optional[float] = None,
        interval_s: Optional[float] = None,
        drain_deadline_s: float = 30.0,
        ewma_alpha: float = 0.5,
    ):
        self.pool = pool
        self._spawn = spawn
        base = self._fleet_size()
        self.fleet_min = int(fleet_min if fleet_min is not None
                             else _env_f("LSOT_FLEET_MIN", base))
        self.fleet_max = int(fleet_max if fleet_max is not None
                             else _env_f("LSOT_FLEET_MAX", base + 4))
        if self.fleet_max < self.fleet_min:
            raise ValueError(
                f"LSOT_FLEET_MAX={self.fleet_max} < "
                f"LSOT_FLEET_MIN={self.fleet_min}")
        # Queue-depth thresholds are PER SERVING REPLICA (queued requests
        # / serving count), so the same knob works for a 2- and a
        # 20-replica fleet.
        self.scale_up_q = float(scale_up_q if scale_up_q is not None
                                else _env_f("LSOT_SCALE_UP_Q", 4.0))
        self.scale_down_q = float(scale_down_q if scale_down_q is not None
                                  else _env_f("LSOT_SCALE_DOWN_Q", 0.5))
        self.hold_s = float(hold_s if hold_s is not None
                            else _env_f("LSOT_SCALE_HOLD_S", 3.0))
        self.interval_s = float(interval_s if interval_s is not None
                                else _env_f("LSOT_SCALE_INTERVAL_S", 5.0))
        self.drain_deadline_s = float(drain_deadline_s)
        self._alpha = float(ewma_alpha)
        self._ewma: Optional[float] = None
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._cooldown_until = 0.0
        self._stats = {"steps": 0, "ups": 0, "downs": 0,
                       "spawn_failures": 0, "spawn_empty": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ signals

    def _fleet_size(self) -> int:
        fs = getattr(self.pool, "fleet_stats", None)
        if callable(fs):
            stats = fs() or {}
            return int(stats.get("serving", stats.get("size", 1)) or 1)
        return 1

    def _signals(self) -> Dict[str, object]:
        """One read of the fleet's exported state → the three scale
        signals. Every read is best-effort: a churning fleet mid-read
        must not crash the control loop."""
        queued = 0
        serving = max(1, self._fleet_size())
        loads = getattr(self.pool, "replica_loads", None)
        if callable(loads):
            try:
                for rec in loads() or []:
                    queued += int(rec.get("queued", 0) or 0)
            except Exception:  # noqa: BLE001 — churning fleet mid-read
                pass
        depth = queued / serving
        self._ewma = (depth if self._ewma is None
                      else self._alpha * depth
                      + (1.0 - self._alpha) * self._ewma)
        burning = False
        try:
            from ..utils import slo as _slo

            burning = bool(_slo.ENGINE.enabled and _slo.ENGINE.burning())
        except Exception:  # noqa: BLE001 — SLO engine optional
            pass
        pressure = False
        try:
            pstats = getattr(self.pool, "page_stats", None)
            if pstats:
                pressure = int(pstats.get("pages_withheld", 0) or 0) > 0
        except Exception:  # noqa: BLE001 — churning fleet mid-read
            pass
        return {"queue_ewma": round(self._ewma, 4), "slo_burn": burning,
                "kv_pressure": pressure, "serving": serving}

    # ------------------------------------------------------------ control

    def step(self, now: float) -> Optional[str]:
        """One control tick at time `now` (monotonic seconds; explicit so
        tests and chaos drive the hysteresis deterministically). Returns
        "up"/"down" when an action fired, else None."""
        self._stats["steps"] += 1
        sig = self._signals()
        self._last_signal = sig
        serving = int(sig["serving"])
        want_up = (serving < self.fleet_max
                   and (sig["queue_ewma"] >= self.scale_up_q
                        or sig["slo_burn"] or sig["kv_pressure"]))
        want_down = (serving > self.fleet_min
                     and sig["queue_ewma"] <= self.scale_down_q
                     and not sig["slo_burn"] and not sig["kv_pressure"])
        # Hysteresis: the direction must hold continuously for hold_s.
        if want_up:
            if self._up_since is None:
                self._up_since = now
        else:
            self._up_since = None
        if want_down:
            if self._down_since is None:
                self._down_since = now
        else:
            self._down_since = None
        if now < self._cooldown_until:
            return None
        if (self._up_since is not None
                and now - self._up_since >= self.hold_s):
            fired = self._scale_up()
            self._cooldown_until = now + self.interval_s
            self._up_since = self._down_since = None
            return "up" if fired else None
        if (self._down_since is not None
                and now - self._down_since >= self.hold_s):
            fired = self._scale_down()
            self._cooldown_until = now + self.interval_s
            self._up_since = self._down_since = None
            return "down" if fired else None
        return None

    def _scale_up(self) -> bool:
        # Chaos seam: `fleet:spawn` fires here, so an injected spawn
        # failure (a standby host that is gone) is a counted, logged
        # non-event — the fleet keeps serving at its current size.
        try:
            FAULTS.check("fleet:spawn")
            replica = self._spawn()
        except InjectedFault as e:
            self._stats["spawn_failures"] += 1
            _log.warning("fleet scale-up spawn failed (injected): %s", e)
            return False
        except Exception:  # noqa: BLE001 — a dead standby host
            self._stats["spawn_failures"] += 1
            _log.exception("fleet scale-up spawn failed")
            return False
        if replica is None:
            self._stats["spawn_empty"] += 1
            return False
        add = getattr(self.pool, "add_replica", None)
        if not callable(add):
            return False
        label = add(replica, elastic=True)
        self._stats["ups"] += 1
        _log.info("fleet scaled up: joined %s", label)
        return True

    def _scale_down(self) -> bool:
        retire = getattr(self.pool, "retire_replica", None)
        if not callable(retire):
            return False
        out = retire(deadline_s=self.drain_deadline_s)
        if not out:
            return False
        self._stats["downs"] += 1
        _log.info("fleet scaled down: retired %s", out.get("replica"))
        return True

    # -------------------------------------------------------- observability

    def stats(self) -> Dict[str, object]:
        out = dict(self._stats)
        out.update({
            "fleet_min": self.fleet_min, "fleet_max": self.fleet_max,
            "scale_up_q": self.scale_up_q,
            "scale_down_q": self.scale_down_q,
            "hold_s": self.hold_s, "interval_s": self.interval_s,
        })
        sig = getattr(self, "_last_signal", None)
        if sig:
            out["signal"] = sig
        return out

    # ------------------------------------------------------------- thread

    def run(self, tick_s: float = 0.25) -> "FleetAutoscaler":
        """Start the control loop in a daemon thread (app path). The tick
        is intentionally faster than hold_s so the hysteresis window, not
        the poll cadence, decides responsiveness."""
        import time as _time

        if self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(tick_s):
                try:
                    self.step(_time.monotonic())
                except Exception:  # noqa: BLE001 — control loop survives
                    _log.exception("autoscaler step failed")

        self._thread = threading.Thread(
            target=loop, name="lsot-fleet-autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
