"""Generation service: model registry, prompt templates, backends."""

from .backends import Completion, EngineBackend, FakeBackend  # noqa: F401
from .ollama_client import OllamaClientService  # noqa: F401
from .resilience import (  # noqa: F401
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    Draining,
    Overloaded,
    RetryPolicy,
    SchedulerCrashed,
    breaker_states,
)
from .scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    SchedulerBackend,
    SchedulerPool,
)
from .supervisor import SupervisedScheduler  # noqa: F401
from .service import GenerateResult, GenerationService  # noqa: F401
from .templates import TEMPLATES  # noqa: F401
