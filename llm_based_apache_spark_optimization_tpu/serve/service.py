"""Generation service: the in-process replacement for the Ollama sidecar.

The reference calls `ollama.generate(model=..., system=..., prompt=...)` over
HTTP to a separate Go server and reads `res.response` (reference
`Flask/app.py:102-107,160-166`; `FastAPI/app.py:85-90,105-111`). Here the
same call shape is a method on an in-process registry of TPU engines — no
sidecar, no socket, and per-request metrics built in (SURVEY.md §5
observability: per-request tok/s and latency counters).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import threading
import time
from typing import Dict, Optional

from ..ops.sampling import SamplingParams
from ..utils import tracing
from ..utils.observability import (
    MetricsRegistry,
    RequestMetrics,
    resilience,
    trace_capture,
)
from ..utils.tracing import TRACER
from .templates import TEMPLATES, Template

log = logging.getLogger("lsot.service")


@dataclasses.dataclass(frozen=True)
class GenerateResult:
    """Mirror of the ollama response surface the reference touches: only
    `.response` is read there; the rest is in-tree observability."""

    response: str
    model: str
    latency_s: float
    output_tokens: int
    # Per-request latency decomposition (scheduler-path backends; 0.0 =
    # not measured): TTFT and queue wait — the evalh report's "where
    # latency lives" columns read these.
    ttft_s: float = 0.0
    queue_wait_s: float = 0.0
    # Trace-correlation id when the request ran under one.
    request_id: str = ""

    @property
    def tok_per_s(self) -> float:
        return self.output_tokens / self.latency_s if self.latency_s > 0 else 0.0


@dataclasses.dataclass
class ModelEntry:
    name: str
    backend: object  # EngineBackend | FakeBackend (duck-typed .complete)
    template: Template


class GenerationService:
    """Named-model registry + generate() — the Ollama capability surface."""

    def __init__(self):
        self._models: Dict[str, ModelEntry] = {}
        self._lock = threading.Lock()
        self.stats: Dict[str, Dict[str, float]] = {}
        self.metrics = MetricsRegistry()
        # Drain mode (SIGTERM path): once set, the HTTP layers answer new
        # work with 503 + Retry-After while in-flight requests finish.
        self._draining = False
        # Per-tenant model routing (ISSUE 20, LSOT_TENANT_MODELS): tenant
        # → model_id atop the multi-model pool. Resolved at every
        # generate front door; unknown tenants (and tenants pinned to a
        # model that never registered) fall through to the request's own
        # model, warned once per tenant.
        import os

        from .qos import parse_tenant_models

        self._tenant_models: Dict[str, str] = parse_tenant_models(
            os.environ.get("LSOT_TENANT_MODELS", ""))
        self._tenant_model_warned: set = set()

    def set_tenant_models(self, spec: str) -> None:
        """Install a tenant → model_id routing map from its spec string
        (config wiring; replaces the env-derived map wholesale)."""
        from .qos import parse_tenant_models

        with self._lock:
            self._tenant_models = parse_tenant_models(spec)
            self._tenant_model_warned = set()

    def resolve_model(self, model: str, tenant: str) -> str:
        """Apply per-tenant model routing: a listed tenant's requests ride
        its pinned model_id; everything else — no tenant, unlisted
        tenant, pinned model not (yet) registered — falls through to the
        request's own `model` untouched."""
        if not tenant:
            return model
        with self._lock:
            pinned = self._tenant_models.get(tenant)
            if pinned is None:
                return model
            if pinned not in self._models:
                if tenant not in self._tenant_model_warned:
                    self._tenant_model_warned.add(tenant)
                    log.warning(
                        "tenant %r pins model %r which is not registered "
                        "(available: %s); falling through to %r",
                        tenant, pinned, sorted(self._models), model,
                    )
                return model
        return pinned

    def register(self, name: str, backend, template: str = "completion") -> None:
        if template not in TEMPLATES:
            raise ValueError(f"unknown template {template!r}; choices {sorted(TEMPLATES)}")
        with self._lock:
            self._models[name] = ModelEntry(name, backend, TEMPLATES[template])
            self.stats.setdefault(
                name, {"requests": 0, "total_latency_s": 0.0, "total_tokens": 0}
            )

    def models(self):
        return sorted(self._models)

    def _entry(self, model: str) -> ModelEntry:
        entry = self._models.get(model)
        if entry is None:
            raise KeyError(
                f"model {model!r} is not registered; available: {self.models()}"
            )
        return entry

    def backend_stats(self) -> Dict[str, Dict]:
        """Per-model serving-layer stats from backends exposing .stats()
        (SchedulerBackend: prefix-cache reuse, speculation acceptance —
        split by constrained/unconstrained class under
        speculation.by_class, since the grammar-masked hot path prices
        its speedup separately) — the /metrics endpoint merges these
        beside the request aggregates."""
        out: Dict[str, Dict] = {}
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            fn = getattr(e.backend, "stats", None)
            if callable(fn):
                out[e.name] = fn()
        return out

    def metrics_snapshot(self) -> Dict[str, Dict]:
        """The /metrics payload: per-model request aggregates with each
        model's serving-layer stats merged under "serving" — ONE
        definition for the web and headless-API endpoints. Process-wide
        fault-tolerance counters (retries, sheds, deadline expiries,
        breaker trips, supervisor restart/replay/lost counts —
        serve/resilience.py, serve/supervisor.py) ride under the reserved
        "resilience" key whenever any fired — or any breaker is live:
        under load these numbers ARE the serving story, and an operator
        reading only per-model aggregates would see throughput without
        the sheds that bought it. Per-dependency breaker state (ollama,
        sql backend, each supervised scheduler's restart breaker) rides
        beside them under "breakers" — WHICH dependency is open, not just
        that some trip counter moved; owners unregister their breakers at
        teardown so the view tracks live dependencies."""
        from .resilience import breaker_states

        snap = self.metrics.snapshot()
        for model, extra in self.backend_stats().items():
            snap.setdefault(model, {})["serving"] = extra
        counters = resilience.snapshot()
        breakers = breaker_states()
        if any(counters.values()) or breakers:
            snap["resilience"] = dict(counters)
            if breakers:
                snap["resilience"]["breakers"] = breakers
        # Rolling SLO view (utils/slo.py) under the reserved "slo" key
        # when objectives are configured: burn rates ARE the serving
        # story under load, and the Prometheus renderer turns this into
        # the lsot_slo_* families.
        from ..utils import slo as slo_mod

        if slo_mod.ENGINE.enabled:
            snap["slo"] = slo_mod.ENGINE.report()
        # Multi-tenant front door (ISSUE 18) under the reserved "qos"
        # key: per-tenant admit/shed counters and live bucket levels —
        # the lsot_tenant_* Prometheus families. Empty (key absent) for
        # a quiet single-tenant deployment.
        from .qos import ADMISSION

        qos_block = ADMISSION.snapshot()
        if qos_block:
            snap["qos"] = qos_block
        # Self-healing SQL (ISSUE 20) under the reserved "repair" key:
        # repair_rounds/repaired/unrepairable + per-class diagnosed
        # counters and the last few repair flight rows — the
        # lsot_repair_* Prometheus families. Empty (key absent) until a
        # repair loop has actually run.
        from ..app.repair import repair_metrics_block

        repair_block = repair_metrics_block()
        if repair_block:
            snap["repair"] = repair_block
        return snap

    def metrics_prometheus(self) -> str:
        """The same payload in Prometheus exposition text
        (`/metrics?format=prometheus`), plus the registry's fixed-bucket
        TTFT/TPOT/queue-wait/latency histograms — which aggregate across
        scrapes and replicas where windowed percentiles cannot."""
        from ..utils.prometheus import render_prometheus

        return render_prometheus(self.metrics_snapshot(),
                                 self.metrics.histograms)

    def flight_snapshot(self, last: Optional[int] = None) -> Dict[str, list]:
        """Per-model flight-recorder records (backends exposing the
        seam; replica-labeled, lifecycle events merged for supervised
        schedulers) — the /debug/flightrecorder payload. Backends are
        deduped by underlying scheduler like health()/drain(), so a
        shared scheduler's ring is not reported twice."""
        out: Dict[str, list] = {}
        with self._lock:
            entries = list(self._models.values())
        seen = set()
        for e in entries:
            fn = getattr(e.backend, "flight_snapshot", None)
            if not callable(fn):
                continue
            key = id(getattr(e.backend, "scheduler", e.backend))
            if key in seen:
                continue
            seen.add(key)
            out[e.name] = fn(last)
        return out

    def recent_traces(self, n: Optional[int] = None) -> list:
        """Last head-sampled request traces (the /debug/traces payload)."""
        return TRACER.recent(n)

    def prefix_registry(self, top_k: Optional[int] = None) -> Dict[str, Dict]:
        """Per-model content-addressed prefix-cache registries (ISSUE 14)
        — the /debug/prefixcache payload: resident digests with live
        metadata (token mass, bytes held, shares, hit counts), the
        reuse-distance histogram over recent admissions, and the
        eviction-churn counters. Deduped by underlying scheduler like
        flight_snapshot(), so a shared scheduler's registry is not
        reported twice; backends without the seam (fakes, engines) are
        skipped."""
        out: Dict[str, Dict] = {}
        with self._lock:
            entries = list(self._models.values())
        seen = set()
        for e in entries:
            fn = getattr(e.backend, "prefix_registry", None)
            if not callable(fn):
                continue
            key = id(getattr(e.backend, "scheduler", e.backend))
            if key in seen:
                continue
            seen.add(key)
            reg = fn(top_k)
            if reg:
                out[e.name] = reg
        return out

    def slo_report(self) -> Dict[str, object]:
        """The /debug/slo payload: the process SLO engine's rolling
        report (objectives, per-replica quantiles + burn rates, fleet
        merge) — populated even with no objective configured, so the
        quantile sketches are inspectable before alerting is wired."""
        from ..utils import slo as slo_mod

        return slo_mod.ENGINE.report()

    def profile_capture(self, rounds: Optional[int] = None,
                        model: Optional[str] = None) -> Dict[str, object]:
        """Arm an on-demand device-trace capture (the /debug/profile
        trigger) on the first backend exposing the seam — or `model`'s.
        Raises LookupError when no registered backend can profile
        (fake/demo backends), RuntimeError when a capture is already in
        flight fleet-wide (the endpoint's 409)."""
        with self._lock:
            entries = [e for e in self._models.values()
                       if model is None or e.name == model]
        seen = set()
        for e in entries:
            key = id(getattr(e.backend, "scheduler", e.backend))
            if key in seen:
                continue
            seen.add(key)
            fn = getattr(e.backend, "profile_rounds", None)
            if callable(fn):
                out = dict(fn(rounds))
                out["model"] = e.name
                return out
        raise LookupError(
            f"no {'backend for model ' + repr(model) if model else 'registered backend'}"
            f" supports device profiling"
        )

    def profile_status(self) -> Dict[str, object]:
        """Per-model capture state (armed/capturing/last artifact) —
        what the smoke script polls after arming."""
        out: Dict[str, object] = {}
        with self._lock:
            entries = list(self._models.values())
        seen = set()
        for e in entries:
            key = id(getattr(e.backend, "scheduler", e.backend))
            if key in seen:
                continue
            seen.add(key)
            fn = getattr(e.backend, "profile_status", None)
            if callable(fn):
                st = fn()
                if st:
                    out[e.name] = st
        return out

    # ------------------------------------------------------------- lifecycle

    @property
    def draining(self) -> bool:
        return self._draining

    def health(self) -> Dict[str, object]:
        """Aggregate lifecycle state for /readyz: the WORST state across
        backends exposing a health() seam (the supervised scheduler's
        ready | restarting | degraded | dead), plus per-model detail and
        summed restart counters. Backends without the seam (engine,
        fakes) are 'ready' by construction — their failures are
        per-request, not lifecycle."""
        order = {"ready": 0, "degraded": 1, "restarting": 2, "dead": 3}
        worst = "ready"
        models: Dict[str, Dict] = {}
        # `stalls` counts watchdog-detected wedges (serve/watchdog.py): a
        # stalled loop surfaces as `restarting` here the moment the
        # monitor escalates it — /readyz must stop saying ready while
        # requests silently sit on a wedged device.
        totals = {"restarts": 0, "replayed": 0, "lost": 0, "stalls": 0}
        with self._lock:
            entries = list(self._models.values())
        seen = set()
        for e in entries:
            hfn = getattr(e.backend, "health", None)
            h = hfn() if callable(hfn) else None
            if not h:
                continue
            models[e.name] = h
            state = h.get("state", "ready")
            if order.get(state, 0) > order[worst]:
                worst = state
            # Dedupe by the underlying SCHEDULER, not the backend wrapper:
            # the shared-weights aliasing rule (serve/factory.py) wraps
            # one supervisor in two SchedulerBackends, and double-counting
            # its restarts would make /readyz report phantom instability.
            key = id(getattr(e.backend, "scheduler", e.backend))
            if key not in seen:
                seen.add(key)
                for k in totals:
                    totals[k] += int(h.get(k, 0) or 0)
        out: Dict[str, object] = {
            "state": worst,
            "draining": self._draining,
            "models": models,
            **totals,
        }
        # Rolling SLO (utils/slo.py): a replica BURNING a configured
        # objective (multi-window burn rate > 1 on both arms) marks the
        # instance degraded — still serving (200 from /readyz), but
        # flagged for operators and visibly worse than 'ready'. Crash/
        # restart states stay strictly worse: a burning SLO never
        # downgrades 'restarting'/'dead' information.
        from ..utils import slo as slo_mod

        if slo_mod.ENGINE.enabled:
            # ONE report per probe: readiness polls every few seconds,
            # and `burning` + `state` must come from the same snapshot
            # (two calls could straddle a window-slice rollover).
            rep = slo_mod.ENGINE.report()
            out["slo"] = {"state": rep["state"],
                          "burning": rep["burning"]}
            if rep["burning"] and out["state"] == "ready":
                out["state"] = "degraded"
        return out

    def fleet_health(self) -> Dict[str, list]:
        """Per-replica lifecycle per model, for backends serving from a
        replica fleet (SchedulerPool / a supervisor wrapping one):
        {model: [{replica, state, phase_role, restarts, ...}]} — a
        disaggregated fleet (ISSUE 13) shows each replica's prefill/
        decode/mixed role beside its lifecycle state, so one probe says
        both WHICH replica is restarting/dead and which phase lost
        capacity. Empty for single-scheduler and engine backends.
        Surfaced on /healthz, and deduped by underlying scheduler like
        health() (shared-weights aliasing)."""
        out: Dict[str, list] = {}
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            sched = getattr(e.backend, "scheduler", None)
            fn = getattr(sched, "replica_health", None)
            if callable(fn):
                try:
                    reps = fn()
                except Exception:  # noqa: BLE001 — a churning fleet mid-read
                    continue
                if reps:
                    out[e.name] = reps
        return out

    def fleet_membership(self) -> Dict[str, Dict[str, object]]:
        """Elastic-membership view per model (ISSUE 17): the pool's
        fleet_stats() — size/serving/elastic counts, join/retire/drain
        lifecycle counters, pushed-handoff pump depth/bytes/latency —
        beside the per-replica lifecycle above. Empty for backends
        without a fleet. Surfaced on /healthz."""
        out: Dict[str, Dict[str, object]] = {}
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            sched = getattr(e.backend, "scheduler", None)
            fn = getattr(sched, "fleet_stats", None)
            if callable(fn):
                try:
                    stats = fn()
                except Exception:  # noqa: BLE001 — a churning fleet mid-read
                    continue
                if stats:
                    out[e.name] = stats
        return out

    def supports_idempotency(self, model: str) -> bool:
        """Can `model`'s backend dedupe an idempotency key against a
        journal? The drain gate uses this to decide whether a keyed
        request during shutdown is a safe journal lookup (let through) or
        plain new work wearing a key (refused like any other)."""
        with self._lock:
            entry = self._models.get(model)
        return bool(entry and getattr(entry.backend, "supports_idempotency",
                                      False))

    def retry_after_hint(self, default: float = 1.0) -> float:
        """Backpressure hint for drain-mode 503s / readiness failures: the
        largest queue-drain estimate across backends exposing one (the
        scheduler's queue-depth × service-time estimate)."""
        hints = []
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            fn = getattr(e.backend, "retry_after_hint", None)
            if callable(fn):
                hints.append(fn())
        return max(hints) if hints else default

    def drain(self, deadline_s: Optional[float] = None) -> None:
        """Graceful shutdown (SIGTERM): stop admitting — the HTTP drain
        gate answers 503 + Retry-After from here on — then let each
        backend finish in-flight work up to the shared drain deadline
        (supervised schedulers journal-and-exit what is left), then close
        everything."""
        from .resilience import Deadline

        self._draining = True
        # deadline_s <= 0 means "journal-and-exit NOW", never "wait
        # forever": a 0-configured drain must not block on a wedged loop.
        deadline = (Deadline.after(deadline_s)
                    if deadline_s is not None and deadline_s > 0 else None)
        immediate = deadline_s is not None and deadline_s <= 0
        seen = set()
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            d = getattr(e.backend, "drain", None)
            # Same scheduler-level dedupe as health(): two wrappers over
            # one supervisor must drain (and spill) it exactly once.
            key = id(getattr(e.backend, "scheduler", e.backend))
            if d is None or key in seen:
                continue
            seen.add(key)
            remaining = deadline.remaining() if deadline is not None else None
            if immediate or (remaining is not None and remaining <= 0):
                remaining = 0.0  # burned: backends spill without waiting
            d(remaining)
        self.close()

    def close(self) -> None:
        """Shut down owned backend resources (scheduler threads, slot-pool
        caches). Idempotent; shared backends (one scheduler behind two
        model names) shut down once."""
        seen = set()
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            shutdown = getattr(e.backend, "shutdown", None)
            if shutdown is not None and id(e.backend) not in seen:
                seen.add(id(e.backend))
                shutdown()

    @staticmethod
    def _constrain_kwargs(entry: ModelEntry, constrain) -> Dict:
        """`constrain` is opt-in per request ("spark_sql", or a schema dict
        {"table", "columns"}): forwarded only to backends that declare
        `supports_constrain`; anything else is a clear request-shape error
        rather than a silently unconstrained completion."""
        if constrain is None:
            return {}
        if not getattr(entry.backend, "supports_constrain", False):
            raise ValueError(
                f"model {entry.name!r} backend does not support "
                f"constrained decoding"
            )
        return {"constrain": constrain}

    @staticmethod
    def _deadline_kwargs(entry: ModelEntry, deadline_s) -> Dict:
        """Per-request deadline (seconds), forwarded only to backends that
        can actually enforce one (`supports_deadline`: the scheduler
        retires in-flight work at harvest; the one-XLA-program engine
        clamps its step budget at issue time from the remaining deadline
        and the measured per-token rate). Backends without the seam —
        fakes — silently ignore it: a deadline is best-effort latency
        control, not a correctness contract, and failing the request over
        an unenforceable hint would be worse than serving it."""
        if deadline_s is None or not getattr(
                entry.backend, "supports_deadline", False):
            return {}
        return {"deadline_s": deadline_s}

    @staticmethod
    def _idempotency_kwargs(entry: ModelEntry, idempotency_key) -> Dict:
        """Client-suppliable idempotency key, forwarded only to backends
        with a journal to dedupe against (`supports_idempotency`: the
        supervised scheduler). Elsewhere it is silently dropped — the key
        is a retry-safety hint, and a backend that cannot honor it still
        serves the request correctly once."""
        if idempotency_key is None or not getattr(
                entry.backend, "supports_idempotency", False):
            return {}
        return {"idempotency_key": idempotency_key}

    @staticmethod
    def _qos_kwargs(entry: ModelEntry, tenant: str, qos: str) -> Dict:
        """Tenant/qos labels (ISSUE 18), forwarded only to backends that
        understand the axis (`supports_qos`: the scheduler path, where
        WFQ ordering and per-tenant prefix namespaces live). Elsewhere
        the labels were still charged at admission — they are a
        fairness/accounting hint, not a correctness contract."""
        if not (tenant or qos) or not getattr(entry.backend,
                                              "supports_qos", False):
            return {}
        return {"tenant": tenant, "qos": qos}

    def _admit_qos(self, tenant: str, qos: str,
                   deadline_s: Optional[float]) -> Optional[float]:
        """Front-door admission (ISSUE 18): consume one bucket token for
        (tenant, class) — raises TenantShed (→ HTTP 429) with a
        bucket-aware Retry-After when the tenant is over budget — and
        apply the class's default deadline when the request carries none
        (interactive gets the tighter budget the deadline machinery
        already honors). No-op with `LSOT_QOS=0`."""
        from .qos import ADMISSION

        if not ADMISSION.enabled:
            return deadline_s
        ADMISSION.admit(tenant, qos, fleet_hint=self.retry_after_hint())
        if deadline_s is None:
            return ADMISSION.default_deadline(qos)
        return deadline_s

    def generate(
        self,
        model: str,
        prompt: str,
        system: str = "",
        max_new_tokens: Optional[int] = None,
        sampling: Optional[SamplingParams] = None,
        seed: int = 0,
        constrain=None,
        deadline_s: Optional[float] = None,
        idempotency_key: Optional[str] = None,
        request_id: Optional[str] = None,
        tenant: str = "",
        qos: str = "",
    ) -> GenerateResult:
        model = self.resolve_model(model, tenant)
        entry = self._entry(model)
        deadline_s = self._admit_qos(tenant, qos, deadline_s)
        rendered = entry.template(system, prompt)
        # Request-scoped tracing: honor the HTTP layer's sampling
        # decision when one exists, else head-sample here — the shared
        # entry-point dance (tracing.begin_or_ambient).
        tr, own, rid = tracing.begin_or_ambient(request_id, model)
        t0 = time.perf_counter()
        try:
            with tracing.use(tr) if own is not None else contextlib.nullcontext():
                with tracing.span("service.generate", model=model,
                                  constrained=constrain is not None):
                    with trace_capture(f"generate-{model}"):
                        completion = entry.backend.complete(
                            rendered, max_new_tokens=max_new_tokens,
                            sampling=sampling, seed=seed,
                            **self._constrain_kwargs(entry, constrain),
                            **self._deadline_kwargs(entry, deadline_s),
                            **self._idempotency_kwargs(entry,
                                                       idempotency_key),
                            **self._qos_kwargs(entry, tenant, qos),
                        )
        finally:
            TRACER.finish(own)
        latency = time.perf_counter() - t0
        with self._lock:
            s = self.stats[model]
            s["requests"] += 1
            s["total_latency_s"] += latency
            s["total_tokens"] += completion.output_tokens
        self.metrics.record(RequestMetrics(
            model=model,
            prompt_tokens=completion.prompt_tokens,
            output_tokens=completion.output_tokens,
            latency_s=latency,
            ttft_s=getattr(completion, "ttft_s", 0.0),
            queue_wait_s=getattr(completion, "queue_wait_s", 0.0),
            rclass=getattr(completion, "rclass", ""),
            replica=getattr(completion, "replica", ""),
            request_id=rid,
        ))
        return GenerateResult(
            response=completion.text,
            model=model,
            latency_s=latency,
            output_tokens=completion.output_tokens,
            ttft_s=getattr(completion, "ttft_s", 0.0),
            queue_wait_s=getattr(completion, "queue_wait_s", 0.0),
            request_id=rid,
        )

    def validate(
        self,
        model: str,
        prompt: str,
        system: str = "",
        max_new_tokens: Optional[int] = None,
        constrain=None,
    ) -> None:
        """Raise the same KeyError/ValueError generate() would raise for a
        bad model name, an oversize prompt, or a bad `constrain` spec —
        WITHOUT generating. Streaming handlers call this before sending
        response headers: a request-shape error must become a 400/404
        status, which is impossible once the NDJSON stream's 200 is on the
        wire. Backends without a budget seam (fakes) validate trivially.

        `constrain` checks mirror the generate path: unsupported backend
        (ValueError here, not a mid-stream line), an uncompilable schema
        spec (e.g. no usable identifiers — the compile runs here and is
        cached for the actual request), and a budget below the grammar's
        shortest complete parse.

        The check tokenizes the rendered prompt a second time (the
        generate call re-encodes it); that is host-side microseconds per
        kilotoken against a device TTFT of tens of milliseconds, and
        keeping validate() stateless beats threading encoded ids through
        the service/backend seam."""
        entry = self._entry(model)
        self._constrain_kwargs(entry, constrain)  # supports check
        compiled = None
        if constrain is not None:
            resolve = getattr(entry.backend, "_resolve_constraint", None)
            if resolve is not None:
                compiled = resolve(constrain)  # compile errors become 400s
        check = getattr(entry.backend, "check_budget", None)
        if check is not None:
            # The backend checks its CLAMPED budget (what generate will
            # actually run with after the decode-room clamp) against the
            # grammar's shortest complete parse — the raw requested value
            # can pass while the clamp still makes the parse impossible.
            check(entry.template(system, prompt), max_new_tokens,
                  constraint=compiled)

    def generate_stream(
        self,
        model: str,
        prompt: str,
        system: str = "",
        max_new_tokens: Optional[int] = None,
        sampling: Optional[SamplingParams] = None,
        seed: int = 0,
        constrain=None,
        deadline_s: Optional[float] = None,
        request_id: Optional[str] = None,
        tenant: str = "",
        qos: str = "",
    ):
        """Yield the completion as text chunks while it decodes (Ollama's
        `stream=true` surface). Backends without a `complete_stream` seam
        (the one-XLA-program engine, fakes) degrade to a single chunk.
        Metrics record the request exactly like generate(). Front-door
        admission (ISSUE 18) runs on the generator's FIRST step — the
        HTTP layer primes the stream before sending headers, so a shed
        still answers a real 429."""
        model = self.resolve_model(model, tenant)
        entry = self._entry(model)
        deadline_s = self._admit_qos(tenant, qos, deadline_s)
        ckw = self._constrain_kwargs(entry, constrain)
        ckw.update(self._deadline_kwargs(entry, deadline_s))
        ckw.update(self._qos_kwargs(entry, tenant, qos))
        rendered = entry.template(system, prompt)
        # Tracing: the BACKEND generator reads tracing.current() at its
        # first step (the scheduler's complete_stream captures it before
        # submit), which runs inside THIS generator's frame. The shared
        # entry-point dance decides the sample (tracing.begin_or_ambient);
        # when this call drew it (`own`), the context is entered only
        # around backend ADVANCEMENT, never across our own yields — a
        # contextvar set held across a yield leaks into the caller's
        # frame between steps (generators don't isolate contextvars), so
        # a library caller interleaving two sampled streams would record
        # request B's spans into request A's tree.
        tr, own, rid = tracing.begin_or_ambient(request_id, model)

        def _ctx():
            return tracing.use(tr) if own is not None \
                else contextlib.nullcontext()

        t0 = time.perf_counter()
        out_tokens = prompt_tokens = 0
        stream_stats: dict = {}
        try:
            streamer = getattr(entry.backend, "complete_stream", None)
            if streamer is None:
                with _ctx():
                    completion = entry.backend.complete(
                        rendered, max_new_tokens=max_new_tokens,
                        sampling=sampling, seed=seed, **ckw,
                    )
                out_tokens, prompt_tokens = (completion.output_tokens,
                                             completion.prompt_tokens)
                if completion.text:
                    yield completion.text
            else:
                # The backend fills real token counts through stats_out
                # (chunk counts are not token counts; re-encoding here
                # would tokenize the prompt twice).
                inner = streamer(
                    rendered, max_new_tokens=max_new_tokens,
                    sampling=sampling, seed=seed, stats_out=stream_stats,
                    **ckw,
                )
                try:
                    with trace_capture(f"generate-{model}"):
                        # tracing.stepwise: the backend advances under
                        # the trace context, which is never held across
                        # our own yields (the generator/contextvar
                        # hazard). Only needed when this call drew the
                        # sample; the HTTP path advances plain.
                        src = tracing.stepwise(inner, tr) \
                            if own is not None else inner
                        for chunk in src:
                            yield chunk
                finally:
                    # Deterministically unwind the backend generator
                    # (its finally cancels the scheduler request and
                    # fills stats_out) BEFORE the accounting below
                    # reads it — a disconnect would otherwise leave it
                    # to the GC. No trace context needed: the backend
                    # captured its trace object at its first step.
                    inner.close()
        finally:
            # Record even when the client disconnects mid-stream (the WSGI
            # server close()s the generator -> GeneratorExit lands here):
            # disconnect-heavy streaming must not vanish from the serving
            # metrics. The backend's own finally has filled stats_out by
            # the time the generator unwinds.
            TRACER.finish(own)
            out_tokens = stream_stats.get("output_tokens", out_tokens)
            prompt_tokens = stream_stats.get("prompt_tokens", prompt_tokens)
            latency = time.perf_counter() - t0
            with self._lock:
                s = self.stats[model]
                s["requests"] += 1
                s["total_latency_s"] += latency
                s["total_tokens"] += out_tokens
            self.metrics.record(RequestMetrics(
                model=model,
                prompt_tokens=prompt_tokens,
                output_tokens=out_tokens,
                latency_s=latency,
                ttft_s=stream_stats.get("ttft_s", 0.0),
                queue_wait_s=stream_stats.get("queue_wait_s", 0.0),
                rclass=stream_stats.get("rclass", ""),
                replica=stream_stats.get("replica", ""),
                request_id=rid,
            ))

    def generate_batch(
        self,
        model: str,
        prompts: "list[str]",
        system: str = "",
        max_new_tokens: Optional[int] = None,
        sampling: Optional[SamplingParams] = None,
        seed: int = 0,
        constrain=None,
        tenant: str = "",
        qos: str = "",
    ) -> "list[GenerateResult]":
        """Batched twin of generate(): one device program for all prompts.

        Latency reported per result is the batch wall-clock (that IS each
        request's latency when submitted together); tok/s aggregates across
        the batch in the metrics registry.
        """
        model = self.resolve_model(model, tenant)
        entry = self._entry(model)
        # One admission token per batch MEMBER: a storm tenant cannot
        # dodge its budget by folding the storm into one batch call.
        for _ in prompts:
            self._admit_qos(tenant, qos, None)
        rendered = [entry.template(system, p) for p in prompts]
        t0 = time.perf_counter()
        with trace_capture(f"generate-batch-{model}"):
            completions = entry.backend.complete_batch(
                rendered, max_new_tokens=max_new_tokens, sampling=sampling,
                seed=seed, **self._constrain_kwargs(entry, constrain),
                **self._qos_kwargs(entry, tenant, qos),
            )
        latency = time.perf_counter() - t0
        with self._lock:
            s = self.stats[model]
            s["requests"] += len(prompts)
            # total_latency_s is DISTINCT wall-clock in both paths: the
            # sequential path adds each request's own wall; here the batch
            # wall counts once, not once per member.
            s["total_latency_s"] += latency
            s["total_tokens"] += sum(c.output_tokens for c in completions)
        for c in completions:
            self.metrics.record(RequestMetrics(
                model=model, prompt_tokens=c.prompt_tokens,
                output_tokens=c.output_tokens, latency_s=latency,
                wall_share_s=latency / len(completions),
                ttft_s=getattr(c, "ttft_s", 0.0),
            ))
        return [
            GenerateResult(
                response=c.text, model=model, latency_s=latency,
                output_tokens=c.output_tokens,
            )
            for c in completions
        ]
