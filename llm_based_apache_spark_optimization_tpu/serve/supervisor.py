"""Supervised scheduler lifecycle: crash-only serving with journal + replay.

Before this module a decode-loop crash was *typed* (PR 2: every future
fails `SchedulerCrashed` → 503) but still an outage: every queued and
in-flight request died with the loop, and the process had no notion of
"restarting" vs "dead". Production serving systems (vLLM/TGI survey,
PAPERS.md) treat the engine loop as a crash-only component: supervise it,
journal admitted work, and replay on restart. `SupervisedScheduler` is
that supervisor, wrapped around `ContinuousBatchingScheduler` (or a
`SchedulerPool` — anything with the scheduler's submit surface):

- **Write-ahead journal.** Every admitted request gets a monotonic request
  id and a journal entry (prompt ids, params, constraint, deadline, and a
  client-suppliable *idempotency key*) BEFORE it reaches the inner
  scheduler. Once journaled (and not shed with a typed `Overloaded` /
  request-shape `ValueError`), the request is ACKNOWLEDGED: it reaches
  exactly one terminal state — a result or a typed error — no matter how
  many times the loop underneath dies. Clients hold the supervisor's OWN
  future; the inner scheduler's future is an implementation detail that
  crashes with the loop.
- **Idempotency keys.** A duplicate key while the original is in flight
  returns the SAME future; after completion it returns the journaled
  result (bounded LRU) without generating again — the retry contract that
  makes "resubmit on 503" safe for clients.
- **Crash → restart → replay.** When an inner future (or submit) fails
  with `SchedulerCrashed`, the supervisor tears the dead loop down,
  rebuilds the scheduler from its factory under bounded restarts with
  full-jitter backoff (`RetryPolicy`), and replays journaled work in
  request-id order: queued requests always; in-flight requests only when
  idempotent-safe — generation IS (per-request seeded RNG streams make
  the replayed prefix byte-identical, so streaming consumers have their
  already-delivered tokens suppressed), while side-effectful consumers
  can opt out with `idempotent=False` (the SQL-execute stage has its own
  breaker and is never replayed blind — it lives above this layer).
  Requests whose deadline expired during the outage fail typed
  `DeadlineExceeded` and count as lost.
- **Health.** `health()` reports `ready | restarting | degraded | dead`
  plus restart/replay/lost counters — the `/readyz` payload. `degraded`
  means the last restart dropped acknowledged work; it clears on the next
  clean completion. Restart budget exhausted → `dead`: everything
  journaled fails typed, new submits are refused. A breaker named
  `scheduler-restart` records each crash/recovery so the per-dependency
  breaker view in `/metrics` includes the engine itself.
- **Liveness (the watchdog).** Everything above only fires when a failure
  *raises*. A WEDGED loop — hung XLA dispatch, stuck device tunnel — never
  raises: without detection, queued requests sit until their deadlines
  burn while `/readyz` keeps saying `ready`. The supervisor runs a monitor
  thread that reads the inner scheduler's `heartbeat` (stamped every event
  -loop iteration, serve/watchdog.py) and, when a BUSY loop's heartbeat
  age exceeds `max(stall_min_s, stall_factor × measured round cadence)`
  (LSOT_STALL_MIN_S / LSOT_STALL_FACTOR), escalates the wedge to a
  synthetic `SchedulerStalled` — a `SchedulerCrashed` subclass, so the
  SAME restart/journal/replay machinery recovers hung requests exactly
  like crashed ones. Teardown of a wedged loop uses a BOUNDED join (the
  zombie daemon thread is abandoned and exits when it unwedges); during
  the restart, `retry_after_hint()` includes the backoff remaining so
  429/503 hints stay honest instead of quoting a stale EWMA over a frozen
  queue. Counters: `sched_stalls` in /metrics, `stalls` +
  `stall_threshold_s` in health()/`watchdog_stats`.

- **Drain.** `drain(deadline_s)` stops admitting (new submits raise
  `Draining` → 503 + Retry-After), waits for in-flight work up to the
  drain deadline, then journals what is left to the optional on-disk
  spill and shuts the loop down — the SIGTERM path. `recover()` resubmits
  a spill file at the next start so retried idempotency keys find their
  results. Constrained entries spill their constraint SPEC (grammar name
  or schema dict — the compiled device tables are not serializable) and
  recover() recompiles it through `constraint_resolver`, which
  SchedulerBackend points at its own spec→tables resolver before
  recovery runs.

Counters land in `utils.observability.resilience` (`sched_restarts`,
`sched_replayed`, `sched_lost`, `sched_idempotent_hits`) and surface in
`/metrics`; `evalh --chaos` and tests/test_supervisor.py assert the
zero-lost-acknowledged-requests contract under injected `sched:crash`
faults.
"""

from __future__ import annotations

import dataclasses
import inspect
import json
import logging
import os
import random
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Callable, Dict, List, Optional, Sequence

from ..ops.sampling import SamplingParams
from ..utils.observability import resilience
from .flightrecorder import FlightRecorder, append_jsonl, merge_snapshots
from .resilience import (
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    Draining,
    Overloaded,
    Quarantined,
    RetryPolicy,
    SchedulerCrashed,
    SchedulerStalled,
)
from .watchdog import stall_threshold

_log = logging.getLogger("lsot.supervisor")

__all__ = ["JournalEntry", "SupervisedScheduler"]


@dataclasses.dataclass
class JournalEntry:
    """One acknowledged request in the write-ahead journal. Everything
    needed to resubmit it verbatim after a restart, plus the delivery
    state that makes streaming replay idempotent (`generated` holds the
    tokens the CLIENT has seen; a replay suppresses that prefix)."""

    rid: int
    ids: List[int]
    max_new: int
    sampling: SamplingParams
    seed: int
    idempotency_key: Optional[str]
    constraint: object
    deadline: Optional[Deadline]
    on_token: Optional[Callable[[int], None]]
    idempotent: bool
    future: Future
    generated: List[int] = dataclasses.field(default_factory=list)
    inner: Optional[Future] = None
    cancelled: bool = False
    done: bool = False
    # The constraint SPEC ("spark_sql" / {"table", "columns"} dict) beside
    # the compiled object: the compiled grammar holds device tables and is
    # not serializable, but the spec is plain JSON — it is what the drain
    # spill writes, and recover() recompiles it through the supervisor's
    # `constraint_resolver` (set by SchedulerBackend, which owns the
    # tokenizer the tables must be compiled against).
    constraint_spec: object = None
    # Request-scoped trace (utils/tracing.RequestTrace) when the request
    # was head-sampled: forwarded to every inner-scheduler attempt (the
    # replayed incarnation records into the SAME tree), and its span tree
    # rides the postmortem dump for requests caught in a crash/stall.
    trace: object = None
    # Fleet pools (targeted restart): how many times this entry has been
    # re-placed onto a sibling after a single-replica crash — the bound
    # that stops an entry ping-ponging across a fleet of dying replicas
    # instead of escalating to the full-pool restart path.
    replica_replays: int = 0
    # Poison-request quarantine: how many crashed/stalled incarnations
    # this entry has been replayed after. Past the supervisor's
    # `max_entry_replays` (LSOT_MAX_ENTRY_REPLAYS) the entry retires
    # typed `Quarantined` instead of riding down — and re-crashing —
    # incarnation after incarnation until the fleet's restart budget is
    # gone.
    crash_replays: int = 0
    # Multi-model serving (ISSUE 16): the registered model this request
    # named. Journaled so a crash replay re-places onto the SAME
    # checkpoint's replicas ("" = model-blind, the single-model shape).
    model_id: str = ""
    # Multi-tenant QoS (ISSUE 18): tenant attribution and service class.
    # Journaled so a crash replay — and a drain spill recovered by the
    # NEXT process — bills to the same tenant and keeps its WFQ/prefix
    # namespace ("" = unlabeled, the single-tenant shape).
    tenant: str = ""
    qos: str = ""


class SupervisedScheduler:
    """Crash-supervised wrapper with the scheduler's submit surface.

    `factory` is a zero-arg callable building a fresh (not-started)
    scheduler; the supervisor owns start/shutdown of every instance it
    builds. Duck-typed: anything exposing the `ContinuousBatchingScheduler`
    submit contract works (SchedulerPool, the chaos harness's host-only
    replica), so the supervisor's journal/replay logic is testable without
    a device.
    """

    #: GenerationService/SchedulerBackend gate `idempotency_key=` on this.
    supports_idempotency = True

    #: Uniquifies the default breaker name across supervisors in one
    #: process (a multi-model service builds several; a shared last-wins
    #: registry slot would report only the last one's loop health).
    _instances = 0
    _instances_lock = threading.Lock()

    def __init__(
        self,
        factory: Callable[[], object],
        max_restarts: int = 5,
        restart_policy: Optional[RetryPolicy] = None,
        spill_path: Optional[str] = None,
        completed_keys: int = 1024,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
        name: Optional[str] = None,
        stall_factor: float = 16.0,
        stall_min_s: float = 10.0,
        stall_join_s: Optional[float] = None,
        warmup_grace_s: float = 0.0,
        postmortem_path: Optional[str] = None,
        max_entry_replays: int = 0,
    ):
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        if max_entry_replays < 0:
            raise ValueError("max_entry_replays must be >= 0")
        if name is None:
            with SupervisedScheduler._instances_lock:
                SupervisedScheduler._instances += 1
                n = SupervisedScheduler._instances
            name = "scheduler" if n == 1 else f"scheduler-{n}"
        self.name = name
        self._factory = factory
        self._inner = factory()
        # Fleet pools (SchedulerPool with a replica factory): wire the
        # pool's replica-lifecycle callbacks at THIS layer — the journal
        # lives here, so the pool tells us when a targeted restart/drain
        # finished and we re-place exactly that replica's requests.
        self._wire_fleet(self._inner)
        self.max_restarts = max_restarts
        self._restart_policy = restart_policy or RetryPolicy(
            max_attempts=max_restarts + 1, base_delay_s=0.1, max_delay_s=5.0
        )
        self.spill_path = spill_path
        self._completed_cap = max(1, completed_keys)
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        # RLock: terminal futures resolve under the lock, and a client
        # done-callback is allowed to submit follow-up work inline.
        self._lock = threading.RLock()
        self._journal: Dict[int, JournalEntry] = OrderedDict()
        self._by_key: Dict[str, JournalEntry] = {}
        self._completed: "OrderedDict[str, tuple]" = OrderedDict()
        self._next_rid = 1
        self._state = "ready"
        self._draining = False
        self._closed = False
        self._crash_exc: Optional[BaseException] = None
        self._restarts = 0
        self._replayed = 0
        self._lost = 0
        # Poison-request quarantine (ISSUE 10): an entry replayed after
        # more than this many crashed/stalled incarnations retires typed
        # `Quarantined` instead of burning the restart budget crash by
        # crash — one poison request must not take the fleet down with
        # it. 0 disables (the library default; the app wires
        # LSOT_MAX_ENTRY_REPLAYS). Set it BELOW max_restarts, or the
        # budget dies first and the quarantine never fires.
        self.max_entry_replays = int(max_entry_replays)
        self._quarantined = 0
        # Quarantine attribution per tenant (ISSUE 18): the poison-
        # request counter gains a tenant axis (bounded top-K labels), so
        # an operator sees WHOSE requests keep crashing the loop.
        self._quarantined_by_tenant: Dict[str, float] = {}
        # Watchdog (serve/watchdog.py): a monitor thread compares the
        # inner loop's heartbeat age against
        # max(stall_min_s, stall_factor × measured round cadence) and
        # escalates a busy-but-stale loop to a synthetic SchedulerStalled.
        # stall_min_s <= 0 disables monitoring entirely; the floor must
        # sit above the worst legitimate host-thread occupation (a cold
        # XLA compile of an unwarmed bucket blocks the loop exactly like
        # a wedge — warmup() first, or raise the floor).
        self.stall_factor = float(stall_factor)
        self.stall_min_s = float(stall_min_s)
        # How long teardown waits for a (possibly wedged) loop thread to
        # join before abandoning it — a wedged join must not block the
        # restart driver for the length of the hang it is recovering from.
        # None = unbounded: with the watchdog DISABLED (stall_min_s <= 0,
        # the operator's opt-out for legitimately slow rounds) nothing
        # ever flags a loop as wedged, so teardown must never abandon a
        # healthy worker mid-round either.
        if stall_join_s is not None:
            self._stall_join_s: Optional[float] = float(stall_join_s)
        elif self.stall_min_s > 0:
            self._stall_join_s = max(1.0, self.stall_min_s)
        else:
            self._stall_join_s = None
        self._stalls = 0
        # Warmup-aware stall floor (ISSUE 6 satellite; the carried
        # ROADMAP item "watchdog stall floor vs first-boot cold
        # compiles"): for `warmup_grace_s` after start()/each restart —
        # and only while the inner has harvested ZERO rounds — the
        # watchdog's floor is raised to the grace value, so a first-boot
        # cold XLA compile (which blocks the loop thread exactly like a
        # wedge) cannot be escalated as one. The first harvested round
        # proves the programs are warm and ends the grace early. 0
        # disables (the library default — tight-threshold tests and
        # pre-warmed deployments keep today's behavior); the app wires
        # LSOT_STALL_WARMUP_S (default 120 s) through AppConfig.
        self.warmup_grace_s = float(warmup_grace_s)
        self._grace_until = 0.0
        # Postmortem dump (the flight recorder's exit path): on
        # crash/stall escalation and on drain, the supervisor writes its
        # lifecycle events + the inner's last-N round records + the
        # still-pending requests' span trees as JSONL here — next to the
        # journal spill by default.
        if postmortem_path is not None:
            self.postmortem_path: Optional[str] = postmortem_path or None
        elif spill_path:
            self.postmortem_path = f"{spill_path}.postmortem.jsonl"
        else:
            self.postmortem_path = os.environ.get("LSOT_POSTMORTEM") or None
        #: Lifecycle black box (serve/flightrecorder.py): restart/stall/
        #: drain/dead markers, merged with the inner's per-round records
        #: in flight_snapshot() and the postmortem dump.
        self.flight = FlightRecorder(capacity=64, replica=name)
        # Expected-recovery instant (monotonic) while a restart backoff
        # sleep is pending: retry_after_hint() folds it in so shed/drain
        # hints during a stall stay honest (the inner's queue-depth ×
        # service-time estimate is frozen while the loop is down).
        self._restart_eta: Optional[float] = None
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._warned_unspillable = False
        # Single-flight drain: orchestrators commonly repeat SIGTERM, and
        # a second concurrent drain would cut the first's grace period
        # short and rewrite ('w' mode) the spill it just wrote.
        self._drain_lock = threading.Lock()
        self._drain_report: Optional[Dict[str, object]] = None
        # Recompiles a spilled constraint SPEC at recover() time
        # (spec -> compiled grammar). Set by SchedulerBackend — the owner
        # of the tokenizer+stop-ids the tables compile against; None means
        # constrained spill records cannot be recovered and count lost.
        self.constraint_resolver: Optional[Callable[[object], object]] = None
        # Per-dependency breaker view: the engine loop is a dependency too.
        # A crash records a failure, a successful restart a success — so
        # /metrics "resilience.breakers.<name>-restart" tells operators
        # EACH supervised loop's health the same way "ollama"/"sql" tell
        # dependency health (the registry is last-wins per name, hence the
        # per-instance name). Never consulted for shedding: the journal
        # admits during restarts on purpose (replay picks the work up).
        self._breaker = CircuitBreaker(
            f"{name}-restart",
            failure_threshold=max(1, max_restarts),
            reset_after_s=60.0,
        )

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "SupervisedScheduler":
        self._inner.start()
        self._grace_until = time.monotonic() + self.warmup_grace_s
        self.flight.event("start")
        if self.stall_min_s > 0 and self._watch_thread is None \
                and getattr(self._inner, "heartbeat", None) is not None:
            self._watch_stop.clear()
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True,
                name="lsot-supervisor-watchdog",
            )
            self._watch_thread.start()
        return self

    def shutdown(self) -> None:
        """Stop the inner loop; fail anything still journaled (clean
        shutdown is not a crash: no restart, no replay). Idempotent."""
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join()
            self._watch_thread = None
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = [e for e in self._journal.values() if not e.done]
        try:
            # Bounded even on the clean path: a SIGTERM aimed at a wedged
            # loop must not hang the exit the drain deadline exists to
            # bound (the abandoned daemon zombie dies with the process).
            self._shutdown_inner(self._inner)
        except Exception:  # noqa: BLE001 — a broken inner must not wedge close
            _log.exception("inner scheduler shutdown failed")
        exc = RuntimeError("scheduler shut down mid-request")
        with self._lock:
            for e in pending:
                if not e.done:
                    self._fail_locked(e, exc)
        # This supervisor's loop is no longer a live dependency: keep the
        # /metrics per-dependency breaker view free of corpses.
        self._breaker.unregister()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()

    def warmup(self, prompt_len: Optional[int] = None) -> None:
        warm = getattr(self._inner, "warmup", None)
        if callable(warm):
            warm(prompt_len)

    # Admission-arithmetic surface, mirrored from the live inner scheduler
    # so SchedulerBackend wraps a supervisor exactly like a bare scheduler.
    @property
    def cfg(self):
        return self._inner.cfg

    @property
    def max_seq(self):
        return self._inner.max_seq

    @property
    def decode_chunk(self):
        return self._inner.decode_chunk

    @property
    def prompt_bucket(self):
        return self._inner.prompt_bucket

    @property
    def stop_ids(self):
        return self._inner.stop_ids

    @property
    def overshoot(self):
        return self._inner.overshoot

    @property
    def _spec_draft(self):
        return getattr(self._inner, "_spec_draft", 0)

    @property
    def _harvest_lag(self):
        return getattr(self._inner, "_harvest_lag", 1)

    @property
    def prefix_stats(self):
        return getattr(self._inner, "prefix_stats", {})

    @property
    def speculation_stats(self):
        return getattr(self._inner, "speculation_stats", None)

    @property
    def prefix_telemetry(self):
        """Prefix-cache telemetry passthrough (ISSUE 14): the
        serving.prefix block and the lsot_prefix_* families survive
        supervision (None for duck-typed inners / disabled caches)."""
        return getattr(self._inner, "prefix_telemetry", None)

    def prefix_registry(self, top_k=None):
        """Content-addressed prefix registry passthrough — the
        /debug/prefixcache payload survives supervision."""
        fn = getattr(self._inner, "prefix_registry", None)
        return fn(top_k) if callable(fn) else None

    def resident_digests(self, limit=None):
        fn = getattr(self._inner, "resident_digests", None)
        return fn(limit) if callable(fn) else []

    def prefix_affinity(self, digests):
        """Cache-aware routing feed passthrough (inner SchedulerPool)."""
        fn = getattr(self._inner, "prefix_affinity", None)
        return fn(digests) if callable(fn) else []

    @property
    def page_stats(self):
        """Paged-KV pool stats passthrough (None for contiguous inner
        schedulers) — the /metrics kv_pages gauges survive supervision."""
        return getattr(self._inner, "page_stats", None)

    @property
    def perf_stats(self):
        """Roofline-ledger passthrough (utils/perfmodel.py): the
        serving.perf view and the lsot_mfu/lsot_hbm_util gauges survive
        supervision (None for duck-typed inners without a ledger)."""
        return getattr(self._inner, "perf_stats", None)

    @property
    def handoff_stats(self):
        """Prefill→decode handoff passthrough (ISSUE 13): the
        serving.handoff view and the lsot_handoff_* families survive
        supervision (None for mixed/duck-typed inners)."""
        return getattr(self._inner, "handoff_stats", None)

    @property
    def phase_role(self):
        return getattr(self._inner, "phase_role", "mixed")

    @property
    def model_id(self):
        """Model axis passthrough (ISSUE 16): a supervised single
        scheduler reports its checkpoint id like a bare one."""
        return getattr(self._inner, "model_id", "")

    @property
    def supports_model_routing(self):
        """Duck-typing flag passthrough: SchedulerBackend forwards a
        model_id through the supervision layer only when the INNER
        scheduler routes on it (a pool; bare schedulers validate)."""
        return bool(getattr(self._inner, "supports_model_routing", False))

    @property
    def supports_qos(self):
        """Tenant/qos axis passthrough (ISSUE 18): callers forward the
        kwargs through supervision only when the INNER scheduler
        understands them (duck-typed like model routing)."""
        return bool(getattr(self._inner, "supports_qos", False))

    def qos_stats(self):
        """Per-tenant WFQ/admission counters passthrough (ISSUE 18),
        with the supervisor's own per-tenant quarantine axis folded in
        (the poison-quarantine enforcement arm's attribution)."""
        fn = getattr(self._inner, "qos_stats", None)
        out = fn() if callable(fn) else None
        with self._lock:
            quarantined = dict(self._quarantined_by_tenant)
        if quarantined:
            out = dict(out) if out else {}
            out["quarantined"] = quarantined
        return out

    def model_stats(self):
        """Per-model serving aggregation passthrough (ISSUE 16)."""
        fn = getattr(self._inner, "model_stats", None)
        return fn() if callable(fn) else None

    @property
    def transport_stats(self):
        """Replica-transport passthrough (ISSUE 15): the
        serving.transport view and the lsot_transport_* families
        survive supervision (None for in-process fleets)."""
        return getattr(self._inner, "transport_stats", None)

    def routing_stats(self):
        """Cache-aware placement counters passthrough (ISSUE 15)."""
        fn = getattr(self._inner, "routing_stats", None)
        return fn() if callable(fn) else None

    def profile_rounds(self, rounds=None, out_dir=None):
        """On-demand device-capture passthrough (/debug/profile): the
        INNER loop owns the device, so it owns the capture; the
        fleet-wide single-capture guard lives below this seam."""
        fn = getattr(self._inner, "profile_rounds", None)
        if not callable(fn):
            raise ValueError(
                "supervised scheduler does not support device profiling"
            )
        return fn(rounds, out_dir)

    def profile_status(self):
        fn = getattr(self._inner, "profile_status", None)
        return fn() if callable(fn) else None

    def retry_after_hint(self) -> float:
        """The inner scheduler's queue-depth × service-time estimate —
        except while the loop is down (stalled/crashed, mid-restart):
        then the inner's EWMA is stale and its queue frozen, so the hint
        is clamped to at least the restart backoff remaining (the
        watchdog's expected-recovery time). Clamped to [1, 60] s like the
        scheduler's own estimate."""
        with self._lock:
            restarting = self._state == "restarting"
            eta = self._restart_eta
        try:
            hint = getattr(self._inner, "retry_after_hint", None)
            base = hint() if callable(hint) else 1.0
        except Exception:  # noqa: BLE001 — a dead/churning inner mid-restart
            base = 1.0
        if restarting and eta is not None:
            base = max(base, eta - time.monotonic())
        return float(min(60.0, max(1.0, base)))

    # Fleet passthroughs (inner SchedulerPool): runtime per-replica ops
    # and the per-replica load/health views keep working through the
    # supervision layer — the journal on THIS side re-places whatever a
    # targeted restart or drain leaves behind (the wired callbacks).
    def restart_replica(self, replica, reason: str = "manual") -> bool:
        fn = getattr(self._inner, "restart_replica", None)
        return bool(fn(replica, reason=reason)) if callable(fn) else False

    def drain_replica(self, replica, deadline_s: Optional[float] = None,
                      remove: bool = False) -> Dict[str, object]:
        fn = getattr(self._inner, "drain_replica", None)
        if not callable(fn):
            raise ValueError("inner scheduler has no replica fleet")
        return fn(replica, deadline_s=deadline_s, remove=remove)

    def replica_loads(self) -> List[Dict[str, object]]:
        fn = getattr(self._inner, "replica_loads", None)
        return fn() if callable(fn) else []

    def replica_health(self) -> List[Dict[str, object]]:
        fn = getattr(self._inner, "replica_health", None)
        return fn() if callable(fn) else []

    # Elastic membership passthroughs (ISSUE 17): the autoscaler and the
    # app's fleet endpoints address the pool through the supervision
    # layer — joins/retires hit the LIVE inner (re-resolved per call, so
    # they keep working across full-restart swaps).
    def add_replica(self, scheduler, label: Optional[str] = None,
                    weight: float = 1.0, elastic: bool = True) -> str:
        fn = getattr(self._inner, "add_replica", None)
        if not callable(fn):
            raise ValueError("inner scheduler has no replica fleet")
        return fn(scheduler, label=label, weight=weight, elastic=elastic)

    def retire_replica(self, replica=None,
                       deadline_s: Optional[float] = None
                       ) -> Optional[Dict[str, object]]:
        fn = getattr(self._inner, "retire_replica", None)
        return (fn(replica, deadline_s=deadline_s)
                if callable(fn) else None)

    def fleet_stats(self) -> Optional[Dict[str, object]]:
        fn = getattr(self._inner, "fleet_stats", None)
        return fn() if callable(fn) else None

    # ---------------------------------------------------------------- client

    def submit(
        self,
        ids: Sequence[int],
        max_new_tokens: int = 256,
        sampling: SamplingParams = SamplingParams(),
        seed: int = 0,
        on_token: Optional[Callable[[int], None]] = None,
        constraint=None,
        deadline_s: Optional[float] = None,
        idempotency_key: Optional[str] = None,
        idempotent: bool = True,
        constraint_spec=None,
        trace=None,
        model_id: str = "",
        tenant: str = "",
        qos: str = "",
    ) -> "Future[List[int]]":
        """Journal + submit. The returned future survives loop crashes: it
        resolves from whichever scheduler incarnation finishes the work.
        `idempotency_key` dedupes retries (same key → same result);
        `idempotent=False` marks a consumer whose delivered tokens cannot
        be replayed (the entry fails typed instead of double-streaming).
        `constraint_spec` is the serializable twin of `constraint`
        (grammar name / schema dict): with it, a keyed constrained entry
        survives the drain spill — recover() recompiles the spec through
        `constraint_resolver` instead of failing the request typed."""
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        with self._lock:
            if idempotency_key is not None:
                # Idempotency lookups come BEFORE every lifecycle check:
                # serving an already-journaled result admits no new work,
                # so even a draining or DEAD supervisor honors the "retry
                # with the same key is safe" contract — a client whose
                # response was lost on the wire must not get a 503 for a
                # result sitting in memory.
                live = self._by_key.get(idempotency_key)
                if live is not None and not live.done:
                    # Same request already acknowledged: one result, one
                    # generation — the retry rides the original's future.
                    resilience.inc("sched_idempotent_hits")
                    return live.future
                hit = self._completed.get(idempotency_key)
                if hit is not None:
                    resilience.inc("sched_idempotent_hits")
                    self._completed.move_to_end(idempotency_key)
                    f: Future = Future()
                    f.set_result(list(hit))
                    return f
            if self._draining:
                # Checked before _closed: a drained-then-shut supervisor
                # still answers the RETRYABLE typed error (the replacement
                # instance takes the retry), not lifecycle misuse.
                raise Draining(
                    "server draining: not admitting new requests",
                    retry_after_s=self.retry_after_hint(),
                )
            if self._closed:
                raise RuntimeError("scheduler has shut down")
            if self._state == "dead":
                raise self._dead_error()
            if constraint is not None \
                    and not isinstance(constraint_spec, (str, dict)):
                # A raw pre-compiled CompiledMask with no serializable
                # spec cannot survive the drain spill (there is nothing
                # portable to write): it fails typed at spill time. Count
                # and warn NOW so operators see the exposure before a
                # drain makes it a lost request — the last recovery gap
                # ROADMAP's crash-recovery item documents.
                resilience.inc("unspillable_constraints")
                if not self._warned_unspillable:
                    self._warned_unspillable = True
                    _log.warning(
                        "constrained request submitted with a pre-compiled "
                        "constraint and no serializable spec: it cannot be "
                        "journal-spilled across a drain (pass the grammar "
                        "name/schema dict as constraint_spec). Counted at "
                        "/metrics resilience.unspillable_constraints; "
                        "warning once."
                    )
            entry = JournalEntry(
                rid=self._next_rid,
                ids=list(ids),
                max_new=max_new_tokens,
                sampling=sampling,
                seed=seed,
                idempotency_key=idempotency_key,
                constraint=constraint,
                constraint_spec=constraint_spec,
                deadline=(Deadline.after(deadline_s)
                          if deadline_s is not None else None),
                on_token=on_token,
                idempotent=idempotent,
                future=Future(),
                trace=trace,
                model_id=str(model_id or ""),
                tenant=str(tenant or ""),
                qos=str(qos or ""),
            )
            self._next_rid += 1
            entry.future._lsot_entry = entry  # cancel() handle
            self._journal[entry.rid] = entry
            if idempotency_key is not None:
                self._by_key[idempotency_key] = entry
            if self._state == "restarting":
                # Acknowledged while the loop is down: the replay pass
                # after the restart submits it in rid order.
                return entry.future
            try:
                self._submit_entry_locked(entry)
            except (ValueError, Overloaded):
                # Request-shape rejection or a typed shed: NOT acknowledged
                # — the caller got a real error, nothing to replay.
                self._forget_locked(entry)
                raise
            except Exception as exc:  # noqa: BLE001 — crash classification below
                if self._is_crash(exc):
                    # The loop died under us: the request IS acknowledged
                    # (journaled); restart + replay will serve it.
                    self._notice_crash_locked(self._wrap_crash(exc))
                    return entry.future
                self._forget_locked(entry)
                raise
            return entry.future

    def cancel(self, future: "Future[List[int]]") -> None:
        """Cooperative cancel, supervisor-aware: marks the journal entry so
        a replay resolves with what was already delivered, and forwards to
        the inner scheduler's cancel seam. Safe on foreign futures."""
        entry: Optional[JournalEntry] = getattr(future, "_lsot_entry", None)
        if entry is None:
            return
        entry.cancelled = True
        inner = entry.inner
        if inner is not None:
            req = getattr(inner, "_lsot_request", None)
            if req is not None:
                req.cancelled = True

    def generate(
        self,
        prompts: List[List[int]],
        max_new_tokens: int = 256,
        sampling: SamplingParams = SamplingParams(),
        seed: int = 0,
    ) -> List[List[int]]:
        """Synchronous batch helper (scheduler-compatible signature)."""
        futs = [
            self.submit(p, max_new_tokens=max_new_tokens, sampling=sampling,
                        seed=seed)
            for p in prompts
        ]
        return [f.result() for f in futs]

    # ---------------------------------------------------------------- health

    def health(self) -> Dict[str, object]:
        """The `/readyz` payload: lifecycle state + restart counters.
        A loop the watchdog caught wedged reports `restarting` here (the
        escalation rides the crash path), with `stalls` counting how many
        times liveness — not an exception — triggered the recovery."""
        with self._lock:
            out = {
                "state": self._state,
                "draining": self._draining,
                "restarts": self._restarts,
                "max_restarts": self.max_restarts,
                "replayed": self._replayed,
                "lost": self._lost,
                "quarantined": self._quarantined,
                "stalls": self._stalls,
                "journal_depth": sum(
                    1 for e in self._journal.values() if not e.done
                ),
                "last_crash": (str(self._crash_exc)
                               if self._crash_exc is not None else None),
            }
        # Fleet pools: per-replica lifecycle beside the pool-level state —
        # /readyz shows WHICH replica is restarting/dead, not just that
        # something somewhere is.
        rh = getattr(self._inner, "replica_health", None)
        if callable(rh):
            try:
                out["replicas"] = rh()
            except Exception:  # noqa: BLE001 — a churning pool mid-read
                pass
        # Elastic membership (ISSUE 17): the fleet size/joins/retires/
        # pump ledger rides the same probe.
        fs = getattr(self._inner, "fleet_stats", None)
        if callable(fs):
            try:
                fleet = fs()
            except Exception:  # noqa: BLE001 — a churning pool mid-read
                fleet = None
            if fleet:
                out["fleet"] = fleet
        return out

    @property
    def heartbeat(self):
        """The live inner loop's heartbeat (None for heartbeat-less
        duck-typed inners) — what the monitor thread reads."""
        return getattr(self._inner, "heartbeat", None)

    @property
    def watchdog_stats(self) -> Dict[str, object]:
        """/metrics liveness view: the inner's heartbeat + per-slot stall
        retirements, plus this supervisor's whole-loop stall detections
        and the threshold currently in force."""
        inner = getattr(self._inner, "watchdog_stats", None)
        out: Dict[str, object] = dict(inner) if inner is not None else {}
        hb = self.heartbeat
        out["stalls_detected"] = self._stalls
        out["stall_threshold_s"] = (
            round(stall_threshold(hb, self.stall_factor,
                                  self._effective_floor(hb)), 3)
            if hb is not None and self.stall_min_s > 0 else None
        )
        # Operators reading a raised threshold need to know WHY: the
        # warmup grace window is active until the first harvested round.
        out["warmup_grace_active"] = self._warmup_grace_active()
        return out

    def flight_stats(self) -> Dict[str, object]:
        """Ring occupancy for /metrics: the INNER scheduler's per-round
        ring — the one sized by LSOT_FLIGHT_ROUNDS, whose fill/overwrite
        counters an operator actually monitors — beside this supervisor's
        small lifecycle ring. Without the split, `getattr(sched, 'flight')`
        on a supervised backend resolves to the sparse 64-slot lifecycle
        recorder and /metrics reports the wrong ring."""
        out: Dict[str, object] = {"supervisor": self.flight.stats()}
        inner = self._inner
        fs = getattr(inner, "flight_stats", None)
        if callable(fs):
            out["scheduler"] = fs()
        else:
            fl = getattr(inner, "flight", None)
            if fl is not None:
                out["scheduler"] = fl.stats()
        return out

    # ----------------------------------------------------------------- drain

    def drain(self, deadline_s: Optional[float] = None) -> Dict[str, object]:
        """SIGTERM path: stop admitting (submits raise `Draining`), let
        in-flight work finish up to the drain deadline, then journal what
        is left to the spill file and shut down. `deadline_s=None` waits
        for everything; `deadline_s <= 0` means journal-and-exit NOW (no
        waiting — an unbounded wait on a wedged loop is exactly the hang
        a drain deadline exists to prevent). Returns the accounting the
        shutdown log wants. Single-flight: a repeated SIGTERM joins the
        in-progress drain and gets its report instead of clobbering the
        freshly written spill."""
        with self._drain_lock:
            if self._drain_report is not None:
                return self._drain_report
            self.flight.event("drain", deadline_s=deadline_s)
            # SIGTERM is a black-box moment too: dump what the scheduler
            # was doing (and which requests were mid-flight) before the
            # spill/shutdown churns the state.
            self._postmortem_dump("drain")
            with self._lock:
                self._draining = True
                waiting = [e for e in self._journal.values() if not e.done]
            if deadline_s is not None and deadline_s <= 0:
                waiting = []  # deadline already burned: straight to the spill
            deadline = (Deadline.after(deadline_s)
                        if deadline_s is not None and deadline_s > 0 else None)
            finished = 0
            for e in waiting:
                timeout = None
                if deadline is not None:
                    timeout = deadline.remaining()
                    if timeout <= 0:
                        break
                try:
                    e.future.result(timeout=timeout)
                    finished += 1
                except FutureTimeoutError:
                    break
                except Exception:  # noqa: BLE001 — typed terminal states count as drained
                    finished += 1
            spilled = self._spill_pending()
            self.shutdown()
            self._drain_report = {
                "drained": finished,
                "spilled": spilled,
                "spill_path": self.spill_path if spilled else None,
            }
            return self._drain_report

    def _spill_pending(self) -> int:
        """Journal-and-exit: persist unfinished entries (JSONL) so the next
        process can `recover()` them, then fail their futures typed
        `Draining` — the client is told to retry, and a retry with the
        same idempotency key finds the recovered result. Only KEYED
        entries spill: the idempotency cache is the sole cross-process
        handle to a recovered result, so regenerating keyless work would
        burn startup device time on futures nobody can claim. Constrained
        entries spill their constraint SPEC (grammar name / schema dict —
        the compiled device tables themselves are not serializable);
        recover() recompiles the spec through `constraint_resolver`.
        A constrained entry WITHOUT a serializable spec (a caller handed
        the scheduler a pre-compiled CompiledMask directly) still fails
        typed without a record — there is nothing portable to write.

        The COMPLETED idempotency cache spills too, as literal `result`
        records: a client whose response was lost on the wire retries its
        key against the NEXT process, and regenerating there would be
        wasteful at best, wrong at worst (the result already exists).
        Every record carries the spill wall-clock so recovery charges
        downtime against remaining deadlines."""
        now = time.time()
        with self._lock:
            pending = [e for e in self._journal.values() if not e.done]
            records = []
            for e in pending:
                # A constrained entry is spillable only through its
                # serializable SPEC (str/dict); a raw CompiledMask has no
                # portable representation and the entry fails typed below.
                spec_ok = (e.constraint is None
                           or isinstance(e.constraint_spec, (str, dict)))
                if spec_ok and not e.cancelled \
                        and e.idempotency_key is not None:
                    rem = (e.deadline.remaining()
                           if e.deadline is not None else None)
                    rec = {
                        "rid": e.rid,
                        "ids": e.ids,
                        "max_new": e.max_new,
                        "temperature": e.sampling.temperature,
                        "top_p": e.sampling.top_p,
                        "top_k": e.sampling.top_k,
                        "seed": e.seed,
                        "idempotency_key": e.idempotency_key,
                        "deadline_remaining_s": rem,
                        "spilled_at_unix": now,
                        # Forensic only ("how far did it get before the
                        # drain"): recover() regenerates from scratch —
                        # deterministic decode makes the result identical,
                        # so there is no cross-process suppression to do.
                        "delivered": len(e.generated),
                    }
                    if e.constraint is not None:
                        rec["constrain"] = e.constraint_spec
                    if e.model_id:
                        rec["model_id"] = e.model_id
                    if e.tenant:
                        rec["tenant"] = e.tenant
                    if e.qos:
                        rec["qos"] = e.qos
                    records.append(rec)
            for key, result in self._completed.items():
                records.append({
                    "idempotency_key": key,
                    "result": list(result),
                    "spilled_at_unix": now,
                })
        spilled = 0
        spilled_keys = set()
        if records and self.spill_path:
            with open(self.spill_path, "w", encoding="utf-8") as f:
                for rec in records:
                    f.write(json.dumps(rec) + "\n")
            spilled = len(records)
            spilled_keys = {r["idempotency_key"] for r in records}
        hint = self.retry_after_hint()
        # Tell each client the truth: only entries actually WRITTEN to
        # the spill may promise their key will find a journaled result;
        # keyless/constrained/spill-disabled entries just get the drain.
        journaled_exc = Draining(
            "server draining: request journaled for restart; retry with "
            "the same idempotency key",
            retry_after_s=hint,
        )
        plain_exc = Draining(
            "server draining: request not completed; retry later",
            retry_after_s=hint,
        )
        with self._lock:
            for e in pending:
                if not e.done:
                    self._fail_locked(
                        e, journaled_exc if e.idempotency_key in spilled_keys
                        else plain_exc,
                    )
        return spilled

    def recover(self, path: Optional[str] = None) -> int:
        """Restore a spill file from a previous process: completed
        `result` records load straight into the idempotency cache (no
        regeneration — retried keys find them immediately); pending
        records resubmit server-side, their results landing in the same
        cache. Deadlines are charged for the DOWNTIME between spill and
        recovery (the spill wall-clock stamp); entries that no longer fit
        their budget count as lost. Returns the number of records
        restored; removes the file.

        Never raises: recovery runs during server startup, and the
        crash-recovery feature must not itself become a startup crash — a
        truncated line (SIGKILL mid-spill), a record that no longer fits
        a reconfigured scheduler (ValueError), or a shed (Overloaded) is
        logged and counted lost; every parseable record still gets its
        chance."""
        path = path or self.spill_path
        if not path or not os.path.exists(path):
            return 0
        recovered = 0
        try:
            with open(path, encoding="utf-8") as f:
                lines = [line for line in f if line.strip()]
            os.remove(path)
        except OSError:
            _log.exception("journal spill at %s unreadable; skipping", path)
            return 0
        now = time.time()
        for line in lines:
            try:
                rec = json.loads(line)
                if "result" in rec:
                    # A completed result from the previous process: serve
                    # future retries of this key from memory.
                    with self._lock:
                        self._completed[rec["idempotency_key"]] = tuple(
                            rec["result"]
                        )
                        while len(self._completed) > self._completed_cap:
                            self._completed.popitem(last=False)
                    recovered += 1
                    continue
                rem = rec.get("deadline_remaining_s")
                if rem is not None:
                    # The clock kept running while the process was down.
                    rem -= max(0.0, now - rec.get("spilled_at_unix", now))
                    if rem <= 0:
                        with self._lock:
                            self._lost += 1
                        resilience.inc("sched_lost")
                        continue
                ckw = {}
                spec = rec.get("constrain")
                if spec is not None:
                    # Recompile the spilled SPEC into device tables
                    # against the serving tokenizer (the compile cache in
                    # constrain/ dedupes across records). No resolver →
                    # the ValueError lands in the per-record guard below
                    # and the record counts lost — logged, not a startup
                    # crash.
                    if self.constraint_resolver is None:
                        raise ValueError(
                            "constrained spill record needs a "
                            "constraint_resolver (SchedulerBackend sets "
                            "one before recovery)"
                        )
                    ckw = {"constraint": self.constraint_resolver(spec),
                           "constraint_spec": spec}
                self.submit(
                    rec["ids"], max_new_tokens=rec["max_new"],
                    sampling=SamplingParams(
                        temperature=rec.get("temperature", 0.0),
                        top_p=rec.get("top_p", 1.0),
                        top_k=rec.get("top_k", 0),
                    ),
                    seed=rec.get("seed", 0),
                    deadline_s=rem,
                    idempotency_key=rec.get("idempotency_key"),
                    model_id=str(rec.get("model_id", "") or ""),
                    tenant=str(rec.get("tenant", "") or ""),
                    qos=str(rec.get("qos", "") or ""),
                    **ckw,
                )
            except Exception:  # noqa: BLE001 — per-record: salvage the rest
                _log.exception("unrecoverable journal spill record: %.120s",
                               line)
                with self._lock:
                    self._lost += 1
                resilience.inc("sched_lost")
                continue
            recovered += 1
        return recovered

    # -------------------------------------------------------------- internal

    @staticmethod
    def _is_crash(exc: BaseException) -> bool:
        # Crashes are classified by TYPE only: the scheduler's loop death
        # and the pool's everything-dead summary both raise
        # SchedulerCrashed (a message-string contract would silently
        # break recovery on rewording).
        return isinstance(exc, SchedulerCrashed)

    @staticmethod
    def _wrap_crash(exc: BaseException) -> SchedulerCrashed:
        if isinstance(exc, SchedulerCrashed):
            return exc
        return SchedulerCrashed.from_exception(exc)

    def _dead_error(self) -> SchedulerCrashed:
        msg = (f"scheduler dead: restart budget exhausted "
               f"({self._restarts}/{self.max_restarts} restarts)")
        err = SchedulerCrashed(msg)
        if self._crash_exc is not None:
            err.__cause__ = self._crash_exc
            err.crash_traceback = getattr(
                self._crash_exc, "crash_traceback", "")
        return err

    def _make_on_token(self, entry: JournalEntry):
        """Per-attempt token tap: counts/records delivered tokens for
        replay, suppressing the prefix the client already received (the
        replayed stream is byte-identical — per-request seeded RNG).
        Returns `(tap, cell)`; the caller binds `cell["fut"]` to the
        attempt's inner future right after submit so the tap can tell
        whether it still speaks for `entry` — an ABANDONED zombie
        incarnation (wedged loop the bounded join gave up on) may
        unwedge and harvest a round long after the replay installed a
        fresh attempt, and its late tokens must reach neither
        `entry.generated` nor the client a second time."""
        suppress = len(entry.generated)
        seen = 0
        cell: Dict[str, object] = {"fut": None}

        def tap(tok: int) -> None:
            nonlocal seen
            f = cell["fut"]
            if f is not None and entry.inner is not f:
                return  # stale attempt from a torn-down/abandoned incarnation
            seen += 1
            if seen <= suppress:
                return
            entry.generated.append(tok)
            if entry.on_token is not None:
                try:
                    entry.on_token(tok)
                except Exception:  # noqa: BLE001 — consumer bugs must not break accounting
                    entry.on_token = None

        return tap, cell

    def _submit_entry_locked(self, entry: JournalEntry) -> None:
        if entry.deadline is not None:
            rem = entry.deadline.remaining()
            if rem <= 0:
                resilience.inc("deadline_expired")
                raise DeadlineExceeded(
                    "request deadline exceeded before admission"
                )
            deadline_s = rem
        else:
            deadline_s = None
        # Invalidate any prior attempt BEFORE the new tap snapshots its
        # suppression prefix: a zombie tap firing from here on sees
        # `entry.inner is not` its own future and drops the token, so the
        # prefix length cannot grow under the snapshot.
        entry.inner = None
        tap, cell = self._make_on_token(entry)
        kwargs = {}
        if entry.trace is not None:
            # Forwarded only when sampled: duck-typed inners without the
            # tracing seam (the chaos harness's toy replica) keep working.
            kwargs["trace"] = entry.trace
        if entry.model_id and getattr(self._inner,
                                      "supports_model_routing", False):
            # Model axis (ISSUE 16): replays ride through here too, so a
            # journaled model-named request re-places onto the same
            # checkpoint's replicas after a crash — duck-typed inners
            # without the axis never see the kwarg.
            kwargs["model_id"] = entry.model_id
        if (entry.tenant or entry.qos) and getattr(self._inner,
                                                   "supports_qos", False):
            # Tenant axis (ISSUE 18): replays and spill recovery keep
            # their attribution so WFQ/preemption charge the right
            # tenant after a crash; qos-blind inners never see it.
            kwargs["tenant"] = entry.tenant
            kwargs["qos"] = entry.qos
        fut = self._inner.submit(
            entry.ids, max_new_tokens=entry.max_new, sampling=entry.sampling,
            seed=entry.seed, on_token=tap,
            constraint=entry.constraint, deadline_s=deadline_s, **kwargs,
        )
        entry.inner = fut
        cell["fut"] = fut
        if entry.cancelled:  # cancelled while the loop was down
            req = getattr(fut, "_lsot_request", None)
            if req is not None:
                req.cancelled = True
        fut.add_done_callback(
            lambda f, e=entry: self._on_inner_done(e, f)
        )

    def _on_inner_done(self, entry: JournalEntry, fut: Future) -> None:
        with self._lock:
            if entry.done or entry.inner is not fut:
                return  # stale attempt from a torn-down incarnation
            exc = fut.exception()
            if exc is None:
                self._finish_locked(entry, fut.result())
                if self._state == "degraded":
                    # A clean completion proves the restarted loop serves.
                    self._state = "ready"
                return
            if self._is_crash(exc):
                # Fleet pools: a SINGLE replica's crash gets a targeted
                # restart and this entry re-places onto a sibling — the
                # whole-pool teardown (which would restart every healthy
                # replica and replay their work too) is reserved for the
                # fleet actually being gone.
                if self._try_fleet_replay_locked(entry, fut, exc):
                    return
                # The entry stays journaled: restart + replay owns it now.
                self._notice_crash_locked(self._wrap_crash(exc))
                return
            if not self._closed and isinstance(exc, RuntimeError) \
                    and str(exc) == "scheduler shut down mid-request":
                # Teardown CROSSFIRE, not a per-request failure: the
                # restart driver shut the old incarnation down and a
                # HEALTHY replica's in-flight work (pool case) was closed
                # with it. The request is acknowledged — leave it
                # journaled; the replay pass resubmits it on the rebuilt
                # scheduler. (Outside supervisor-owned teardown this
                # message can only mean lifecycle misuse — the supervisor
                # owns start/shutdown of every inner it builds.)
                return
            self._fail_locked(entry, exc)

    def _finish_locked(self, entry: JournalEntry, result: List[int]) -> None:
        entry.done = True
        self._journal.pop(entry.rid, None)
        # Surface the serving attempt's measured queue wait / replica on
        # the CLIENT-facing future (the inner future is an implementation
        # detail that dies with the loop).
        for attr in ("_lsot_queue_wait", "_lsot_replica"):
            v = getattr(entry.inner, attr, None)
            if v is not None:
                setattr(entry.future, attr, v)
        if entry.idempotency_key is not None:
            if self._by_key.get(entry.idempotency_key) is entry:
                del self._by_key[entry.idempotency_key]
            if not entry.cancelled:
                # A cancelled entry resolves with its PARTIAL tokens —
                # never cache that as the key's authoritative result; a
                # retry with the key deserves a full generation.
                self._completed[entry.idempotency_key] = tuple(result)
                while len(self._completed) > self._completed_cap:
                    self._completed.popitem(last=False)
        entry.future.set_result(result)

    def _fail_locked(self, entry: JournalEntry, exc: BaseException) -> None:
        entry.done = True
        self._journal.pop(entry.rid, None)
        if entry.idempotency_key is not None and \
                self._by_key.get(entry.idempotency_key) is entry:
            del self._by_key[entry.idempotency_key]
        entry.future.set_exception(exc)

    def _forget_locked(self, entry: JournalEntry) -> None:
        """Un-acknowledge: the submit itself answered the caller (shed or
        request-shape error), so nothing may linger for replay."""
        entry.done = True
        self._journal.pop(entry.rid, None)
        if entry.idempotency_key is not None and \
                self._by_key.get(entry.idempotency_key) is entry:
            del self._by_key[entry.idempotency_key]

    def _notice_crash_locked(self, exc: SchedulerCrashed) -> None:
        self._crash_exc = exc
        if self._state in ("restarting", "dead") or self._closed:
            return  # single-flight: one restart driver at a time
        self._breaker.record_failure()
        self._state = "restarting"
        self.flight.event(
            "stall" if isinstance(exc, SchedulerStalled) else "crash",
            error=str(exc)[:200],
        )
        _log.warning("scheduler loop crashed; supervisor restarting: %s", exc)
        threading.Thread(
            target=self._restart_and_replay, daemon=True,
            name="lsot-supervisor-restart",
        ).start()

    def _restart_and_replay(self) -> None:
        """The restart driver (one thread per crash episode): tear down,
        rebuild with backoff under the restart budget, replay the journal.
        A crash DURING replay loops back to another rebuild; budget
        exhaustion fails everything typed and marks the supervisor dead."""
        # The black-box moment: dump the postmortem BEFORE teardown churns
        # anything — supervisor lifecycle + the dead loop's last-N rounds
        # + the hung requests' span trees, next to the journal spill.
        self._postmortem_dump(
            "stall" if isinstance(self._crash_exc, SchedulerStalled)
            else "crash"
        )
        while True:
            old = self._inner
            try:
                # Joins the dead worker (all its done-callbacks have run
                # past this point) — BOUNDED: a worker the watchdog caught
                # WEDGED never joins, so schedulers that support a join
                # timeout get one and the zombie daemon thread is
                # abandoned (it exits when it unwedges; its late
                # callbacks are superseded by the replay's fresh inner
                # futures — the `entry.inner is not fut` staleness guard).
                self._shutdown_inner(old)
            except Exception:
                _log.exception("dead scheduler teardown failed; continuing")
            with self._lock:
                if self._closed:
                    return
                if self._restarts >= self.max_restarts:
                    self._die_locked()
                    return
                attempt = self._restarts
                self._restarts += 1
            resilience.inc("sched_restarts")
            delay = self._restart_policy.delay_s(attempt, self._rng)
            with self._lock:
                # Published for retry_after_hint: shed/drain hints during
                # the outage promise at least the backoff remaining.
                self._restart_eta = time.monotonic() + delay
            self._sleep(delay)
            try:
                inner = self._factory()
                # Warm BEFORE serving: a rebuilt scheduler recompiles its
                # XLA programs, and a cold first round blocks the fresh
                # loop's thread exactly like the wedge this restart may be
                # recovering from — the watchdog would re-flag it and burn
                # the budget on compiles. Warming happens here, while the
                # state is `restarting` and the monitor is quiet.
                warm = getattr(inner, "warmup", None)
                if callable(warm):
                    warm()
                inner.start()
            except Exception:  # noqa: BLE001 — rebuild failure burns one restart credit
                _log.exception("scheduler rebuild failed (restart %d/%d)",
                               attempt + 1, self.max_restarts)
                self._breaker.record_failure()
                continue
            with self._lock:
                if self._closed:
                    inner.shutdown()
                    return
                self._inner = inner
                self._wire_fleet(inner)
                try:
                    lost = self._replay_locked()
                except _CrashedAgain:
                    continue  # the fresh loop died mid-replay: go again
                self._state = "degraded" if lost else "ready"
                self._restart_eta = None
                # The rebuilt loop recompiled nothing (warmup() above ran
                # while the monitor was quiet), but re-open the grace
                # window anyway: a pool rebuild or a changed shape can
                # still compile lazily on the first real admission.
                self._grace_until = time.monotonic() + self.warmup_grace_s
                self._breaker.record_success()
                self.flight.event("restart", attempt=self._restarts,
                                  state=self._state, lost=lost)
                _log.info(
                    "scheduler restarted (restart %d/%d): state=%s lost=%d",
                    self._restarts, self.max_restarts, self._state, lost,
                )
                return

    def _replay_one_locked(self, e: JournalEntry,
                           defer_on_overload: bool = False) -> str:
        """Replay ONE journal entry onto the current inner: the shared
        core of the full-restart replay pass and the fleet pools'
        per-replica re-placement. Returns `"replayed"`, `"lost"` (failed
        typed), `"quarantined"` (poison entry retired typed after too
        many crashed incarnations), `"skipped"` (done/cancelled), or
        `"deferred"` (kept
        journaled for a later pass — only with `defer_on_overload`, the
        fleet case where a shed now would drop acknowledged work that a
        finishing replica rebuild is about to have room for). Raises
        `_CrashedAgain` when the inner dies under the resubmit."""
        if e.done:
            return "skipped"
        if e.cancelled:
            # The consumer already gave up: resolve with what it got
            # (the bare scheduler's cancel contract), don't re-decode.
            self._finish_locked(e, list(e.generated))
            return "skipped"
        if e.deadline is not None and e.deadline.expired():
            resilience.inc("deadline_expired")
            resilience.inc("sched_lost")
            self._lost += 1
            self._fail_locked(e, DeadlineExceeded(
                f"request deadline expired during scheduler restart "
                f"with {len(e.generated)} of {e.max_new} tokens "
                f"delivered"
            ))
            return "lost"
        if not e.idempotent and e.generated:
            # Tokens already reached a consumer that declared itself
            # replay-unsafe: failing typed beats double-applying.
            resilience.inc("sched_lost")
            self._lost += 1
            self._fail_locked(e, self._wrap_crash(
                self._crash_exc
                or SchedulerCrashed("scheduler loop crashed")
            ))
            return "lost"
        # Poison-request quarantine: every call here means the entry's
        # previous incarnation ended in a crash/stall/teardown — an entry
        # that keeps riding down incarnations is the prime suspect for
        # CAUSING them (a deterministically-crashing input replays into a
        # crash every time, burning one restart credit per lap). Past the
        # budget, retire it typed instead of replaying it again; the
        # remaining journal replays normally and the fleet keeps its
        # restart credits for organic failures.
        e.crash_replays += 1
        if self.max_entry_replays and \
                e.crash_replays > self.max_entry_replays:
            self._quarantined += 1
            from .qos import DEFAULT_TENANT, bounded_bump
            bounded_bump(self._quarantined_by_tenant,
                         e.tenant or DEFAULT_TENANT)
            resilience.inc("quarantined")
            self.flight.event("quarantine", rid=e.rid,
                              replays=e.crash_replays - 1)
            _log.warning(
                "journal entry rid=%d quarantined after %d crashed "
                "incarnations (max_entry_replays=%d)",
                e.rid, e.crash_replays - 1, self.max_entry_replays,
            )
            self._fail_locked(e, Quarantined(
                f"request quarantined: {e.crash_replays - 1} scheduler "
                f"incarnations crashed while it was in flight "
                f"(LSOT_MAX_ENTRY_REPLAYS={self.max_entry_replays}); "
                f"not replaying it again"
            ))
            return "quarantined"
        try:
            self._submit_entry_locked(e)
        except DeadlineExceeded as exc:
            resilience.inc("sched_lost")
            self._lost += 1
            self._fail_locked(e, exc)
            return "lost"
        except Overloaded as exc:
            if defer_on_overload:
                # Fleet re-placement with nowhere to place right now
                # (e.g. a pool-of-one mid-rebuild): keep the entry
                # journaled — the pool's on_replica_restart callback
                # replays it once the rebuild lands. The entry never
                # reached an incarnation, so the quarantine tally above
                # must not count this attempt (sustained overload would
                # otherwise quarantine a healthy acknowledged request).
                e.crash_replays -= 1
                return "deferred"
            # A fresh loop's queue should hold the journal; a cap
            # smaller than the backlog is a deployment error — fail
            # typed rather than spin the restart thread.
            resilience.inc("sched_lost")
            self._lost += 1
            self._fail_locked(e, exc)
            return "lost"
        except Exception as exc:  # noqa: BLE001 — crash classification
            if self._is_crash(exc):
                self._crash_exc = self._wrap_crash(exc)
                self._breaker.record_failure()
                raise _CrashedAgain() from exc
            resilience.inc("sched_lost")
            self._lost += 1
            self._fail_locked(e, exc)
            return "lost"
        if not e.done and e.inner is not None and e.inner.done():
            # The fresh loop killed this submit before its callback
            # was even attached: the callback ran INLINE on this
            # thread (RLock), where _notice_crash_locked's
            # single-flight guard no-ops because WE are the restart
            # driver. Detect it here — otherwise the entry would stay
            # journaled forever with a dead inner future and its
            # client would hang.
            exc2 = e.inner.exception()
            if exc2 is not None and self._is_crash(exc2):
                self._crash_exc = self._wrap_crash(exc2)
                self._breaker.record_failure()
                raise _CrashedAgain()
        self._replayed += 1
        resilience.inc("sched_replayed")
        return "replayed"

    def _replay_locked(self) -> int:
        """Resubmit journaled work in rid order. Returns how many
        acknowledged requests were LOST (failed typed instead of
        replayed): expired deadlines, and in-flight non-idempotent
        streams. Raises `_CrashedAgain` if the fresh loop dies under the
        replay itself."""
        lost = 0
        for rid in sorted(self._journal):
            if self._replay_one_locked(self._journal[rid]) == "lost":
                lost += 1
        return lost

    # ----------------------------------------------------- fleet (pools)

    def _fleet_inner(self):
        """The inner when it is a fleet pool (SchedulerPool with a
        replica factory): targeted restart + per-replica replay replace
        the whole-pool teardown for single-replica failures."""
        inner = self._inner
        return inner if getattr(inner, "supports_replica_restart",
                                False) else None

    def _wire_fleet(self, inner) -> None:
        """Point a fleet pool's replica-lifecycle callbacks at this
        journal: after a targeted restart/drain completes, re-place
        exactly that replica's outstanding requests."""
        if getattr(inner, "supports_replica_restart", False):
            inner.on_replica_restart = self._on_replica_restarted
            inner.on_replica_drained = self._replay_replica
            # Pushed constrained handoffs (ISSUE 17): the pool resolves
            # wire constraint SPECs through the supervisor's resolver
            # (installed by SchedulerBackend, the tokenizer owner).
            # Bound late so a resolver set AFTER start() still reaches
            # every inner rebuild.
            if hasattr(inner, "constraint_resolver"):
                inner.constraint_resolver = self._resolve_fleet_constraint

    def _resolve_fleet_constraint(self, spec):
        """Late-bound spec→tables resolver for the inner pool (pushed
        handoffs re-materialized from the wire)."""
        fn = self.constraint_resolver
        if fn is None:
            raise ValueError(
                "constrained handoff spec needs a constraint_resolver "
                "(SchedulerBackend installs one)"
            )
        return fn(spec)

    def _on_replica_restarted(self, label: str) -> None:
        """A targeted replica rebuild just landed: re-open the warmup
        grace window BEFORE replaying — the fresh replica's lazy XLA
        compiles block its loop exactly like the wedge that triggered
        the rebuild (the pool's driver warms it, but warmup covers one
        prompt bucket; the replayed traffic's bucket can still compile
        cold), and without the grace the watchdog would re-flag the
        rebuild and burn the replica's budget on compiles — the same
        cascade the full-restart path already guards against."""
        with self._lock:
            self._grace_until = time.monotonic() + self.warmup_grace_s
        self._replay_replica(label)

    @staticmethod
    def _is_teardown_runtime(exc: Optional[BaseException]) -> bool:
        return (isinstance(exc, RuntimeError)
                and str(exc) == "scheduler shut down mid-request")

    def _replay_replica(self, label: str,
                        defer_on_overload: bool = False) -> int:
        """Re-place the journaled requests still ATTRIBUTED to replica
        `label` — inner futures that will never resolve (a wedged corpse
        abandoned by a targeted restart), teardown crossfire
        (RuntimeError from the replica's clean close), or a crash the
        inline fleet path deferred — onto the current fleet in rid
        order. Entries already re-placed carry a different (or live)
        inner and are skipped, so the pass is idempotent. Returns how
        many entries were resubmitted."""
        replayed = 0
        with self._lock:
            if self._closed or self._state == "dead":
                return 0
            for rid in sorted(self._journal):
                e = self._journal[rid]
                if e.done:
                    continue
                if e.inner is None:
                    # A DEFERRED fleet re-placement (the prior attempt
                    # was invalidated and nothing could take the work
                    # mid-rebuild): claim it regardless of label — it
                    # has no attribution left, and this callback fires
                    # exactly when capacity returned.
                    pass
                elif getattr(e.inner, "_lsot_replica", None) != label:
                    continue
                elif e.inner.done():
                    exc = e.inner.exception()
                    if not (self._is_teardown_runtime(exc)
                            or self._is_crash(exc)):
                        continue  # resolved for real: nothing to recover
                try:
                    if self._replay_one_locked(
                            e, defer_on_overload=defer_on_overload) \
                            == "replayed":
                        replayed += 1
                except _CrashedAgain:
                    # The whole fleet is gone under the re-placement:
                    # the standard full-pool crash path owns recovery.
                    self._notice_crash_locked(self._wrap_crash(
                        self._crash_exc
                        or SchedulerCrashed("fleet replay crashed")
                    ))
                    return replayed
        if replayed:
            self.flight.event("replica_replay", replica=label,
                              replayed=replayed)
        return replayed

    def _try_fleet_replay_locked(self, entry: JournalEntry, fut: Future,
                                 exc: BaseException) -> bool:
        """A journaled request's inner future failed with a crash while
        the inner is a fleet pool: notify the pool (targeted restart of
        the crashed replica) and re-place THIS entry on a sibling
        immediately, instead of escalating to the whole-pool teardown.
        Returns True when the entry was handled (re-placed, deferred for
        the post-rebuild pass, or terminally failed) — False falls back
        to the full crash path."""
        inner = self._fleet_inner()
        if (inner is None or self._closed
                or self._state not in ("ready", "degraded")):
            return False
        label = getattr(fut, "_lsot_replica", None)
        if label:
            try:
                inner.notice_replica_crash(label, exc)
            except Exception:  # noqa: BLE001 — restart kick is best-effort
                _log.exception("notice_replica_crash(%s) failed", label)
        entry.replica_replays += 1
        cap = len(getattr(inner, "schedulers", ())) + 1
        if entry.replica_replays > max(2, cap):
            # Ping-ponging across a fleet of dying replicas: stop playing
            # whack-a-mole and let the full-pool restart own it.
            return False
        try:
            self._replay_one_locked(entry, defer_on_overload=True)
        except _CrashedAgain:
            return False
        return True

    def _shutdown_inner(self, sched) -> None:
        """Shut an inner scheduler down with a bounded join when it
        supports one (ContinuousBatchingScheduler/SchedulerPool do);
        duck-typed inners without a timeout parameter get the plain
        call. The bound is what keeps teardown of a WEDGED loop from
        hanging the restart driver for the length of the hang it is
        recovering from; with the watchdog disabled (`_stall_join_s` is
        None) the join is unbounded — nothing can have flagged the loop
        as wedged, so a healthy slow round must not be abandoned."""
        try:
            takes_timeout = "timeout" in inspect.signature(
                sched.shutdown
            ).parameters
        except (TypeError, ValueError):  # builtins/uninspectable callables
            takes_timeout = False
        if takes_timeout and self._stall_join_s is not None:
            sched.shutdown(timeout=self._stall_join_s)
        else:
            sched.shutdown()

    def _effective_floor(self, hb) -> float:
        """The watchdog floor, warmup-aware: during the post-(re)start
        grace window — and only while the loop has harvested ZERO rounds
        (the first harvest proves the XLA programs are warm) — the floor
        is raised to `warmup_grace_s`, so a first-boot cold compile that
        blocks the loop thread exactly like a wedge cannot be escalated
        as one. Outside the window (or once disabled) it is stall_min_s,
        unchanged."""
        if self.warmup_grace_s <= 0:
            return self.stall_min_s
        if self._hb_cold(hb) and time.monotonic() < self._grace_until:
            return max(self.stall_min_s, self.warmup_grace_s)
        return self.stall_min_s

    @staticmethod
    def _hb_cold(hb) -> bool:
        """Still in first-boot compile territory? Prefer the heartbeat's
        `cold` property (CombinedHeartbeat: ANY replica at zero rounds —
        the pool-summed `rounds` would let one warmed replica end the
        grace while a sibling's cold compile still reads as a wedge);
        fall back to rounds==0 for single heartbeats."""
        cold = getattr(hb, "cold", None)
        if cold is not None:
            return bool(cold)
        return getattr(hb, "rounds", 1) == 0

    def _warmup_grace_active(self) -> bool:
        hb = self.heartbeat
        return (self.warmup_grace_s > 0 and hb is not None
                and self._hb_cold(hb)
                and time.monotonic() < self._grace_until)

    def flight_snapshot(self, last: Optional[int] = None) -> List[Dict]:
        """Merged black-box view: the live inner's per-round records
        (pool-merged when the inner is a SchedulerPool) + this
        supervisor's lifecycle events, in time order — the
        /debug/flightrecorder payload for supervised backends."""
        return merge_snapshots([self.flight, self._inner], last)

    def _postmortem_dump(self, reason: str) -> Optional[str]:
        """Write the black box to disk: supervisor lifecycle events, the
        inner's last-N round records, and the span trees of every
        still-pending (hung) request — one JSONL, next to the journal
        spill. Returns the path (None when no postmortem path is
        configured — the last rounds still go to the restart log either
        way). Never raises: the postmortem writer must not turn a crash
        into a second crash."""
        try:
            rounds = self.flight_snapshot()
            with self._lock:
                pending = [e for e in self._journal.values() if not e.done]
            traces = []
            for e in pending:
                rec: Dict[str, object] = {
                    "rid": e.rid, "delivered": len(e.generated),
                    "max_new": e.max_new,
                    "idempotency_key": e.idempotency_key,
                }
                if e.trace is not None:
                    try:
                        rec["trace"] = e.trace.to_dict()
                    except Exception:  # noqa: BLE001 — a broken trace stays out
                        pass
                traces.append(rec)
            # The restart log gets the tail even with no dump file: the
            # "what was it doing" question must be answerable from logs
            # alone on a diskless deployment.
            tail = [r for r in rounds if "round" in r][-5:]
            _log.warning(
                "%s postmortem (%s): %d pending request(s), last rounds: %s",
                self.name, reason, len(pending),
                json.dumps(tail) if tail else "none recorded",
            )
            if not self.postmortem_path:
                return None
            # APPEND, never truncate (append_jsonl): every dump starts
            # with its own "kind": "postmortem" header, so a routine
            # SIGTERM-drain dump cannot clobber the stall/crash evidence
            # written minutes earlier — the whole point of the black box.
            # Readers take the records after the last header they care
            # about.
            header = {
                "kind": "postmortem", "reason": reason,
                "name": self.name, "ts": time.time(),
                "state": self._state, "restarts": self._restarts,
                "stalls": self._stalls, "pending": len(pending),
            }
            written = append_jsonl(self.postmortem_path, [
                header,
                *rounds,
                *({"kind": "pending_request", **t} for t in traces),
            ])
            return self.postmortem_path if written else None
        except Exception:  # noqa: BLE001 — diagnostics must never crash recovery
            _log.exception("postmortem dump failed")
            return None

    def _watch_loop(self) -> None:
        """The watchdog monitor: poll the live inner's heartbeat and
        escalate a busy loop whose stamp has gone stale past the stall
        threshold. One escalation per episode — the state gate (only
        ready/degraded loops are judged) and the heartbeat identity check
        keep the monitor from re-flagging a loop already being rebuilt or
        flagging the fresh one with the corpse's stale reading."""
        poll = max(0.02, min(0.25, self.stall_min_s / 4.0))
        while not self._watch_stop.wait(poll):
            with self._lock:
                if self._closed:
                    return
                if self._state not in ("ready", "degraded"):
                    continue
                inner = self._inner
            hb = getattr(inner, "heartbeat", None)
            if hb is None or not hb.busy:
                continue
            if getattr(inner, "supports_replica_restart", False) and \
                    callable(getattr(inner, "stalled_replicas", None)):
                # Fleet pools: judge each replica by ITS OWN heartbeat and
                # escalate only the stale ones to TARGETED restarts —
                # siblings keep serving. The wedged replica's journaled
                # requests re-place immediately (deferred if nothing can
                # take them yet; the post-rebuild callback finishes the
                # job). The whole-pool SchedulerStalled escalation below
                # is reserved for non-fleet inners.
                try:
                    stalled = inner.stalled_replicas(
                        self.stall_factor, self._effective_floor(hb))
                except Exception:  # noqa: BLE001 — a churning pool mid-read
                    stalled = []
                for label in stalled:
                    with self._lock:
                        if self._closed or self._state not in (
                                "ready", "degraded"):
                            break
                        if self._inner is not inner:
                            break
                        self._stalls += 1
                    resilience.inc("sched_stalls")
                    self.flight.event("replica_stall", replica=label)
                    _log.warning(
                        "watchdog: replica %s busy-stale past its stall "
                        "threshold; targeted restart", label,
                    )
                    if inner.restart_replica(label, reason="stalled"):
                        self._replay_replica(label, defer_on_overload=True)
                continue
            age = hb.age()
            threshold = stall_threshold(hb, self.stall_factor,
                                        self._effective_floor(hb))
            if age <= threshold:
                continue
            exc = SchedulerStalled(
                f"decode loop made no progress for {age:.2f}s "
                f"(stall threshold {threshold:.2f}s) with work in flight: "
                f"escalating the wedge to a restart"
            )
            with self._lock:
                if self._closed or self._state not in ("ready", "degraded"):
                    continue
                if self._inner is not inner:
                    continue  # the wedged incarnation is already gone
                self._stalls += 1
                resilience.inc("sched_stalls")
                _log.warning("watchdog: %s", exc)
                self._notice_crash_locked(exc)

    def _die_locked(self) -> None:
        self._state = "dead"
        self._restart_eta = None
        self.flight.event("dead", restarts=self._restarts)
        err = self._dead_error()
        _log.error("supervisor giving up: %s", err)
        for e in list(self._journal.values()):
            if not e.done:
                resilience.inc("sched_lost")
                self._lost += 1
                self._fail_locked(e, err)


class _CrashedAgain(Exception):
    """Internal signal: the freshly restarted loop crashed during replay."""
