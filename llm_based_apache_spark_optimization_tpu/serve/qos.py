"""Multi-tenant QoS front door (ISSUE 18): token buckets, class policy,
WFQ weights, and the process-wide admission controller.

The reference app falls over the moment two users collide on one Flask
worker; our fleet behind `submit()` is elastic and partition-tolerant,
but until this module the admission edge treated every caller
identically — one tenant's 100k-token-prompt storm could starve everyone
at `_page_wait`. Three cooperating pieces fix that:

* **Token buckets** (here): per-(tenant, class) refillable budgets shed
  over-rate tenants with a typed 429 *before* the request touches the
  scheduler. `LSOT_TENANT_RATE` / `LSOT_TENANT_BURST` configure them
  ("2" = 2 req/s for every class; "2,interactive=4" overrides per
  class). Empty rate = buckets off (WFQ fairness still applies).
* **Weighted-fair queueing** (serve/scheduler.py): per-tenant
  virtual-finish-time ordering at admission and `_page_wait`, weights
  from `LSOT_TENANT_WEIGHTS` ("tenantA=4,tenantB=1").
* **Prefix-cache namespaces** (serve/scheduler.py): `tenant_salt` below
  prepends two tenant-derived int32s to every prefix-cache key and
  chain digest (`LSOT_PREFIX_TENANT_NS`, default on) so one tenant can
  neither probe nor evict another's cached schema prefixes.

`LSOT_QOS=0` switches the whole subsystem off: the scheduler's
admission order, prefix keys, and preemption choices reproduce the
pre-QoS code paths bit-for-bit (the PR-13/15/16 off-switch discipline;
reconciliation-tested at the token level in tests/test_scheduler.py).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, Optional, Tuple

from .resilience import Overloaded

#: The three service classes the front door understands. `interactive`
#: is the latency class (gets the tighter default deadline when the
#: operator configures one); `batch` is throughput traffic; `replay` is
#: backfill/re-run traffic (the journal-replay and eval harness class).
QOS_CLASSES = ("interactive", "batch", "replay")

#: Bucket accounting label for unlabeled traffic. The empty tenant stays
#: "" end-to-end through the scheduler/wire (so off-switch and
#: single-tenant paths are untouched); only the *accounting* here folds
#: it into one named default bucket.
DEFAULT_TENANT = "default"

#: Bounded label cardinality for everything per-tenant (counters here,
#: the lsot_tenant_* Prometheus families, the scheduler's WFQ ledgers):
#: the top-K tenants keep their own label, the long tail aggregates
#: under "_other" so a tenant-id cardinality attack cannot balloon the
#: metrics payload.
TENANT_TOPK = 32
OTHER_TENANT = "_other"


def _truthy(env: str, default: str = "1") -> bool:
    return os.environ.get(env, default).strip().lower() in (
        "1", "true", "yes", "on")


def qos_enabled() -> bool:
    """The master off-switch (`LSOT_QOS`, default on)."""
    return _truthy("LSOT_QOS")


def prefix_tenant_ns_enabled() -> bool:
    """Per-tenant prefix-cache namespacing (`LSOT_PREFIX_TENANT_NS`,
    default on; off = today's shared registry bit-for-bit)."""
    return _truthy("LSOT_PREFIX_TENANT_NS")


def normalize_qos(qos: str) -> str:
    """Lower-cased class name, "" for unlabeled. Raises ValueError for an
    unknown class — the HTTP layer turns that into a 400."""
    q = (qos or "").strip().lower()
    if q and q not in QOS_CLASSES:
        raise ValueError(
            f"unknown qos class {qos!r}; choices {list(QOS_CLASSES)}")
    return q


def bounded_bump(counters: Dict[str, float], tenant: str,
                 amount: float = 1.0, top_k: int = TENANT_TOPK) -> None:
    """Increment `counters[tenant]`, folding tenants beyond the top-K
    into the `_other` aggregate (bounded label cardinality)."""
    key = tenant or DEFAULT_TENANT
    if key not in counters and len(counters) >= top_k:
        key = OTHER_TENANT
    counters[key] = counters.get(key, 0) + amount


def tenant_salt(tenant: str) -> Tuple[int, ...]:
    """Two int32 salts derived from the tenant id: prepended to prefix
    cache keys/chain digests when namespacing is on, so the same token
    prefix keys differently per tenant (cross-tenant cache probing and
    eviction become impossible by construction). "" salts to () — the
    unlabeled/single-tenant key shape is bit-for-bit unchanged."""
    if not tenant:
        return ()
    h = hashlib.blake2b(tenant.encode("utf-8"), digest_size=8).digest()
    return (int.from_bytes(h[:4], "little", signed=True),
            int.from_bytes(h[4:], "little", signed=True))


def parse_tenant_weights(spec: str) -> Dict[str, float]:
    """`LSOT_TENANT_WEIGHTS` ("tenantA=4,tenantB=1") → weight map for
    the scheduler's WFQ. Missing tenants weigh 1.0; malformed entries
    are ignored (a bad knob must not take down serving)."""
    out: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        try:
            w = float(val)
        except ValueError:
            continue
        if name.strip() and w > 0:
            out[name.strip()] = w
    return out


def parse_tenant_models(spec: str) -> Dict[str, str]:
    """`LSOT_TENANT_MODELS` ("tenantA=duckdb-nsql,tenantB=llama3.2") →
    tenant → model_id routing map atop the multi-model pool (ISSUE 20:
    what lets a tenant pin its SQL/repair/explainer model). Unknown
    tenants fall through to the request's own model; malformed entries
    are ignored (a bad knob must not take down serving)."""
    out: Dict[str, str] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, val = part.partition("=")
        if name.strip() and val.strip():
            out[name.strip()] = val.strip()
    return out


def _parse_budget_spec(spec: str) -> Tuple[float, Dict[str, float]]:
    """"2,interactive=4,batch=1" → (2.0, {"interactive": 4.0, ...}).
    The bare number is the default for every class; `class=value`
    entries override per class. Malformed entries are ignored."""
    base = 0.0
    per: Dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, val = part.partition("=")
            try:
                per[name.strip().lower()] = float(val)
            except ValueError:
                continue
        else:
            try:
                base = float(part)
            except ValueError:
                continue
    return base, per


class TokenBucket:
    """Refillable token bucket: `rate` tokens/s toward `burst` capacity,
    starting full. Not thread-safe on its own — the registry serializes
    access under its lock."""

    __slots__ = ("rate", "burst", "level", "_t")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.level = self.burst
        self._t: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._t is not None and self.rate > 0 and now > self._t:
            self.level = min(self.burst,
                             self.level + (now - self._t) * self.rate)
        self._t = now

    def take(self, n: float = 1.0, now: Optional[float] = None) -> bool:
        """Consume `n` tokens if available; False (nothing consumed)
        otherwise."""
        self._refill(time.monotonic() if now is None else now)
        if self.level >= n:
            self.level -= n
            return True
        return False

    def refill_eta(self, n: float = 1.0,
                   now: Optional[float] = None) -> float:
        """Seconds until `n` tokens will be available (0.0 if they
        already are). A zero-rate bucket never refills: cap at a minute
        so Retry-After stays a sane HTTP hint rather than infinity."""
        self._refill(time.monotonic() if now is None else now)
        if self.level >= n:
            return 0.0
        if self.rate <= 0:
            return 60.0
        return min(60.0, (n - self.level) / self.rate)


class TenantShed(Overloaded):
    """A tenant's token bucket is empty: the front door shed the request
    before it touched the scheduler — HTTP 429 (rides the existing
    Overloaded → 429 mapping in app/api.py). `retry_after_s` is
    bucket-aware: max(bucket refill ETA, fleet backpressure hint), so a
    rate-limited tenant is never told to retry into the same empty
    bucket."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 tenant: str = "", qos: str = ""):
        super().__init__(message, retry_after_s=retry_after_s)
        self.tenant = tenant
        self.qos = qos


class TenantBucketRegistry:
    """Per-(tenant, class) token buckets from the rate/burst specs.
    Rate 0 (the default) disables shedding entirely — QoS without
    configured budgets still gets WFQ fairness, just no hard ceiling.
    Bucket count is bounded: beyond `max_buckets` distinct keys, new
    tenants share the overflow bucket (a tenant-id flood cannot grow
    memory without bound — and the overflow bucket throttling strangers
    collectively is the *right* failure mode under such a flood)."""

    def __init__(self, rate_spec: str = "", burst_spec: str = "",
                 max_buckets: int = 4 * TENANT_TOPK):
        self.rate_base, self.rate_per = _parse_budget_spec(rate_spec)
        self.burst_base, self.burst_per = _parse_budget_spec(burst_spec)
        self.max_buckets = max_buckets
        self._buckets: Dict[Tuple[str, str], TokenBucket] = {}

    def _limits(self, qos: str) -> Tuple[float, float]:
        rate = self.rate_per.get(qos, self.rate_base)
        # Default burst = 2 seconds of rate (room for a small volley)
        # unless the operator pins one.
        burst = self.burst_per.get(
            qos, self.burst_base if self.burst_base > 0
            else max(1.0, 2.0 * rate))
        return rate, burst

    def bucket(self, tenant: str, qos: str) -> Optional[TokenBucket]:
        """The live bucket for (tenant, class); None when that class is
        unlimited (rate <= 0)."""
        rate, burst = self._limits(qos)
        if rate <= 0:
            return None
        key = (tenant or DEFAULT_TENANT, qos)
        b = self._buckets.get(key)
        if b is None:
            if len(self._buckets) >= self.max_buckets:
                key = (OTHER_TENANT, qos)
                b = self._buckets.get(key)
            if b is None:
                b = self._buckets[key] = TokenBucket(rate, burst)
        return b

    def check(self, tenant: str, qos: str,
              now: Optional[float] = None) -> Optional[float]:
        """Take one token for (tenant, class). None = admitted; a float
        = shed, with that many seconds until the bucket refills."""
        b = self.bucket(tenant, qos)
        if b is None or b.take(1.0, now=now):
            return None
        return max(0.0, b.refill_eta(1.0, now=now))


class AdmissionController:
    """Process-wide front-door state: the bucket registry, per-class
    default deadlines, and bounded per-tenant admit/shed counters (the
    "qos" block in /metrics → the lsot_tenant_* Prometheus families).
    Reconfigured from the environment at app boot (the slo.ENGINE
    pattern); tests call `reconfigure()` directly."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reconfigure()

    def reconfigure(self, enabled: Optional[bool] = None,
                    rate: Optional[str] = None,
                    burst: Optional[str] = None,
                    deadlines: Optional[Dict[str, float]] = None) -> None:
        with self._lock:
            self.enabled = qos_enabled() if enabled is None else bool(enabled)
            self.buckets = TenantBucketRegistry(
                os.environ.get("LSOT_TENANT_RATE", "") if rate is None
                else rate,
                os.environ.get("LSOT_TENANT_BURST", "") if burst is None
                else burst,
            )
            # Per-class default deadline (seconds; 0 = none): applied by
            # the service ONLY when the request carries no deadline of
            # its own — "interactive gets the tighter default deadline
            # the machinery already honors".
            if deadlines is None:
                deadlines = {}
                for cls in QOS_CLASSES:
                    try:
                        deadlines[cls] = float(os.environ.get(
                            f"LSOT_QOS_DEADLINE_{cls.upper()}", "0") or 0)
                    except ValueError:
                        deadlines[cls] = 0.0
            self.class_deadlines = dict(deadlines)
            self.admitted: Dict[str, float] = {}
            self.shed: Dict[str, float] = {}
            self.shed_wait_s: Dict[str, float] = {}

    def default_deadline(self, qos: str) -> Optional[float]:
        """The class's configured default deadline, or None when the
        class has no tighter budget (or traffic is unlabeled)."""
        d = self.class_deadlines.get(qos, 0.0)
        return d if d and d > 0 else None

    def admit(self, tenant: str, qos: str,
              fleet_hint: float = 1.0) -> None:
        """Front-door check: consume one bucket token for (tenant, qos)
        or raise TenantShed with a bucket-aware Retry-After. No-op when
        QoS is off or no rate is configured."""
        if not self.enabled:
            return
        with self._lock:
            if not (tenant or qos) and \
                    self.buckets.bucket(tenant, qos) is None:
                # Quiet single-tenant deployment (no labels, no rates):
                # zero accounting, so the /metrics payload is unchanged.
                return
            eta = self.buckets.check(tenant, qos)
            label = f"{tenant or DEFAULT_TENANT}/{qos or 'batch'}"
            if eta is None:
                bounded_bump(self.admitted, label)
                return
            bounded_bump(self.shed, label)
            bounded_bump(self.shed_wait_s, label, amount=eta)
        # Satellite fix (ISSUE 18): the hint a shed tenant gets must be
        # max(bucket refill time, fleet backpressure hint) — the fleet
        # hint alone would tell a rate-limited tenant to retry into the
        # same empty bucket.
        retry = max(float(eta), float(fleet_hint or 0.0), 0.1)
        raise TenantShed(
            f"tenant {tenant or DEFAULT_TENANT!r} over {qos or 'default'} "
            f"rate budget; retry in {retry:.2f}s",
            retry_after_s=retry, tenant=tenant, qos=qos,
        )

    def snapshot(self) -> Dict[str, object]:
        """The reserved "qos" block for /metrics: non-empty only once
        the front door actually admitted or shed something (a quiet
        single-tenant deployment's payload is unchanged)."""
        with self._lock:
            if not (self.admitted or self.shed):
                return {}
            out: Dict[str, object] = {
                "admitted": dict(self.admitted),
                "shed": dict(self.shed),
            }
            if self.shed_wait_s:
                out["shed_wait_s"] = {
                    k: round(v, 3) for k, v in self.shed_wait_s.items()}
            levels = {}
            for (tenant, qos), b in self.buckets._buckets.items():
                b._refill(time.monotonic())
                levels[f"{tenant}/{qos or 'batch'}"] = round(b.level, 2)
            if levels:
                out["bucket_level"] = levels
            return out


#: The process singleton (the slo.ENGINE pattern): app/__main__ calls
#: ADMISSION.reconfigure() after loading config; the service checks it
#: on every generate/generate_stream.
ADMISSION = AdmissionController()
