"""Completion backends: the text→text seam under the generation service.

`EngineBackend` is the real path (tokenizer + in-tree TPU engine).
`FakeBackend` makes the whole app/eval stack hermetically testable without
weights — the capability the reference never had (its only 'test' needed a
live Ollama server, SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, List, Optional, Sequence

from ..engine.generate import InferenceEngine
from ..ops.sampling import SamplingParams
from ..tokenizer.base import Tokenizer


def resolve_stop_ids(cfg, tokenizer) -> tuple:
    """Union of the checkpoint config's stop list and every stop token the
    tokenizer's vocabulary declares (HFTokenizer.eos_ids). Either source
    alone under-stops llama-3.x chat models: the config may carry only
    <|end_of_text|> while the turn actually ends at <|eot_id|>."""
    ids = list(cfg.stop_ids)
    for i in getattr(tokenizer, "eos_ids", ()):
        if i not in ids:
            ids.append(i)
    return tuple(ids)


@dataclasses.dataclass
class Completion:
    text: str
    output_tokens: int
    prompt_tokens: int = 0
    # Time to first token, when the backend has a first-token seam (the
    # continuous-batching scheduler); 0.0 = not measured.
    ttft_s: float = 0.0
    # Queue wait (submit -> slot admission) on the scheduler path: the
    # backlog share of latency. 0.0 = not measured.
    queue_wait_s: float = 0.0
    # Request class ("constrained"/"speculative"/both/"") and serving
    # replica — the label set the Prometheus histograms slice by.
    rclass: str = ""
    replica: str = ""


def resolve_constraint(constrain, tokenizer, stop_ids):
    """Spec ("spark_sql" / {"table","columns"} / CompiledMask) -> compiled
    grammar tables for a backend's tokenizer + stop ids; None passes
    through. get_constraint caches per triple, so repeated requests reuse
    the same precomputed masks. Shared by EngineBackend and
    SchedulerBackend — one resolution path, not two drifting copies."""
    if constrain is None:
        return None
    from ..constrain import get_constraint

    return get_constraint(constrain, tokenizer, stop_ids)


def stok_seed_from_bench(path: str) -> Optional[float]:
    """Seconds-per-output-token seed from the last committed bench
    artifact line (bench.py emits one JSON artifact per line; the last
    parseable line is the richest). The artifact's headline is AGGREGATE
    output tok/s at batch B, and decode is weight-streaming bound, so the
    wall of one decode step — which is what a serving request pays per
    token regardless of its own batch size — is ~B / value; B is parsed
    from the metric string (falls back to 1, which UNDER-estimates
    s/token and therefore under-clamps: a conservative failure mode, the
    request may overrun its deadline but is never spuriously rejected).
    Returns None when the file is missing/unparseable — callers degrade
    to the unseeded (unclamped-first-request) behavior."""
    import json
    import re

    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    obj = None
    for ln in reversed(text.splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                obj = json.loads(ln)
            except json.JSONDecodeError:
                continue
            break
    if not isinstance(obj, dict):
        return None
    value = obj.get("value")
    if not isinstance(value, (int, float)) or value <= 0:
        return None
    m = re.search(r"B=(\d+)", str(obj.get("metric", "")))
    batch = int(m.group(1)) if m else 1
    return batch / float(value)


def trim_stop_texts(text: str, stop_texts: Sequence[str]) -> str:
    """Cut the completion at the first occurrence of any stop string."""
    for stop in stop_texts:
        cut = text.find(stop)
        if cut != -1:
            text = text[:cut]
    return text


class EngineBackend:
    """Tokenize → engine.generate → detokenize. Thread-safe: one lock per
    backend serializes device work (the continuous-batching scheduler
    replaces this lock for concurrent serving)."""

    #: GenerationService checks this before forwarding a `constrain=` spec.
    supports_constrain = True
    #: Deadline enforcement, smallest slice (ROADMAP follow-up): the
    #: one-XLA-program decode cannot retire mid-flight like the scheduler,
    #: but the STEP BUDGET can be clamped at issue time from the request's
    #: remaining deadline × the measured per-token service rate — so a
    #: nearly-expired request occupies the device for roughly its budget,
    #: not a full max-tokens decode. An already-expired deadline fails
    #: typed before any device work.
    supports_deadline = True

    def __init__(
        self,
        engine: InferenceEngine,
        tokenizer: Tokenizer,
        max_new_tokens: int = 256,
        sampling: SamplingParams = SamplingParams(),
        stop_texts: Sequence[str] = (),
        add_bos: bool = True,
        sec_per_tok_seed: Optional[float] = None,
    ):
        """Set `add_bos=False` for chat templates whose rendered prompt
        already begins with the BOS string (e.g. llama3-chat's
        <|begin_of_text|>) — otherwise the model sees BOS twice, an
        off-distribution prompt that silently degrades output quality.

        `sec_per_tok_seed` primes the deadline-clamp s/token EWMA at
        startup (LSOT_STOK_SEED, or stok_seed_from_bench over the last
        bench artifact): without it the FIRST request after boot runs
        unclamped because there is nothing to exchange a deadline against
        (ROADMAP PR-3 follow-up). The seed is a prior, not a pin — real
        completions EWMA-blend it away at the usual 0.2 rate."""
        self.engine = engine
        self.tokenizer = tokenizer
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.stop_texts = tuple(stop_texts)
        self.add_bos = add_bos
        self._lock = threading.Lock()
        # EWMA of seconds-per-output-token over completed requests (wall /
        # tokens, prefill amortized in): the deadline→step-budget exchange
        # rate. The FIRST completion of each program shape (batch size ×
        # padded prompt length) is discarded — its wall is dominated by
        # that shape's one-time XLA compilation, orders of magnitude off
        # steady state, and would poison the exchange rate into spurious
        # DeadlineExceeded for affordable requests. Until a real sample
        # exists, requests run unclamped (a guessed rate would silently
        # truncate output); shapes the key doesn't capture (budget
        # buckets) can still land one inflated sample, which the 0.2 EWMA
        # bounds (ROADMAP notes the follow-up).
        self._sec_per_tok: Optional[float] = (
            float(sec_per_tok_seed)
            if sec_per_tok_seed is not None and sec_per_tok_seed > 0
            else None
        )
        self._rate_warm_shapes: set = set()

    @classmethod
    def from_hf_checkpoint(
        cls,
        ckpt_dir: str,
        tokenizer: Tokenizer,
        mesh=None,
        dtype=None,
        prompt_bucket: int = 128,
        stop_ids: Optional[Sequence[int]] = None,
        quantize_int8: bool = False,
        quantize_int4: bool = False,
        quantize_unembed8: bool = False,
        speculative_draft: int = 0,
        kv_quant=None,
        **kwargs,
    ) -> "EngineBackend":
        """Stand up a backend straight from an HF-format checkpoint directory
        (the deployment path: weights land pre-sharded on the mesh).

        `quantize_int8=True` converts the block matmul weights to int8
        QTensors before placement (ops/quant.py) — halves weight HBM
        traffic for bandwidth-bound decode; `quantize_int4=True` packs
        them to 4-bit nibbles served by the pallas int4 matmul kernel
        (one quarter of bf16's weight bytes; TP-shards like the other
        quantized layouts — parallel/sharding.specs_for_params).
        `speculative_draft=N` turns on prompt-lookup speculative decoding
        for greedy requests (engine/speculative.py — the NL→SQL
        copy-heavy workload is its sweet spot)."""
        import jax.numpy as jnp

        from ..checkpoint import load_hf_checkpoint

        if quantize_int8 and quantize_int4:
            raise ValueError("pick one of quantize_int8 / quantize_int4")
        if quantize_int8 or quantize_int4 or quantize_unembed8:
            from ..ops.quant import (
                quantize_params,
                quantize_params_int4,
                quantize_unembed,
            )
            from ..parallel.sharding import shard_params

            # Load host-side, quantize, then place: the quantized tree is
            # what ships to devices, not the full-precision one.
            cfg, params = load_hf_checkpoint(
                ckpt_dir, dtype=dtype or jnp.bfloat16, mesh=None
            )
            if quantize_int4:
                params = quantize_params_int4(params)
            elif quantize_int8:
                params = quantize_params(params)
            if quantize_unembed8:
                # Per-row int8 embed/unembed tables (composes with either
                # block quantization — or none).
                params = quantize_unembed(params)
            if mesh is not None:
                params = shard_params(params, cfg, mesh)
        else:
            cfg, params = load_hf_checkpoint(
                ckpt_dir, dtype=dtype or jnp.bfloat16, mesh=mesh
            )
        engine = InferenceEngine(
            cfg, params, mesh=mesh, prompt_bucket=prompt_bucket,
            stop_ids=stop_ids if stop_ids is not None
            else resolve_stop_ids(cfg, tokenizer),
            speculative_draft=speculative_draft, kv_quant=kv_quant,
        )
        return cls(engine, tokenizer, **kwargs)

    @classmethod
    def from_gguf(
        cls,
        gguf_path: str,
        tokenizer: Tokenizer,
        cfg=None,
        mesh=None,
        dtype=None,
        prompt_bucket: int = 128,
        stop_ids: Optional[Sequence[int]] = None,
        quantize_int8: bool = False,
        quantize_int4: bool = False,
        quantize_unembed8: bool = False,
        speculative_draft: int = 0,
        kv_quant=None,
        **kwargs,
    ) -> "EngineBackend":
        """Stand up a backend from a GGUF blob — the exact file format the
        reference's Ollama models ship as (parsed + dequantized by the
        in-tree C++ core, native/src/gguf.cpp). The loader dequantizes the
        blob's own quantization to the compute dtype; `quantize_int8` /
        `quantize_int4` then re-quantize into the in-tree serving formats
        (a Q4 blob served with quantize_int4 stays 4-bit end to end)."""
        from ..checkpoint import load_gguf_checkpoint

        if quantize_int8 and quantize_int4:
            raise ValueError("pick one of quantize_int8 / quantize_int4")
        if quantize_int8 or quantize_int4 or quantize_unembed8:
            from ..ops.quant import (
                quantize_params,
                quantize_params_int4,
                quantize_unembed,
            )
            from ..parallel.sharding import shard_params

            cfg, params = load_gguf_checkpoint(
                gguf_path, cfg=cfg, dtype=dtype, mesh=None
            )
            if quantize_int4:
                params = quantize_params_int4(params)
            elif quantize_int8:
                params = quantize_params(params)
            if quantize_unembed8:
                params = quantize_unembed(params)
            if mesh is not None:
                params = shard_params(params, cfg, mesh)
        else:
            cfg, params = load_gguf_checkpoint(
                gguf_path, cfg=cfg, dtype=dtype, mesh=mesh
            )
        engine = InferenceEngine(
            cfg, params, mesh=mesh, prompt_bucket=prompt_bucket,
            speculative_draft=speculative_draft, kv_quant=kv_quant,
            stop_ids=stop_ids if stop_ids is not None
            else resolve_stop_ids(cfg, tokenizer),
        )
        return cls(engine, tokenizer, **kwargs)

    def check_budget(self, prompt: str,
                     max_new_tokens: Optional[int] = None,
                     constraint=None) -> None:
        """Raise ValueError if `prompt` leaves no decode room — the same
        rejection complete() would make, runnable BEFORE any response
        bytes go on the wire (streaming handlers must turn request-shape
        errors into 400s, which is impossible once 200 headers are sent).
        With a compiled `constraint`, also checks the CLAMPED budget
        (after the context-room clamp complete() applies) against the
        grammar's shortest complete parse."""
        ids = self.tokenizer.encode(prompt, add_bos=self.add_bos)
        room = self._room(len(ids))
        if constraint is not None:
            budget = min(max_new_tokens or self.max_new_tokens, room)
            if budget < constraint.min_new_tokens:
                raise ValueError(
                    f"decode budget {budget} (after the context-room "
                    f"clamp) cannot hold a complete constrained parse "
                    f"(grammar needs >= {constraint.min_new_tokens} tokens)"
                )

    def _room(self, n_prompt_tokens: int) -> int:
        cfg = self.engine.cfg
        room = cfg.max_seq_len - self.engine.padded_prompt_len(n_prompt_tokens)
        if room < 1:
            raise ValueError(
                f"prompt ({n_prompt_tokens} tokens) leaves no room in the "
                f"{cfg.max_seq_len}-token context of {cfg.name}"
            )
        return room

    def _resolve_constraint(self, constrain):
        return resolve_constraint(constrain, self.tokenizer,
                                  self.engine.stop_ids)

    @staticmethod
    def _make_deadline(deadline_s: Optional[float]):
        """Stamp the deadline at REQUEST ENTRY: the exchange below runs
        inside the backend lock, so time queued behind another decode on
        this serialized engine is charged against the budget too."""
        if deadline_s is None:
            return None
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        from .resilience import Deadline

        return Deadline.after(deadline_s)

    def _deadline_budget(self, budget: int, deadline) -> int:
        """Exchange the REMAINING deadline for a step budget: tokens the
        request can afford at the measured rate. Expired (or unaffordable
        even for one token) fails typed DeadlineExceeded BEFORE the device
        is touched — the engine has no mid-decode retirement, so issue
        time is the only enforcement point (smallest slice)."""
        if deadline is None:
            return budget
        from ..utils.observability import resilience
        from .resilience import DeadlineExceeded

        remaining = deadline.remaining()
        if remaining <= 0:
            resilience.inc("deadline_expired")
            raise DeadlineExceeded(
                "request deadline expired before issue (burned queueing "
                "behind the serialized engine)"
            )
        rate = self._sec_per_tok
        if rate is None or rate <= 0:
            return budget
        afford = int(remaining / rate)
        if afford < 1:
            resilience.inc("deadline_expired")
            raise DeadlineExceeded(
                f"remaining deadline of {remaining:.3f}s cannot afford one "
                f"token at the measured {rate:.4f}s/token — not issued"
            )
        if afford < budget:
            resilience.inc("deadline_clamps")
            return afford
        return budget

    def _record_rate(self, wall_s: float, output_tokens: int,
                     shape: tuple) -> None:
        if output_tokens < 1 or wall_s <= 0:
            return
        if shape not in self._rate_warm_shapes:
            # First completion at this program shape: wall includes that
            # shape's jit compile — discard.
            self._rate_warm_shapes.add(shape)
            return
        rate = wall_s / output_tokens
        prev = self._sec_per_tok
        self._sec_per_tok = rate if prev is None else 0.2 * rate + 0.8 * prev

    def complete(self, prompt: str, max_new_tokens: Optional[int] = None,
                 sampling: Optional[SamplingParams] = None, seed: int = 0,
                 constrain=None,
                 deadline_s: Optional[float] = None) -> Completion:
        import time

        deadline = self._make_deadline(deadline_s)
        ids = self.tokenizer.encode(prompt, add_bos=self.add_bos)
        # Clamp the decode budget to what fits the model context after the
        # bucketed (and sp-padded, on a sequence-parallel mesh) prompt: a
        # serving backend degrades to a shorter completion instead of
        # erroring (the engine itself raises on overflow).
        room = self._room(len(ids))
        budget = min(max_new_tokens or self.max_new_tokens, room)
        # Resolve (and first-use compile) the grammar OUTSIDE the timed
        # window: a one-off token-mask precompute inside it would poison
        # the s/token rate the deadline exchange runs on.
        constraint = self._resolve_constraint(constrain)
        with self._lock:
            # Inside the lock: the wait behind another decode has already
            # been charged against the deadline by the time we exchange
            # what REMAINS for a step budget.
            budget = self._deadline_budget(budget, deadline)
            t0 = time.perf_counter()
            out = self.engine.generate(
                [ids],
                max_new_tokens=budget,
                sampling=sampling or self.sampling,
                seed=seed,
                constraint=constraint,
            )[0]
            self._record_rate(time.perf_counter() - t0, len(out),
                              (1, self.engine.padded_prompt_len(len(ids))))
        # Strip the stop token itself from the text.
        if out and out[-1] in self.engine.stop_ids:
            out = out[:-1]
        text = trim_stop_texts(self.tokenizer.decode(out), self.stop_texts)
        return Completion(text=text, output_tokens=len(out), prompt_tokens=len(ids))

    def complete_batch(
        self, prompts: Sequence[str], max_new_tokens: Optional[int] = None,
        sampling: Optional[SamplingParams] = None, seed: int = 0,
        constrain=None, deadline_s: Optional[float] = None,
    ) -> List[Completion]:
        """One batched device program for many prompts (BASELINE config 4:
        batch=32 Spider questions) — amortizes weight streaming across the
        whole batch instead of paying it per request. A `deadline_s` clamps
        the SHARED step budget (the batch decodes in lockstep, so the
        deadline is the batch's, not per member)."""
        import time

        deadline = self._make_deadline(deadline_s)
        ids = [self.tokenizer.encode(p, add_bos=self.add_bos) for p in prompts]
        room = self.engine.cfg.max_seq_len - self.engine.padded_prompt_len(
            max(len(i) for i in ids)
        )
        if room < 1:
            raise ValueError("longest prompt leaves no decode room")
        budget = min(max_new_tokens or self.max_new_tokens, room)
        constraint = self._resolve_constraint(constrain)  # outside the timer
        with self._lock:
            budget = self._deadline_budget(budget, deadline)
            t0 = time.perf_counter()
            outs = self.engine.generate(
                ids, max_new_tokens=budget,
                sampling=sampling or self.sampling, seed=seed,
                constraint=constraint,
            )
            self._record_rate(
                time.perf_counter() - t0,
                max(len(o) for o in outs) if outs else 0,
                (len(prompts), self.engine.padded_prompt_len(
                    max(len(i) for i in ids))),
            )
        completions = []
        for prompt_ids, out in zip(ids, outs):
            if out and out[-1] in self.engine.stop_ids:
                out = out[:-1]
            text = trim_stop_texts(self.tokenizer.decode(out), self.stop_texts)
            completions.append(Completion(
                text=text, output_tokens=len(out),
                prompt_tokens=len(prompt_ids),
            ))
        return completions


class FakeBackend:
    """Deterministic canned backend: `fn(prompt) -> text`."""

    def __init__(self, fn: Callable[[str], str]):
        self.fn = fn
        self.calls: List[str] = []

    def complete(self, prompt: str, max_new_tokens: Optional[int] = None,
                 sampling: Optional[SamplingParams] = None, seed: int = 0) -> Completion:
        self.calls.append(prompt)
        text = self.fn(prompt)
        return Completion(
            text=text,
            output_tokens=len(text.split()),
            prompt_tokens=len(prompt.split()),
        )

    def complete_batch(self, prompts, max_new_tokens=None, sampling=None,
                       seed: int = 0) -> List[Completion]:
        return [self.complete(p, max_new_tokens, sampling, seed) for p in prompts]
