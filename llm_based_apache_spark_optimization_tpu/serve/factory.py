"""Reference-service assembly shared by the product CLI and the runbook.

One place owns the role -> model -> template mapping of the reference's
three-model zoo (`duckdb-nsql` NL->SQL completion, `llama3.2` error analysis
on the llama3 chat template, optional `mistral` on [INST] — reference
`Flask/app.py:102-107,160-166`, `Model_Evaluation_&_Comparision.py:69,83`)
and the shared-weights aliasing rule, so a stop-id or template fix lands
once instead of drifting between `app/__main__.py` and `runbook.py` (which
differ only in HOW weights load: direct vs through the orbax cache).
"""

from __future__ import annotations

from typing import Callable, Optional

from .service import GenerationService


def assemble_multimodel_service(
    spec,
    *,
    max_new_tokens: int = 48,
    supervise: bool = False,
    num_slots: int = 2,
    total_pages: int = 0,
    seed: int = 0,
):
    """LSOT_MODELS assembly (ISSUE 16): co-resident checkpoints in ONE
    scheduler pool that routes on `model_id`.

    `spec` is the `LSOT_MODELS` string (or a pre-parsed list of
    `ModelSpec`). Only `tiny` sources assemble here — the proof-harness
    fleet tests, smoke scripts and the bench `multi_model` leg use;
    `hf`/`gguf` sources carry real checkpoints and assemble through
    `app/__main__.py --backend checkpoint`, which owns mesh/quant
    plumbing. Returns `(service, pool, registry)`.

    This path REPLACES `assemble_reference_service` when LSOT_MODELS is
    set: the error model becomes its own registered checkpoint (the
    in-fleet explainer) instead of a shared-weights alias of the SQL
    model. With LSOT_MODELS unset, the alias path below runs unchanged
    — bit for bit.
    """
    from .modelpool import build_tiny_model_service, parse_models_spec

    specs = parse_models_spec(spec) if isinstance(spec, str) else list(spec)
    if not specs:
        raise ValueError("LSOT_MODELS is empty")
    bad = [m.model_id for m in specs if m.source != "tiny"]
    if bad:
        raise ValueError(
            f"models {bad} have hf/gguf sources — assemble real "
            f"checkpoints through --backend checkpoint (this path "
            f"builds the tiny proof-harness fleet)"
        )
    return build_tiny_model_service(
        specs, num_slots=num_slots, total_pages=total_pages,
        max_new_tokens=max_new_tokens, supervise=supervise, seed=seed,
    )


def assemble_reference_service(
    build: Callable[[str, bool], object],
    sql_src: str,
    error_src: Optional[str] = None,
    mistral_src: Optional[str] = None,
    *,
    max_new_tokens: int = 256,
) -> GenerationService:
    """`build(src, add_bos) -> backend` supplies loaded backends; this
    assembles the registry.

    - llama3-chat's rendered prompt starts with <|begin_of_text|>, so the
      error-analysis backend must not prepend a second BOS.
    - Without a separate error model, the error role reuses the SQL
      backend's loaded engine/scheduler (one param placement, one slot
      pool) — only the template and add_bos differ.
    """
    from .backends import EngineBackend
    from .scheduler import SchedulerBackend

    svc = GenerationService()
    sql_backend = build(sql_src, True)
    svc.register("duckdb-nsql", sql_backend)
    if error_src:
        error_backend = build(error_src, False)
    elif isinstance(sql_backend, SchedulerBackend):
        error_backend = SchedulerBackend(
            sql_backend.scheduler, sql_backend.tokenizer,
            max_new_tokens=max_new_tokens, add_bos=False,
        )
    else:
        error_backend = EngineBackend(
            sql_backend.engine, sql_backend.tokenizer,
            max_new_tokens=max_new_tokens, add_bos=False,
        )
    svc.register("llama3.2", error_backend, template="llama3-chat")
    if mistral_src:
        svc.register("mistral", build(mistral_src, True),
                     template="mistral-instruct")
    return svc


def standby_spawner(spec=None, *, label_prefix: str = "standby",
                    connect_timeout_s: float = 5.0):
    """Elastic fleet spawn source (ISSUE 17): turn `LSOT_FLEET_WORKERS`
    — a comma-separated list of standby `serve.remote` worker addresses
    ("host:port,host:port") — into the `spawn` callable a
    `FleetAutoscaler` pops from on scale-up.

    Each call connects a `SocketTransport` to the NEXT unclaimed
    address and returns it (the pool's `add_replica` then runs the
    page-geometry/model join handshake); `None` once every standby is
    claimed — the autoscaler counts that as `spawn_empty` and the fleet
    stays at its current size. A standby that refuses the connection
    raises, which the autoscaler (and its chaos `fleet:spawn` seam)
    degrades to a counted spawn failure. Addresses are claimed
    permanently: a retired worker's process was told to drain, so its
    address is not silently reused."""
    import os as _os
    import threading as _threading

    from .remote import SocketTransport

    raw = spec if spec is not None else _os.environ.get(
        "LSOT_FLEET_WORKERS", "")
    if isinstance(raw, str):
        addrs = [a.strip() for a in raw.split(",") if a.strip()]
    else:
        addrs = [str(a) for a in raw]
    lock = _threading.Lock()
    state = {"next": 0}

    def spawn():
        with lock:
            i = state["next"]
            if i >= len(addrs):
                return None
            state["next"] = i + 1
        return SocketTransport(
            addrs[i], label=f"{label_prefix}{i}",
            connect_timeout_s=connect_timeout_s,
        )

    spawn.addresses = tuple(addrs)
    return spawn
