"""TPU-native NL->Spark-SQL studio.

A from-scratch, TPU-first framework with the capabilities of the reference
`Rajwardhan0511/LLM-Based-Apache-Spark-Optimization` (see SURVEY.md): a
natural-language data studio where a CSV + English question become Spark SQL
via a text-to-SQL LLM, the SQL is executed, results exported and recorded in a
query-history store, and failures are diagnosed by a second LLM — with the LLM
inference engine **in-tree** as a JAX/XLA stack (reference delegates it to an
out-of-process Ollama/llama.cpp sidecar, reference `Flask/app.py:102-107`).

Subpackages (bottom-up):
  models/      Llama-family transformer definitions (pure-functional JAX)
  ops/         numerical building blocks: rmsnorm, rope, attention, sampling,
               Pallas TPU kernels
  engine/      generation runtime: KV cache, prefill/decode, samplers
  parallel/    device mesh, TP/DP/SP sharding, ring attention, collectives
  checkpoint/  weight loading (HF safetensors -> sharded jax.Arrays)
  tokenizer/   in-tree BPE tokenizers (+ optional HF tokenizer.json loader)
  serve/       model registry + generation service + continuous batching
  sql/         Spark-parity SQL execution backends (CSV -> temp_view -> SQL)
  history/     query_results history store (sqlite default, MySQL optional)
  app/         web layer: WSGI micro-framework, Flask-parity UI,
               FastAPI-parity JSON API
  evalh/       evaluation harness (exact match / edit distance / latency)
  utils/       config, logging, timing/tracing
"""

__version__ = "0.1.0"
