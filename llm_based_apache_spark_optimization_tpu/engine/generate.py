"""Autoregressive generation: batched prefill + `lax.while_loop` decode.

This module is the heart of the in-tree engine that replaces the reference's
`ollama.generate(...)` calls (reference `Flask/app.py:102-107,160-166`,
`FastAPI/app.py:85-90,105-111`). One jit-compiled function per
(batch, prompt-bucket, max_new, sampling) signature does:

    prefill (all prompt tokens at once, MXU-bound)
      -> sample first token from each sequence's last real logit
      -> while_loop decode (one token/step, HBM-bandwidth-bound)
         with per-sequence stop-token handling and early exit when
         every sequence is done.

TPU/XLA notes:
- The whole generate call is ONE XLA program: no host round-trip per token.
  The while_loop carries the KV cache; XLA keeps it in HBM and updates it
  in place.
- Early exit is real: the loop condition is `step < max_new & ~all(done)`,
  so a batch of short SQL answers doesn't pay for the longest possible
  completion.
- Prompt lengths are bucketed (engine/kvcache.bucket_len) so the number of
  distinct compilations stays small; compiled fns are cached per signature.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models.configs import LlamaConfig
from ..models.llama import Params, forward, split_blocks
from ..ops.pallas import attention_impl, decode_attention_impl
from ..ops.sampling import SamplingParams, apply_token_mask, sample
from ..parallel.sharding import constrain_cache, shard_batch, shard_params
from .kvcache import bucket_len, init_cache


def _is_stop(tok: jnp.ndarray, stop_ids: Tuple[int, ...]) -> jnp.ndarray:
    hit = jnp.zeros(tok.shape, jnp.bool_)
    for s in stop_ids:
        hit = hit | (tok == s)
    return hit


def make_generate_fn(
    cfg: LlamaConfig,
    max_new: int,
    sampling: SamplingParams,
    stop_ids: Tuple[int, ...],
    mesh=None,
    attn_impl: Optional[str] = None,
    kv_quant: Optional[str] = None,
    constrained: bool = False,
    kv_layout: str = "contiguous",
    kv_page_size: Optional[int] = None,
):
    """Resolve the attention impl *outside* the cache boundary so a
    set_attention_impl() flip between calls maps to a different cache key
    (and thus a fresh compilation) even for callers that omit attn_impl.

    `max_new` here is the compile-time CAP (output buffer width / cache
    allocation); the returned fn takes a traced `budget` argument that bounds
    the decode loop at runtime, so callers can serve any budget <= cap from
    one compilation (serving backends bucket the cap — see
    InferenceEngine.new_bucket — instead of compiling per distinct budget).

    Prefill and decode resolve their impls separately: the engine's cache is
    request-sized and mostly live, so auto-mode decode takes the XLA einsum
    path (`ops.pallas.decode_attention_impl`) — the flash kernel's bounded
    streaming has nothing to bound there and its per-cell overhead is pure
    loss (measured: einsum decode 2160 vs kernel 1978 tok/s at B=8, 4091 vs
    2779 at B=32 on v5e). An explicit `attn_impl` forces both phases.

    `kv_quant="int8"` stores the decode-time KV cache as int8 with per-slot
    scales: prefill fills the normal bf16 cache, one pass quantizes it
    (ops/quant.quantize_kv), and every decode step streams half the cache
    bytes (decode is cache-streaming-bound at long context). Decodes via
    the einsum impl (auto default) or, when forced, the int8-streaming
    flash kernel (flash_gqa_attention_quantized).

    `constrained=True` returns a fn taking two extra traced arguments —
    `(next, need)` grammar tables from
    constrain.CompiledMask.device_tables, plus `init_states [B]` — and
    runs the grammar FSM ON DEVICE: every step gathers the state's
    precomputed tokens-to-finish row, masks out entries that no longer
    fit the remaining budget, and advances the state by one
    [state, token] gather. No host round-trip, no per-token Python over
    the vocab, still ONE XLA program.

    `kv_layout="paged"` swaps the decode loop's cache for the paged pool
    (engine/paged_kv.py): prefill still runs the contiguous scan path over
    a PROMPT-sized transient cache, one transpose-scatter packs it into
    pool pages with identity per-row tables, and every decode step
    reads/writes K/V through the page table — the same paged programs the
    continuous-batching scheduler serves with, parity-tested here where
    the loop is a single jit. Page size rides `kv_page_size` /
    LSOT_KV_PAGE_SIZE.
    """
    if kv_layout not in ("contiguous", "paged"):
        raise ValueError(
            f"kv_layout must be 'contiguous' or 'paged', got {kv_layout!r}"
        )
    page_size = 0
    if kv_layout == "paged":
        from .paged_kv import default_page_size

        page_size = int(kv_page_size or default_page_size())
        # kv_quant="int8" + paged (ISSUE 11): the pool stores int8 pages
        # + per-position scales — quantized inside pack_prefill_pages,
        # dequantized in the ragged read kernel's DMA'd tiles / the
        # int8-streaming reference path. A mesh shards the pool's KV-head
        # axis over tp like the contiguous cache (constrain_cache's paged
        # branch); page tables replicate.
    return _make_generate_fn(
        cfg, max_new, sampling, stop_ids, mesh,
        attn_impl or attention_impl(mesh),
        attn_impl or decode_attention_impl(mesh),
        kv_quant,
        constrained,
        kv_layout,
        page_size,
    )


@functools.lru_cache(maxsize=64)
def _make_generate_fn(
    cfg: LlamaConfig,
    max_new: int,
    sampling: SamplingParams,
    stop_ids: Tuple[int, ...],
    mesh,
    attn_impl: str,
    decode_impl: str,
    kv_quant: Optional[str] = None,
    constrained: bool = False,
    kv_layout: str = "contiguous",
    page_size: int = 0,
):
    """Build + jit a generate function for a fixed decode-budget cap and sampler.

    Returned fn: (params, tokens [B,T] i32, lengths [B] i32, budget [] i32,
    key) -> (out_tokens [B, max_new] i32, gen_lens [B] i32), with the loop
    stopping at the traced `budget` (<= max_new cap). Cached so repeated
    calls with the same signature reuse the compiled executable.

    With a `jax.sharding.Mesh`, the KV cache allocated inside the program is
    pinned to the TP×DP×SP layout (parallel/sharding.cache_spec — KV heads
    over tp, batch over dp, cache SLOTS over sp, so an sp-way mesh fits
    sp× the context); params/tokens carry their own NamedShardings in, and
    GSPMD lays the collectives.
    """
    pad_id = cfg.pad_id
    impl = attn_impl
    # With a sequence-parallel axis in the mesh, prefill runs ring attention
    # (sequence sharded over sp, KV blocks rotating on ICI); decode keeps the
    # resolved single-block impl — its T=1 queries have nothing to shard.
    sp = dict(mesh.shape).get("sp", 1) if mesh is not None else 1
    prefill_impl = "ring" if sp > 1 else impl
    if kv_quant not in (None, "int8"):
        raise ValueError(f"kv_quant must be None or 'int8', got {kv_quant!r}")
    if sp > 1 and decode_impl == "pallas":
        # The flash decode kernel's shard_map expects S-replicated K/V;
        # against the sp-sharded cache (parallel/sharding.cache_spec) GSPMD
        # would all-gather the whole cache every step — OOM at exactly the
        # long-context sizes sp exists to serve. The einsum path IS the sp
        # decode impl (flash-decoding-style partial combines).
        raise ValueError(
            "attn_impl='pallas' decode cannot run on an sp>1 mesh: the "
            "sequence-sharded cache would be all-gathered every step; use "
            "the auto/einsum decode impl"
        )
    if kv_quant and decode_impl not in ("xla", "pallas"):
        # "xla" is the auto default (uniform engine caches are mostly live
        # — ops.pallas.decode_attention_impl); a forced "pallas" runs the
        # int8-streaming flash decode kernel
        # (flash_gqa_attention_quantized). Ring has no quantized path.
        raise ValueError(
            "kv_quant='int8' decodes through the einsum impl (auto "
            f"default) or the pallas flash kernel; resolved to "
            f"{decode_impl!r}"
        )

    def gen(
        params: Params,
        tokens: jnp.ndarray,
        lengths: jnp.ndarray,
        budget: jnp.ndarray,
        key: jax.Array,
        grammar=None,       # (next [S,V] i32, need [S,V] i32) device tables
        init_states=None,   # [B] int32 DFA start states (0 = unconstrained)
    ):
        b, t = tokens.shape
        # The output buffer and cache are sized for the compile-time cap; a
        # caller-passed budget beyond it would silently corrupt both, so
        # clamp (InferenceEngine always passes budget <= cap, but this fn is
        # exported for direct use).
        budget = jnp.minimum(budget, max_new)
        paged = kv_layout == "paged"
        # Paged mode prefills a PROMPT-sized transient cache (packed into
        # pool pages after the prefill forward); contiguous allocates the
        # whole prompt+completion window up front.
        cache = init_cache(cfg, b, t if paged else t + max_new,
                           dtype=params["final_norm"].dtype)
        if mesh is not None:
            cache = constrain_cache(cache, mesh)
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))
        # Unembed only each sequence's last real position: sampling never looks
        # at the other T-1 logits, and skipping them drops the [B, T, V]
        # prefill unembed to [B, 1, V].
        logits, cache = forward(
            cfg, params, tokens, positions, cache,
            logit_indices=lengths - 1, attn_impl=prefill_impl, mesh=mesh,
        )
        first_logits = logits[:, 0]
        if constrained:
            g_next, g_need = grammar
            # The first token is constrained too (otherwise one free token
            # breaks the guarantee): a token is allowed iff the tokens it
            # commits to — itself, the shortest completion after it, the
            # stop id — fit the whole budget (g_need table, masks.py).
            first_logits = apply_token_mask(
                first_logits, g_need[init_states] <= budget
            )
        first = sample(first_logits, sampling, jax.random.fold_in(key, 0))
        cstate = g_next[init_states, first] if constrained else None
        done = _is_stop(first, stop_ids)
        out = jnp.full((b, max_new), pad_id, jnp.int32)
        out = out.at[:, 0].set(first)
        # Per-layer weight slices anchored OUTSIDE the decode loop: layout
        # conversions for the decode matmuls run once per call, not per
        # token (split_blocks docstring). Only the unrolled decode branch
        # accepts pre-sliced params — a forced ring impl scans instead.
        dec_params = params if decode_impl == "ring" else split_blocks(params)

        if paged:
            # Prefill→decode handoff: pack the prompt K/V into pool pages
            # with identity per-row tables; the while_loop below carries
            # the pool, and forward's paged branch reads/writes through
            # the table every step (the same paged decode program shape
            # the scheduler serves with). kv_quant="int8" quantizes
            # INSIDE the pack (int8 pages + per-position scales) — the
            # same prefill-bf16-then-quantize-once handoff as the
            # contiguous int8 path, per page.
            from .paged_kv import pack_prefill_pages

            ppr = -(-(t + max_new) // page_size)
            cache = pack_prefill_pages(cache, page_size, ppr,
                                       kv_quant=kv_quant)
            if mesh is not None:
                cache = constrain_cache(cache, mesh)
        elif kv_quant:
            # One-pass cache quantization between prefill and decode: the
            # loop carries int8 values + f32 per-slot scales and every step
            # streams ~half the cache bytes (ops/quant.quantize_kv).
            from ..ops.quant import quantize_cache

            cache = quantize_cache(cache["k"], cache["v"])
            if mesh is not None:
                cache = constrain_cache(cache, mesh)

        def cond(carry):
            done, step = carry[3], carry[5]
            return (step < budget) & ~jnp.all(done)

        def body(carry):
            out, cur, pos, done, cache, step = carry[:6]
            logits, cache = forward(
                cfg, dec_params, cur[:, None], pos[:, None], cache,
                attn_impl=decode_impl, mesh=mesh,
            )
            step_logits = logits[:, 0]
            if constrained:
                cstate = carry[6]
                # A token is allowed iff its completion still fits the
                # remaining budget (need table): tokens that merely keep
                # the DFA alive but can no longer close in time drop out
                # exactly when that becomes true, so the guarantee holds
                # for any budget >= the grammar's shortest parse. One
                # gather + one compare per step.
                rem = budget - step
                step_logits = apply_token_mask(
                    step_logits, g_need[cstate] <= rem
                )
            nxt = sample(step_logits, sampling, jax.random.fold_in(key, step))
            nxt = jnp.where(done, pad_id, nxt)
            tail = ()
            if constrained:
                # Finished rows freeze their state (their pad fill must not
                # walk the FSM); live rows advance one [state, token]
                # gather — the whole per-step grammar cost.
                tail = (jnp.where(done, cstate, g_next[cstate, nxt]),)
            done = done | _is_stop(nxt, stop_ids)
            out = lax.dynamic_update_slice(out, nxt[:, None], (0, step))
            return (out, nxt, pos + 1, done, cache, step + 1) + tail

        carry = (out, first, lengths.astype(jnp.int32), done, cache,
                 jnp.int32(1))
        if constrained:
            carry = carry + (cstate,)
        final = lax.while_loop(cond, body, carry)
        out, done = final[0], final[3]

        stops = _is_stop(out, stop_ids)
        gen_lens = jnp.where(
            jnp.any(stops, axis=1),
            jnp.argmax(stops, axis=1).astype(jnp.int32) + 1,
            budget.astype(jnp.int32),
        )
        return out, gen_lens

    return jax.jit(gen)


class InferenceEngine:
    """Convenience host-side wrapper: ragged python prompts -> ragged outputs.

    Pads/buckets prompts, dispatches to the cached jitted generate fn, and
    slices per-sequence completions. This is the object the serve/ registry
    holds per model name.
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        params: Params,
        stop_ids: Optional[Sequence[int]] = None,
        prompt_bucket: int = 128,
        mesh=None,
        new_bucket: int = 64,
        speculative_draft: int = 0,
        speculative_ngram: int = 3,
        kv_quant: Optional[str] = None,
        fuse_matmuls: bool = False,
        kv_layout: str = "contiguous",
        kv_page_size: Optional[int] = None,
    ):
        self.cfg = cfg
        self.mesh = mesh
        # Fused wqkv/wgu matmuls (models/llama.fuse_blocks): fewer, wider
        # MXU calls — a prefill-throughput lever.
        if fuse_matmuls:
            from ..models.llama import maybe_fuse

            params = maybe_fuse(params, mesh)
        # "int8": decode streams an int8 KV cache (half the cache bytes;
        # make_generate_fn docstring). Greedy/sampled both supported. The
        # CONTIGUOUS speculative path has no int8-KV variant (its verify
        # loop streams the bf16 cache), and silently dropping a requested
        # memory/bandwidth mode would misattribute results — so that
        # combination stays rejected; the PAGED pool's verify windows run
        # the int8-streaming reference gather, so int8 + paged +
        # speculative composes.
        if kv_quant and speculative_draft and kv_layout != "paged":
            raise ValueError(
                "kv_quant and speculative_draft cannot combine on the "
                "contiguous layout: the speculative verify loop streams "
                "the bf16 cache (use kv_layout='paged')"
            )
        self.kv_quant = kv_quant
        # "paged": decode loops carry the shared page pool + per-row page
        # tables instead of a contiguous cache (engine/paged_kv.py) —
        # greedy-parity-tested against the contiguous layout, and the
        # engine-side proof of the programs the scheduler serves with.
        # Composes with kv_quant="int8" (int8 pages + per-position
        # scales) and with a mesh (pool KV heads shard over tp).
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be 'contiguous' or 'paged', got "
                f"{kv_layout!r}"
            )
        self.kv_layout = kv_layout
        self.kv_page_size = kv_page_size
        # Prompt-lookup speculative decoding (engine/speculative.py):
        # requests draft `speculative_draft` tokens per round by n-gram
        # lookup over prompt+history and verify them in one forward. 0
        # disables. Greedy requests verify by exact argmax; sampled
        # requests verify by rejection sampling (unbiased).
        self.speculative_draft = speculative_draft
        self.speculative_ngram = speculative_ngram
        # Diagnostics from the last speculative generate: verify rounds vs
        # tokens emitted (rounds << tokens means drafts were accepted).
        self.last_spec_rounds: Optional[int] = None
        if mesh is not None:
            params = shard_params(params, cfg, mesh)
        self.params = params
        self.stop_ids = tuple(stop_ids) if stop_ids is not None else cfg.stop_ids
        # A bucket as large as the whole context would leave no decode room
        # after bucketing even a short prompt; cap at half the context.
        self.prompt_bucket = min(prompt_bucket, max(1, cfg.max_seq_len // 2))
        # Decode budgets are bucketed the same way prompts are: the compiled
        # program's cap rounds up to a multiple of new_bucket and the loop
        # stops at the traced budget, so serving backends that clamp
        # max_new to per-prompt context room (serve/backends.py) don't
        # compile one program per distinct budget value.
        self.new_bucket = max(1, new_bucket)

    def padded_prompt_len(self, n: int) -> int:
        """Device-side prompt length for an n-token prompt: bucketed, then —
        on an sp mesh — padded so ring prefill gives each device an equal
        sequence block. Callers budgeting decode room against max_seq_len
        (serve/backends.py) must use this, not bucket_len alone."""
        t = bucket_len(n, self.prompt_bucket)
        if self.mesh is not None:
            t += -t % dict(self.mesh.shape).get("sp", 1)
        return t

    def generate(
        self,
        prompts: List[List[int]],
        max_new_tokens: int = 256,
        sampling: SamplingParams = SamplingParams(),
        seed: int = 0,
        constraint=None,  # constrain.CompiledMask: grammar-masked decode
    ) -> List[List[int]]:
        assert prompts and all(len(p) >= 1 for p in prompts), "empty prompt"
        b = len(prompts)
        if constraint is not None and max_new_tokens < constraint.min_new_tokens:
            raise ValueError(
                f"max_new_tokens={max_new_tokens} cannot hold a complete "
                f"constrained parse (grammar needs >= "
                f"{constraint.min_new_tokens} tokens incl. the stop id)"
            )
        t = self.padded_prompt_len(max(len(p) for p in prompts))
        if t + max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"bucketed prompt ({t}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds model context max_seq_len={self.cfg.max_seq_len}"
            )
        padded = list(prompts)
        if self.mesh is not None:
            # The batch axis shards over dp; pad with dummy rows to a multiple
            # of dp (sliced off after decode) so any request count works.
            dp = self.mesh.shape["dp"]
            padded += [[self.cfg.bos_id]] * (-b % dp)
        tokens = jnp.asarray(
            [p + [self.cfg.pad_id] * (t - len(p)) for p in padded], jnp.int32
        )
        lengths = jnp.asarray([len(p) for p in padded], jnp.int32)
        if self.mesh is not None:
            tokens, lengths = shard_batch((tokens, lengths), self.mesh)
        cap = min(bucket_len(int(max_new_tokens), self.new_bucket),
                  self.cfg.max_seq_len - t)
        if self.speculative_draft > 0:
            # Constrained requests speculate too: the verify window
            # evaluates the grammar mask at every draft position
            # (constrain.fsm_advance_chain threads per-position FSM states
            # through the chain), so drafted tokens cannot bypass the mask
            # and greedy output stays token-identical to constrained
            # vanilla decode. Sampled requests run rejection-sampling
            # verification (engine/speculative.rejection_sample_chain):
            # distribution-identical to the vanilla sampled loop, not
            # token-identical — the RNG consumption pattern differs.
            from .speculative import make_speculative_generate_fn

            fn = make_speculative_generate_fn(
                self.cfg, cap, self.stop_ids, self.mesh,
                self.speculative_draft, self.speculative_ngram,
                constrained=constraint is not None,
                kv_layout=self.kv_layout, kv_page_size=self.kv_page_size,
                kv_quant=self.kv_quant,
                sampling=sampling,
            )
            args = [
                self.params, tokens, lengths, jnp.int32(max_new_tokens),
                # key: unused by the greedy verify, drives the
                # accept/residual draws in sampled mode.
                None if sampling.is_greedy else jax.random.key(seed),
            ]
            if constraint is not None:
                tabs = constraint.device_tables(self.cfg.vocab_size)
                args += [
                    (tabs["next"], tabs["need"]),
                    jnp.full((tokens.shape[0],), constraint.init_state,
                             jnp.int32),
                ]
            out, gen_lens, rounds = fn(*args)
            self.last_spec_rounds = int(jax.device_get(rounds))
        else:
            self.last_spec_rounds = None  # this call ran no speculation
            fn = make_generate_fn(
                self.cfg, cap, sampling, self.stop_ids, self.mesh,
                kv_quant=self.kv_quant,
                constrained=constraint is not None,
                kv_layout=self.kv_layout, kv_page_size=self.kv_page_size,
            )
            args = [
                self.params, tokens, lengths, jnp.int32(max_new_tokens),
                jax.random.key(seed),
            ]
            if constraint is not None:
                tabs = constraint.device_tables(self.cfg.vocab_size)
                args += [
                    (tabs["next"], tabs["need"]),
                    jnp.full((tokens.shape[0],), constraint.init_state,
                             jnp.int32),
                ]
            out, gen_lens = fn(*args)
        out, gen_lens = jax.device_get(out), jax.device_get(gen_lens)
        return [list(map(int, out[i, : gen_lens[i]])) for i in range(b)]
