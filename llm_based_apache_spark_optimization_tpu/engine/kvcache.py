"""Preallocated KV cache in device memory (HBM on TPU).

Replaces llama.cpp's KV-cache management (the reference's context handling all
lives inside Ollama — SURVEY.md §5 "Long-context"). Layout:

    {"k": [L, B, K, S_max, H], "v": [L, B, K, S_max, H]}

- Leading L axis matches the scan-over-layers parameter stacking in
  models/llama.py, so one `lax.scan` carries cache slices alongside weights.
- KV heads sit *outside* the sequence axis: per (batch, head) the cache is a
  contiguous [S, H] tile — the shape the MXU wants for the attention
  contraction and the Pallas flash kernel's block grid wants for streaming
  (TPU blocks must tile the trailing (sublane, lane) = (S, H) dims; a
  [S, K, H] layout would put a singleton in the sublane dim per head,
  which the Mosaic lowering rejects).
- The whole generate call (prefill + decode loop) is one jitted XLA program:
  the cache is allocated inside it and carried through the `lax.while_loop`,
  so XLA keeps it in HBM and updates it in place across decode steps — no
  per-step realloc or host round-trip. (There is deliberately no cross-call
  buffer reuse yet; a persistent donated cache arrives with the continuous
  batching scheduler in serve/.)
- Invariant (relied on by ops/attention.py): every cache slot with index
  <= a live query position holds that sequence's real token K/V. Prefill
  writes slots [0, T); right-padding garbage beyond a sequence's length is
  overwritten by decode exactly when it would first become visible.

Sizing: bf16 cache for duckdb-nsql-7B at B=32, S=4096 is
2*32*32*4096*128*2B*32L ≈ 4.3 GiB — fits v5e-8 sharded over TP=4/8 on the KV
heads axis (parallel/sharding.py).
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

from ..models.configs import LlamaConfig


def init_cache(
    cfg: LlamaConfig, batch: int, max_seq: int, dtype=jnp.bfloat16
) -> Dict[str, jnp.ndarray]:
    # S rounds up to a sublane multiple so Pallas KV blocks tile cleanly; the
    # extra slots sit past every reachable position and stay causally masked.
    max_seq += -max_seq % 8
    shape = (cfg.num_layers, batch, cfg.num_kv_heads, max_seq, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_bytes(cfg: LlamaConfig, batch: int, max_seq: int, itemsize: int = 2) -> int:
    """Device bytes `init_cache` actually allocates — including the sublane
    rounding above (the two used to disagree, under-reporting HBM for any
    non-multiple-of-8 length; the paged pool sizing reuses this as the
    fixed-budget baseline)."""
    max_seq += -max_seq % 8
    return (
        2 * cfg.num_layers * batch * max_seq * cfg.num_kv_heads * cfg.head_dim * itemsize
    )


def bucket_len(n: int, bucket: int = 128) -> int:
    """Round a sequence length up to a bucket so jit recompiles are bounded."""
    return ((n + bucket - 1) // bucket) * bucket
