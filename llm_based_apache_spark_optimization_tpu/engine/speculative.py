"""Prompt-lookup speculative decoding: n-gram drafts, one-forward verify.

NL→SQL output is dominated by tokens COPIED from the prompt — column names,
the table name, literals from the question (the reference's whole workload:
schema + question in, SQL over that schema out, reference
`Flask/app.py:98-107`). Prompt-lookup decoding exploits that: draft the next
`draft_len` tokens by finding the most recent occurrence of the current
n-gram in (prompt + generated-so-far) and copying what followed it, then
verify all drafts with ONE forward pass of T = draft_len + 1. Greedy
verification is exact: the emitted tokens are identical to vanilla greedy
decode token-for-token (asserted in tests/test_speculative.py), whatever the
drafts were — bad drafts only cost speed, never correctness. No draft model,
no extra weights.

TPU-first shape of the idea:

- The whole loop stays ONE XLA program (`lax.while_loop`), like the vanilla
  engine: drafting is a handful of vectorized compares over the token
  history, and verification is a T=draft_len+1 cached forward — the same
  weight stream a T=1 step pays, so a round that accepts `a` drafts divides
  decode's HBM-bound cost by (a+1) at ~zero marginal FLOP cost (the MXU is
  >97% idle at T=1; T=9 is still tiny).
- Verify windows take the unrolled small-T decode path in models/llama.py
  (in-place cache sliver writes), not the prefill scan.
- Rejected drafts leave garbage K/V beyond the accepted point; the next
  round's verify window starts at the first unverified position, so its
  cache writes overwrite exactly that garbage before attention can see it —
  the same visibility invariant engine/kvcache.py documents.
- Sampled requests (temperature > 0) get the SAME draft/verify speedup via
  standard rejection sampling (`rejection_sample_chain`): each drafted
  token is accepted with min(1, p/q) under the target distribution — a
  delta q for these deterministic drafts, so accept iff a uniform draw
  lands under the draft's target mass — and the first rejection resamples
  from the normalized residual max(0, p − q). The emitted tokens are
  exactly a sample from vanilla `sample_runtime`'s distribution (the
  property tests' acceptance bar), while greedy requests keep the exact
  argmax verify (token-identical to vanilla greedy, as before).

Measured cost model (v5e, bench-1b, B=8, D=8): a verify round runs ~1.6x a
vanilla decode step (same weight stream; wider unembed + draft/accept
bookkeeping), so speculation breaks even around ~1.6 accepted tokens per
round and wins above it. Random-weight smoke models accept ~0-1.5 (nothing
real to copy), hence the engine default is OFF; enable it for real
checkpoints on copy-heavy workloads (NL→SQL over a schema is the
archetype — published prompt-lookup results and the reference's own
workload shape put acceptance at 3-6+).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..constrain.masks import fsm_advance_chain
from ..models.configs import LlamaConfig
from ..models.llama import _UNROLL_MAX_T, forward, split_blocks
from ..ops.pallas import attention_impl, decode_attention_impl
from ..ops.sampling import (
    SamplingParams,
    apply_token_mask,
    filtered_runtime_logits,
    sample,
)
from ..parallel.sharding import constrain_cache
from .kvcache import init_cache

# Cost of one T=D+1 verify round relative to a T=1 decode step: the single
# source for every est_speedup_vs_vanilla figure (scheduler
# speculation_stats, bench speculative block) — re-measure here, and both
# surfaces move together. ADVICE r5 #3: the old single 1.6 constant was
# measured at ONE draft length (D=8) and silently mispriced every other
# config, so the cost is now a LINEAR MODEL in draft length, fit at two
# anchor shapes:
#   D=0: ratio 1.0 by construction — a T=1 "verify" IS a vanilla decode
#        step (same forward, argmax instead of sample).
#   D=8: ratio 1.6 measured (v5e, bench-1b, B=8 — module docstring).
# Linearity is the right first-order model because the verify forward pays
# the same weight stream at any small T (the MXU is >97% idle at T=1) and
# the extra cost — wider unembed, draft/accept bookkeeping — scales with
# the window width. At other SHAPES (7B, int8/int4, TP meshes) the whole
# line can shift, so /metrics labels the estimate with its calibration
# instead of presenting it as universal.
VERIFY_COST_ANCHORS = ((0, 1.0), (8, 1.6))
VERIFY_COST_CALIBRATION = (
    "linear in draft length, anchored at D=0 (=1.0 by construction) and "
    "D=8 (=1.6 measured: v5e, bench-1b, B=8, bf16); other shapes scale "
    "the slope by (unembed marginal / weight-stream fixed) cost relative "
    "to that anchor"
)


def _param_count(cfg) -> int:
    """Approximate parameter count from the architecture shape — the
    decode step's fixed cost is streaming these bytes."""
    d, f, n_layers = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    nh, kh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    attn = d * nh * hd + 2 * d * kh * hd + nh * hd * d
    mlp = 3 * d * f
    emb = cfg.vocab_size * d * (1 if cfg.tie_embeddings else 2)
    return emb + n_layers * (attn + mlp + 2 * d) + d


def infer_weight_bits(params) -> int:
    """Weight bits/param of a params tree: 4 for int4-packed trees, 8 for
    int8 QTensor trees, else the leaf dtype width — the shape input
    `verify_cost_ratio` prices the fixed weight stream with."""
    import jax

    blocks = params.get("blocks", params)
    sample = blocks[0] if isinstance(blocks, (list, tuple)) else blocks
    if isinstance(sample, dict):
        if any(isinstance(v, dict) and "q4" in v for v in sample.values()):
            return 4
        if any(isinstance(v, dict) and "q8" in v for v in sample.values()):
            return 8
    return jax.tree.leaves(params)[0].dtype.itemsize * 8


def verify_cost_ratio(draft_len: int, cfg=None, weight_bits: int = 16,
                      tp: int = 1) -> float:
    """verify(T=draft_len+1) / decode(T=1) cost under the anchored linear
    model, scaled to the caller's MODEL SHAPE (ROADMAP carried-over item:
    the old signature priced every config at the 1B bench shape).

    The slope — marginal cost per extra window position — is dominated by
    the unembed (a V×D dot and a V-wide f32 logit row per position; the
    block matmuls are MXU-idle at small T), while the round's fixed cost
    is streaming the weight bytes. So the slope scales with
    (vocab·hidden) / weight_bytes relative to the anchor shape (bench-1b
    bf16), where `weight_bits` prices int8/int4 trees (fewer fixed bytes →
    a verify window is relatively MORE expensive → higher breakeven).
    `tp` cancels to first order — each device streams 1/tp of the weights
    AND computes 1/tp of the unembed — and is accepted so callers can
    record their topology; only the collective overhead it adds is
    unmodeled. Floors at 1.0: a verify round can never be cheaper than
    the vanilla step it replaces."""
    del tp  # cancels: fixed and marginal costs shard identically
    (d0, r0), (d1, r1) = VERIFY_COST_ANCHORS
    slope = (r1 - r0) / (d1 - d0)
    if cfg is not None:
        from ..models.configs import BENCH_1B

        def marg_over_fixed(c, bits):
            return (c.vocab_size * c.hidden_size) / (
                _param_count(c) * bits / 8
            )

        slope *= marg_over_fixed(cfg, weight_bits) / marg_over_fixed(
            BENCH_1B, 16
        )
    return max(1.0, r0 + slope * (draft_len - d0))


#: Backward-compatible single-number view: the D=8 anchor (the bench's
#: historical default draft). Prefer verify_cost_ratio(D) — this constant
#: only prices D=8 correctly.
VERIFY_COST_RATIO = verify_cost_ratio(8)


def ngram_draft(
    hist: jnp.ndarray,      # [B, HT] i32 token history (prompt + generated)
    hist_len: jnp.ndarray,  # [B] i32 — tokens valid in hist (incl. current)
    draft_len: int,
    ngram: int,
) -> jnp.ndarray:
    """Draft [B, draft_len] tokens by prompt lookup.

    For each row: take the trailing `ngram` tokens of the history (the
    current context suffix), find an earlier occurrence, and copy the
    `draft_len` tokens that followed it. Occurrence choice: the LATEST
    match whose whole draft window is already-written history (recency
    predicts best), else the EARLIEST match — a late match near the tail
    has almost no written continuation to copy (a pure-repetition loop
    would cap at ~period tokens per round), while the earliest match
    maximizes it. No occurrence -> returns whatever sits at the history
    tail (padding); those drafts simply fail verification. All comparisons
    are static-shape; per-row starts ride dynamic slices.
    """
    b, ht = hist.shape
    nw = ht - ngram + 1  # number of n-gram windows

    def row(h, hlen):
        suffix = lax.dynamic_slice(h, (hlen - ngram,), (ngram,))
        match = jnp.ones((nw,), jnp.bool_)
        for j in range(ngram):
            match = match & (lax.slice(h, (j,), (j + nw,)) == suffix[j])
        idx = jnp.arange(nw, dtype=jnp.int32)
        # Strictly before the suffix's own occurrence at hlen - ngram.
        valid = match & (idx < hlen - ngram)
        full = valid & (idx <= hlen - ngram - draft_len)
        found = jnp.any(valid)
        last_full = (nw - 1) - jnp.argmax(full[::-1]).astype(jnp.int32)
        first_any = jnp.argmax(valid).astype(jnp.int32)
        m = jnp.where(jnp.any(full), last_full, first_any)
        start = jnp.where(found, m + ngram, hlen)
        # dynamic_slice clamps start so the read stays in bounds; a clamped
        # window only shifts WHICH tokens get drafted — still just a draft.
        out = lax.dynamic_slice(h, (start,), (draft_len,))
        # Stale-memory guard: the copy window can cross hlen (an earliest
        # match's continuation, or the no-match fallback at the tail),
        # and beyond hlen sits whatever a PREVIOUS occupant of this
        # history row left there (scheduler slots are reused across
        # requests). Greedy verification never cared — drafts change
        # round counts, never output — but SAMPLED rejection
        # verification's realized tokens depend on the drafts (accept
        # iff u < p(draft)), so reading stale memory would break
        # (seed, request) reproducibility across batch compositions and
        # scheduler incarnations — the crash-replay suppression
        # contract. Pin past-hlen positions to token 0: any FIXED value
        # is a valid junk draft.
        pos = start + jnp.arange(draft_len, dtype=jnp.int32)
        return jnp.where(pos < hlen, out, 0)

    return jax.vmap(row)(hist, hist_len.astype(jnp.int32))


def rejection_sample_chain(
    filt: jnp.ndarray,    # [B, D+1, V] filtered target logits (see below)
    drafts: jnp.ndarray,  # [B, D] i32 deterministic prompt-lookup drafts
    keys: jax.Array,      # [B] typed PRNG keys, one per row per round
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Standard speculative rejection sampling (Leviathan et al.; Chen et
    al.) specialized to DETERMINISTIC drafts — the shared accept/resample
    core of both one-XLA-program speculative loops (this module's
    `lax.while_loop` and the scheduler's spec-decode program).

    `filt` must be `ops.sampling.filtered_runtime_logits` output over the
    verify window's logits, grammar-masked BEFORE filtering exactly where
    vanilla decode masks (per-position budget-aware state rows):
    `softmax(filt[:, j])` is then the EXACT distribution p_j(·) a vanilla
    sampled step would draw token j from.

    The general scheme accepts draft token x_i ~ q(·) with probability
    min(1, p(x_i)/q(x_i)) and resamples the first rejection from the
    normalized residual max(0, p - q). Prompt-lookup drafts are not
    model-sampled — q is a DELTA at the drafted token d (q(d) = 1) — so
    the scheme degenerates cleanly:

      accept:    min(1, p(d)/1) = p(d) — accept iff u < p(d), i.e. iff
                 the drafted token has enough TARGET mass. (p(d) = 0 for
                 a grammar-masked draft, so invalid drafts auto-reject.)
      residual:  max(0, p - δ_d) is p with d zeroed (p(d) <= 1 always),
                 renormalized — which is exactly `categorical` over filt
                 with d's logit dropped to NEG_INF. The residual stays
                 grammar-renormalized for free: masked tokens were
                 already at NEG_INF in filt.

    Unbiasedness at one position: P(emit t) = p(d)·1[t=d] +
    (1-p(d))·p(t)·1[t≠d]/(1-p(d)) = p(t). Chained over positions with
    the standard longest-accepted-prefix rule, plus the bonus draw from
    p_D itself when every draft accepts, the emitted tokens are exactly
    a sample from the target process — property-tested against vanilla
    `sample_runtime` output distributions in tests/test_speculative.py.
    (The p(d)=1 corner where the residual would be empty is unreachable:
    u ~ U[0,1) < 1 accepts with certainty there.)

    Returns (acc [B], extra [B]): `acc` is the accepted draft prefix
    length in [0, D], `extra` the token sampled at position `acc` — the
    residual draw when acc < D, the bonus target sample when acc == D.
    Callers emit drafts[:acc] + [extra], i.e. acc + 1 tokens (see
    `emit_chain`)."""
    from ..ops.common import NEG_INF

    b, d1, v = filt.shape
    d = d1 - 1
    p = jax.nn.softmax(filt, axis=-1)
    # Dead-row guard: a FULLY-masked position (possible only past the
    # budget horizon) must reject with certainty. NEG_INF is a finite
    # -1e30, so softmax over an all-masked row degenerates to UNIFORM
    # (exp(0)/V), not NaN — without this clamp a past-horizon draft
    # would accept with probability ~1/V and inflate acceptance
    # counters with tokens the loops discard anyway. Partially-masked
    # rows are unaffected: a masked token's mass underflows to exactly
    # 0 against any finite max, so grammar-rejected drafts still
    # auto-reject through p_draft == 0 alone.
    alive = (jnp.max(filt, axis=-1) > NEG_INF * 0.5)     # [B, D+1]
    p_draft = jnp.take_along_axis(
        p[:, :d], drafts[..., None], axis=-1
    )[..., 0] * alive[:, :d]                             # [B, D]
    ks = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
    u = jax.vmap(lambda k: jax.random.uniform(k, (d,)))(ks[:, 0])
    accept = (u < p_draft).astype(jnp.int32)
    acc = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)   # [B] in [0, D]
    final = jnp.take_along_axis(filt, acc[:, None, None], axis=1)[:, 0]
    rej = jnp.take_along_axis(
        jnp.concatenate([drafts, drafts[:, :1]], axis=1),  # pad col unused
        acc[:, None], axis=1,
    )[:, 0]
    final = jnp.where(
        (acc < d)[:, None] & (jnp.arange(v, dtype=jnp.int32)[None, :]
                              == rej[:, None]),
        NEG_INF, final,
    )
    extra = jax.vmap(jax.random.categorical)(ks[:, 1], final).astype(jnp.int32)
    return acc, extra


def emit_chain(drafts: jnp.ndarray, acc: jnp.ndarray, extra: jnp.ndarray,
               pad_id: int) -> jnp.ndarray:
    """Materialize `rejection_sample_chain`'s (acc, extra) contract as the
    emitted window [B, D+1]: the accepted draft prefix, then the
    residual/bonus token at position `acc`, pad beyond — the ONE place
    the emission indexing lives for both one-XLA-program loops."""
    b, d = drafts.shape
    jd = jnp.arange(d + 1, dtype=jnp.int32)[None, :]
    chain = jnp.concatenate(
        [drafts, jnp.full((b, 1), pad_id, jnp.int32)], axis=1
    )
    return jnp.where(
        jd < acc[:, None], chain,
        jnp.where(jd == acc[:, None], extra[:, None], pad_id),
    )


def make_speculative_generate_fn(
    cfg: LlamaConfig,
    max_new: int,
    stop_ids: Tuple[int, ...],
    mesh=None,
    draft_len: int = 8,
    ngram: int = 3,
    attn_impl: Optional[str] = None,
    constrained: bool = False,
    kv_layout: str = "contiguous",
    kv_page_size: Optional[int] = None,
    kv_quant: Optional[str] = None,
    sampling: Optional["SamplingParams"] = None,
):
    """Generate with prompt-lookup speculation (greedy or sampled).

    Same contract as `make_generate_fn` (bucketed cap, traced budget) plus a
    third output: `rounds` — the number of verify forwards the batch ran.
    rounds < total emitted tokens means speculation paid off; equality means
    every draft missed (the worst case, which still emits one token per
    round like vanilla decode, paying only the wider verify unembed).

    `sampling` (static, default greedy): greedy mode verifies by exact
    argmax — output token-identical to vanilla greedy decode. A
    temperature>0 `sampling` runs rejection-sampling verification
    (`rejection_sample_chain`): per round, each drafted token is accepted
    iff a uniform draw lands under its mass in the target distribution
    (temperature/top-k/top-p-filtered, grammar-masked when constrained),
    and the round's final token is drawn from the residual (first
    rejection) or the target itself (all accepted) — output
    DISTRIBUTION-identical to the vanilla sampled loop, not
    token-identical (the RNG consumption pattern differs). The traced
    `key` argument is required in sampled mode; round r derives per-row
    keys as fold_in(fold_in(key, r+1), row), so a (seed, request) pair is
    reproducible whatever the drafts accepted.

    `constrained=True` returns a fn taking two extra traced arguments —
    `(next, need)` grammar tables from constrain.CompiledMask.device_tables
    plus `init_states [B]` — and evaluates the grammar mask AT EVERY DRAFT
    POSITION: the draft chain advances the FSM per position
    (constrain.fsm_advance_chain) and truncates at the first
    grammar-rejected token (so acceptance doesn't crater on junk drafts),
    every verify-window logit row is masked with ITS position's
    budget-aware state row before argmax, and the committed FSM state is
    the one after the ACCEPTED prefix — rejected drafts never advance it
    (the same rewind-by-construction the rejected-K/V garbage relies on).
    Greedy parity is the contract: constrained+speculative output is
    token-identical to the constrained vanilla loop, drafts only change
    how many forwards it takes.
    """
    if not 1 <= draft_len <= _UNROLL_MAX_T - 1:
        raise ValueError(
            f"draft_len must be in [1, {_UNROLL_MAX_T - 1}] (the verify "
            f"window T = draft_len + 1 must take the unrolled small-T "
            f"decode path), got {draft_len}"
        )
    if ngram < 1:
        raise ValueError(f"ngram must be >= 1, got {ngram}")
    if kv_layout not in ("contiguous", "paged"):
        raise ValueError(
            f"kv_layout must be 'contiguous' or 'paged', got {kv_layout!r}"
        )
    page_size = 0
    decode = attn_impl or decode_attention_impl(mesh)
    if kv_quant not in (None, "int8"):
        raise ValueError(f"kv_quant must be None or 'int8', got {kv_quant!r}")
    if kv_quant and kv_layout != "paged":
        raise ValueError(
            "kv_quant='int8' speculation needs kv_layout='paged': the "
            "contiguous verify loop streams the bf16 cache, the paged "
            "pool's verify windows run the int8-streaming reference gather"
        )
    if kv_layout == "paged":
        from .paged_kv import default_page_size

        page_size = int(kv_page_size or default_page_size())
        # The verify window is T=D+1: since the ragged-paged kernel takes
        # per-row query lengths, a resolved-pallas mode runs verify windows
        # through the kernel grid; the auto resolution still lands on the
        # reference gather path off-TPU. A mesh shards the pool's KV-head
        # axis over tp (constrain_cache's paged branch); page tables
        # replicate.
    return _make_speculative_generate_fn(
        cfg, max_new, stop_ids, mesh, draft_len, ngram,
        attn_impl or attention_impl(mesh),
        decode,
        constrained,
        kv_layout,
        page_size,
        kv_quant,
        sampling or SamplingParams(),
    )


@functools.lru_cache(maxsize=64)
def _make_speculative_generate_fn(
    cfg: LlamaConfig,
    max_new: int,
    stop_ids: Tuple[int, ...],
    mesh,
    draft_len: int,
    ngram: int,
    prefill_impl: str,
    decode_impl: str,
    constrained: bool = False,
    kv_layout: str = "contiguous",
    page_size: int = 0,
    kv_quant: Optional[str] = None,
    sampling: SamplingParams = SamplingParams(),
):
    from .generate import _is_stop as _is_stop_ids

    pad_id = cfg.pad_id
    d1 = draft_len + 1
    sp = dict(mesh.shape).get("sp", 1) if mesh is not None else 1
    pre_impl = "ring" if sp > 1 else prefill_impl
    if sp > 1 and decode_impl == "pallas":
        # Same hazard as generate.py's guard: the flash kernel's shard_map
        # expects S-replicated K/V, and against the sp-sharded cache
        # (parallel/sharding.cache_spec) every verify round would
        # all-gather the whole cache.
        raise ValueError(
            "attn_impl='pallas' verify/decode cannot run on an sp>1 mesh: "
            "the sequence-sharded cache would be all-gathered every round; "
            "use the auto/einsum impl"
        )

    def _is_stop(tok):
        return _is_stop_ids(tok, stop_ids)

    sampled = not sampling.is_greedy

    def gen(params, tokens, lengths, budget, key=None,
            grammar=None,       # (next [S,V] i32, need [S,V] i32) tables
            init_states=None):  # [B] int32 DFA start states
        b, t = tokens.shape
        budget = jnp.minimum(budget, max_new)
        lengths = lengths.astype(jnp.int32)
        paged = kv_layout == "paged"
        # Cache spans prompt + completion + one verify window of overshoot
        # (paged mode prefills a prompt-sized transient cache and packs it
        # into pool pages covering the same span — verify windows write
        # through the page table, spanning page boundaries freely).
        cache = init_cache(cfg, b, t if paged else t + max_new + d1,
                           dtype=params["final_norm"].dtype)
        if mesh is not None:
            cache = constrain_cache(cache, mesh)
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None, :], (b, t))
        logits, cache = forward(
            cfg, params, tokens, positions, cache,
            logit_indices=lengths - 1, attn_impl=pre_impl, mesh=mesh,
        )
        first_logits = logits[:, 0]
        if constrained:
            g_next, g_need = grammar
            # First token constrained exactly like the vanilla loop: a
            # token is allowed iff itself + shortest completion + stop id
            # fit the whole budget (masks.py need table).
            first_logits = apply_token_mask(
                first_logits, g_need[init_states] <= budget
            )
        if sampled:
            # Vanilla-identical first draw: the same grammar-masked logits,
            # the same static sampler, fold index 0 of the batch key.
            first = sample(first_logits, sampling, jax.random.fold_in(key, 0))
        else:
            first = jnp.argmax(first_logits, axis=-1).astype(jnp.int32)
        cstate = g_next[init_states, first] if constrained else None
        if paged:
            from .paged_kv import pack_prefill_pages

            ppr = -(-(t + max_new + d1) // page_size)
            cache = pack_prefill_pages(cache, page_size, ppr,
                                       kv_quant=kv_quant)
            if mesh is not None:
                cache = constrain_cache(cache, mesh)

        # History = prompt tokens + generated, contiguous per row (generated
        # tokens land at hlen, after the row's REAL prompt; the pad gap up
        # to the bucket boundary never sits inside an n-gram window that
        # can win: drafts sourced from it fail verification).
        ht = t + max_new + d1
        hist = jnp.concatenate(
            [tokens, jnp.full((b, max_new + d1), pad_id, jnp.int32)], axis=1
        )
        hist = jax.vmap(
            lambda h, f, s: lax.dynamic_update_slice(h, f[None], (s,))
        )(hist, first, lengths)

        out = jnp.full((b, max_new + d1), pad_id, jnp.int32)
        out = out.at[:, 0].set(first)
        done = _is_stop(first) | (budget <= 1)
        glen = jnp.ones((b,), jnp.int32)
        hlen = lengths + 1
        dec_params = params if decode_impl == "ring" else split_blocks(params)
        jd = jnp.arange(d1, dtype=jnp.int32)[None, :]

        def cond(carry):
            return ~jnp.all(carry[4])

        def body(carry):
            hist, hlen, out, glen, done, cache, cur, pos, rounds = carry[:9]
            drafts = ngram_draft(hist, hlen, draft_len, ngram)  # [B, D]
            verify = jnp.concatenate([cur[:, None], drafts], axis=1)  # [B, D+1]
            vpos = pos[:, None] + jd
            logits, cache = forward(
                cfg, dec_params, verify, vpos, cache,
                attn_impl=decode_impl, mesh=mesh,
            )
            if constrained:
                # The draft chain advances the FSM per position; drafts
                # stop counting at the first grammar-rejected token
                # (vlen), and EVERY verify position's logits are masked
                # with its own state's budget-aware row — the masked
                # argmax at position j is exactly the token vanilla
                # constrained decode would emit there, which is what makes
                # greedy parity hold whatever the drafts were.
                cstate = carry[9]
                rem0 = budget - glen                         # [B]
                pstates, vlen = fsm_advance_chain(
                    g_next, g_need, cstate, drafts, rem0
                )                                            # [B,D+1], [B]
                pos_rem = rem0[:, None] - jd                 # [B, D+1]
                logits = apply_token_mask(
                    logits, g_need[pstates] <= pos_rem[:, :, None]
                )
            if sampled:
                # Rejection-sampling verification: the filtered target
                # logits at every window position (softmax = the EXACT
                # per-position distribution vanilla sample_runtime draws
                # from — grammar-masked above, so grammar-rejected drafts
                # carry zero target mass and auto-reject, capping
                # acceptance at the valid prefix without a separate
                # clamp). Per-row keys derive from (key, round, row), so
                # the whole run is reproducible per (seed, batch).
                filt = filtered_runtime_logits(
                    logits, jnp.float32(sampling.temperature),
                    jnp.float32(sampling.top_p), jnp.int32(sampling.top_k),
                )
                round_key = jax.random.fold_in(key, rounds + 1)
                rkeys = jax.vmap(
                    lambda i: jax.random.fold_in(round_key, i)
                )(jnp.arange(b, dtype=jnp.int32))
                acc, extra = rejection_sample_chain(filt, drafts, rkeys)
                preds = emit_chain(drafts, acc, extra, pad_id)
            else:
                preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, D+1]
                # preds[j] is the TRUE greedy token after verify[j] iff all
                # drafts before j were accepted; accept the longest such
                # chain.
                eq = (drafts == preds[:, :draft_len]).astype(jnp.int32)
                if constrained:
                    # A grammar-rejected draft can never be accepted even
                    # if the (masked-out) model would have agreed:
                    # acceptance is capped at the valid prefix, so the
                    # committed chain only ever walks live FSM
                    # transitions.
                    eq = eq * (jd[:, :draft_len]
                               < vlen[:, None]).astype(jnp.int32)
                acc = jnp.sum(jnp.cumprod(eq, axis=1), axis=1)  # [B] in [0, D]
            emit_mask = jd <= acc[:, None]
            stops = _is_stop(preds)
            # Keep through the FIRST stop, nothing after it.
            stops_before = jnp.cumsum(stops.astype(jnp.int32), axis=1) - stops
            emit_mask = emit_mask & (stops_before == 0)
            emit_mask = emit_mask & (jd < (budget - glen)[:, None])
            emit_mask = emit_mask & ~done[:, None]
            n_emit = jnp.sum(emit_mask, axis=1).astype(jnp.int32)
            emitted = jnp.where(emit_mask, preds, pad_id)

            out = jax.vmap(
                lambda o, e, s: lax.dynamic_update_slice(o, e, (s,))
            )(out, emitted, glen)
            hist = jax.vmap(
                lambda h, e, s: lax.dynamic_update_slice(h, e, (s,))
            )(hist, emitted, hlen)

            cur = jax.vmap(
                lambda e, n, c: jnp.where(n > 0, e[jnp.maximum(n - 1, 0)], c)
            )(emitted, n_emit, cur)
            tail = ()
            if constrained:
                # Commit the state AFTER the accepted prefix: the last
                # emitted token advances from ITS per-position state
                # (pstates[n_emit-1] — for accepted drafts that is the
                # chain state, and emitted[j] == drafts[j] there).
                # Rejected drafts never touch the committed state, the
                # FSM twin of the rejected-K/V rewind. n_emit == 0 rows
                # (done / budget-exhausted) freeze.
                idx = jnp.maximum(n_emit - 1, 0)
                last_s = jnp.take_along_axis(pstates, idx[:, None], 1)[:, 0]
                last_t = jnp.take_along_axis(emitted, idx[:, None], 1)[:, 0]
                tail = (jnp.where(n_emit > 0, g_next[last_s, last_t],
                                  cstate),)
            glen = glen + n_emit
            hlen = hlen + n_emit
            pos = pos + n_emit
            done = done | jnp.any(stops & emit_mask, axis=1) | (glen >= budget)
            return (hist, hlen, out, glen, done, cache, cur, pos,
                    rounds + 1) + tail

        carry = (hist, hlen, out, glen, done, cache, first, lengths,
                 jnp.int32(0))
        if constrained:
            carry = carry + (cstate,)
        final = lax.while_loop(cond, body, carry)
        out, rounds = final[2], final[8]

        out = out[:, :max_new]
        stops = _is_stop(out)
        gen_lens = jnp.where(
            jnp.any(stops, axis=1),
            jnp.argmax(stops, axis=1).astype(jnp.int32) + 1,
            budget.astype(jnp.int32),
        )
        return out, gen_lens, rounds

    return jax.jit(gen)
