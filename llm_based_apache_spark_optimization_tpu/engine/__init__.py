"""Generation runtime: KV cache, prefill/decode loop, engine wrapper."""

from .generate import InferenceEngine, make_generate_fn  # noqa: F401
from .kvcache import bucket_len, cache_bytes, init_cache  # noqa: F401
from .speculative import (  # noqa: F401
    make_speculative_generate_fn,
    ngram_draft,
)
