"""Generation runtime: KV cache, prefill/decode loop, engine wrapper."""

from .generate import InferenceEngine, make_generate_fn  # noqa: F401
from .kvcache import bucket_len, cache_bytes, init_cache  # noqa: F401
