"""Paged KV cache: a shared device page pool + host-side page allocator.

The contiguous layout (engine/kvcache.py) allocates every slot its
worst-case window — `[L, slots, K, S_max, H]` — so concurrent slot count is
bounded by `slots × S_max × layer bytes` no matter how many tokens are
actually live, and the scheduler's prefix cache pays a gather-copy per hit.
The paged layout breaks both bounds (the Ragged Paged Attention / vLLM
PagedAttention design, PAPERS.md):

    pool:        {"kp": [L, P, K, page_size, H], "vp": [L, P, K, page_size, H]}
    page table:  [slots, pages_per_slot] int32 — per-slot logical->pool map

- The pool is sized to an HBM budget (`pages_for_budget`), not to
  slots × S_max: a request holds ceil(need / page_size) pages for
  `need = bucketed prompt + max_new + overshoot` — mixed long/short traffic
  stops paying max-bucket padding, and concurrent requests scale with live
  tokens.
- `PageAllocator` is pure host bookkeeping (free list + per-page refcounts):
  page table updates are a few int32 scatters per admission, never a device
  sync. Refcounts make prefix-cache hits ZERO-COPY — a hit maps the cached
  prefix's pages into the new slot's table (refcount++) instead of
  gather-copying K/V.
- Copy-on-write: a shared page is never written in place. The only writer
  of a shared page is a slot whose write range starts INSIDE one — a
  non-page-aligned prefix boundary — and it first copies that one page
  (`PageAllocator.cow` + a one-page device copy) and remaps. Everything
  page-aligned stays zero-copy.
- The unmapped sentinel is `num_pages` (one past the pool): jax drops
  out-of-bounds scatter writes, so unmapped table entries make parked /
  padding rows' K/V writes true no-ops, and gathers clip the sentinel to a
  real page whose garbage the causal mask hides (the same
  visibility-by-causality invariant engine/kvcache.py documents).

Page size rides `LSOT_KV_PAGE_SIZE` (default 64): a multiple of 8 keeps
pool pages sublane-aligned for the Pallas ragged-paged-attention kernel
(ops/pallas/paged_attention.py), whose block grid DMAs one [K, page, H]
page per cell through the scalar-prefetched page table.

`kv_quant="int8"` (ISSUE 11) stores the pool as int8 values plus one f32
scale per (layer, page, kv-head, position) — "kps"/"vps" arrays
[L, P, K, page] beside "kp"/"vp" — so the same HBM budget holds ~2x the
live tokens. Quantization happens on the way IN (pack_prefill_pages, the
prefill windowed scatter, the fused page-write kernel) and dequantization
on the way OUT (inside the ragged read kernel's DMA'd tiles, or the
int8-streaming einsum reference); `page_bytes`/`pages_for_budget` price
the KV dtype so every capacity surface reports true bytes.

`export_pages`/`import_pages` (ISSUE 13) make KV page migration a
first-class op: a request's live pages (values + int8 scales — the full
cache tuple, generalized from the LSOT_KV_SPILL host-copy path) extract
into a portable host blob and install into ANOTHER pool's freshly
allocated pages — the page-table + page-transfer handoff that
disaggregated prefill/decode serving rides (serve/scheduler.py
`phase_role`).
"""

from __future__ import annotations

import os
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import LlamaConfig


class PageAccountingError(RuntimeError):
    """A refcount went negative or a freed page was freed again — the
    allocator's invariants are broken and the pool can no longer be
    trusted (this is a bug, not an operational condition)."""


def default_page_size() -> int:
    """LSOT_KV_PAGE_SIZE (default 64). Must be a positive multiple of 8 so
    pool pages stay sublane-aligned for the TPU kernel's block grid."""
    try:
        ps = int(os.environ.get("LSOT_KV_PAGE_SIZE", "64"))
    except ValueError:
        ps = 64
    if ps <= 0 or ps % 8:
        raise ValueError(
            f"LSOT_KV_PAGE_SIZE must be a positive multiple of 8, got {ps}"
        )
    return ps


def page_bytes(
    cfg: LlamaConfig, page_size: int, itemsize: int = 2,
    kv_quant: Optional[str] = None,
) -> int:
    """Device bytes of ONE pool page across all layers (K and V).

    `kv_quant="int8"` prices the QUANTIZED pool layout: int8 values plus
    one f32 scale per (layer, page, kv-head, position) — the KV dtype, not
    the compute dtype (`itemsize` is ignored there). Every capacity
    surface (pages_for_budget, the scheduler's HBM-budget sizing,
    /metrics serving.kv_pages, the bench accounting) must go through this
    so an int8 pool reports ~2x the true tokens per HBM byte instead of
    compute-dtype fiction."""
    per_pos = cfg.head_dim * itemsize
    if kv_quant == "int8":
        # int8 value bytes + one f32 scale per position (absmax over H).
        per_pos = cfg.head_dim * 1 + 4
    elif kv_quant is not None:
        raise ValueError(f"kv_quant must be None or 'int8', got {kv_quant!r}")
    return 2 * cfg.num_layers * cfg.num_kv_heads * page_size * per_pos


def pages_for_budget(
    cfg: LlamaConfig, budget_bytes: int, page_size: int, itemsize: int = 2,
    kv_quant: Optional[str] = None,
) -> int:
    """Pool pages an HBM budget buys (the paged twin of
    engine/kvcache.cache_bytes — same cfg, same itemsize convention;
    `kv_quant` prices the int8 page layout, so the same budget buys ~2x
    the pages)."""
    return max(
        0, int(budget_bytes) // page_bytes(cfg, page_size, itemsize, kv_quant)
    )


def pages_for_tokens(n_tokens: int, page_size: int) -> int:
    """Pages covering n_tokens positions (ceil)."""
    return -(-int(n_tokens) // int(page_size))


def init_page_pool(
    cfg: LlamaConfig, num_pages: int, page_size: int, dtype=jnp.bfloat16,
    kv_quant: Optional[str] = None,
) -> Dict[str, jnp.ndarray]:
    """Allocate the shared device page pool. Layout mirrors the contiguous
    cache with the (batch, S) axes replaced by one page axis: per
    (page, kv-head) the pool is a contiguous [page_size, H] tile — the
    MXU/Pallas-friendly trailing (sublane, lane) shape.

    `kv_quant="int8"` stores int8 values plus f32 per-position scales
    ("kps"/"vps", [L, P, K, page_size] — the paged twin of the contiguous
    {"k8","ks","v8","vs"} layout, ops/quant.quantize_kv): the pool holds
    ~2x the live tokens per HBM byte. Scales init to 1.0 so an unwritten
    page dequantizes to harmless zeros, never NaN."""
    if page_size <= 0 or page_size % 8:
        raise ValueError(
            f"page_size must be a positive multiple of 8, got {page_size}"
        )
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size,
             cfg.head_dim)
    if kv_quant == "int8":
        sshape = shape[:-1]
        return {
            "kp": jnp.zeros(shape, jnp.int8),
            "kps": jnp.ones(sshape, jnp.float32),
            "vp": jnp.zeros(shape, jnp.int8),
            "vps": jnp.ones(sshape, jnp.float32),
        }
    if kv_quant is not None:
        raise ValueError(f"kv_quant must be None or 'int8', got {kv_quant!r}")
    return {"kp": jnp.zeros(shape, dtype), "vp": jnp.zeros(shape, dtype)}


def pack_prefill_pages(
    cache: Dict[str, jnp.ndarray], page_size: int, pages_per_row: int,
    kv_quant: Optional[str] = None,
) -> Dict[str, jnp.ndarray]:
    """Contiguous prefill cache {"k","v"} [L, B, K, S, H] -> paged cache
    {"kp","vp","ptab"} with identity per-row tables (row b owns pool pages
    [b*ppr, (b+1)*ppr)).

    The engines' one-XLA-program loops use this as the prefill→decode
    handoff: prefill runs the proven contiguous scan path over a
    prompt-sized transient cache, one transpose-scatter packs its K/V into
    pool pages, and the decode `lax.while_loop` carries the pool + tables
    (models/llama.forward's paged branch). Pure jnp — runs inside jit.

    `kv_quant="int8"` QUANTIZES inside the pack (ops/quant.quantize_kv:
    int8 values + one f32 scale per position, absmax over H) and returns
    the int8 pool layout {"kp","kps","vp","vps","ptab"} — the
    prefill-fills-bf16-then-quantize-once handoff the contiguous int8
    path uses, applied per page. Unwritten pool scale entries stay 1.0 so
    unmapped-page garbage dequantizes finite."""
    k = cache["k"]
    n_layers, b, kh, s, h = k.shape
    ppr = int(pages_per_row)
    num_pages = b * ppr
    s_pad = s + (-s % page_size)
    np0 = s_pad // page_size
    if np0 > ppr:
        raise ValueError(
            f"prefill cache ({s} positions = {np0} pages) exceeds "
            f"pages_per_row={ppr}"
        )
    ptab = (
        jnp.arange(b, dtype=jnp.int32)[:, None] * ppr
        + jnp.arange(ppr, dtype=jnp.int32)[None, :]
    )

    def pack(arr, fill=0.0):
        # Values [L, B, K, S, H] and per-position scales [L, B, K, S] both
        # land here: the scale path just drops the trailing H axis.
        has_h = arr.ndim == 5
        pad = ((0, 0), (0, 0), (0, 0), (0, s_pad - s)) + (
            ((0, 0),) if has_h else ()
        )
        a = jnp.pad(arr, pad, constant_values=fill)
        shape = (n_layers, b, kh, np0, page_size) + ((h,) if has_h else ())
        a = a.reshape(shape)
        perm = (0, 1, 3, 2, 4, 5) if has_h else (0, 1, 3, 2, 4)
        a = a.transpose(perm)  # [L, B, np0, K, PS(, H)]
        pool = jnp.full(
            (n_layers, num_pages, kh, page_size) + ((h,) if has_h else ()),
            fill, arr.dtype,
        )
        return pool.at[:, ptab[:, :np0]].set(a)

    if kv_quant == "int8":
        from ..ops.quant import quantize_kv

        kq, vq = quantize_kv(cache["k"]), quantize_kv(cache["v"])
        return {
            "kp": pack(kq["q8"]), "kps": pack(kq["s"], fill=1.0),
            "vp": pack(vq["q8"]), "vps": pack(vq["s"], fill=1.0),
            "ptab": ptab,
        }
    if kv_quant is not None:
        raise ValueError(f"kv_quant must be None or 'int8', got {kv_quant!r}")
    return {"kp": pack(cache["k"]), "vp": pack(cache["v"]), "ptab": ptab}


def export_pages(
    cache: Sequence[jnp.ndarray], page_ids: Sequence[int],
) -> Tuple[np.ndarray, ...]:
    """Extract live pool pages into a PORTABLE host-side handoff blob:
    one `[L, n, K, page_size(, H)]` numpy array per cache array, in the
    pool tuple's own order — `(kp, vp)` for a compute-dtype pool,
    `(kp, kps, vp, vps)` for the int8 pool, so the quantization scales
    always serialize beside their values and a restore reproduces the
    page content `(q8, s)` exactly. This is the LSOT_KV_SPILL host-copy
    format promoted to a first-class op: the same blob serves victim
    spill-resume on one replica AND prefill→decode page migration across
    replicas (disaggregated serving — ISSUE 13). The arrays are COPIES
    (one `device_get`): a page the source shared copy-on-write with its
    prefix cache exports as content, never as a reference, so the blob
    stays valid after the source releases, evicts or overwrites every
    page it covered."""
    idx = np.asarray(list(page_ids), np.int32)
    return jax.device_get(tuple(c[:, idx] for c in cache))


def import_pages(
    cache: Sequence[jnp.ndarray], page_ids, stacks: Sequence,
) -> Tuple[jnp.ndarray, ...]:
    """Install an `export_pages` blob into (freshly allocated, exclusive)
    pool pages: one scatter per cache array, pure jnp — callers jit it
    (the scheduler's `restore_pages` op wraps exactly this with buffer
    donation). The receiving side owns the allocation policy: the
    scheduler grants the blob's pages all-or-nothing through the same
    `_page_wait`/overcommit admission every fresh request rides, so
    migration changes no pressure semantics."""
    idx = jnp.asarray(page_ids, jnp.int32)
    return tuple(
        c.at[:, idx].set(jnp.asarray(s)) for c, s in zip(cache, stacks)
    )


def handoff_bytes(stacks: Sequence[np.ndarray]) -> int:
    """Host bytes of one export_pages blob (the handoff observability
    figure: what actually crossed — or would cross — the wire)."""
    return int(sum(int(np.asarray(s).nbytes) for s in stacks))


def blob_meta(stacks: Sequence[np.ndarray]) -> dict:
    """Self-description of one `export_pages` blob — the pushed-handoff
    observability/validation record (ISSUE 17): page count + page_size
    read from the blob's own geometry, whether it carries int8 scales
    (4 arrays) or compute-dtype pages (2), and the wire bytes. The
    receiving side of a push compares `page_size` against its own pool
    BEFORE importing — a geometry mismatch is a typed rejection, not a
    scatter into the wrong page stride."""
    arrs = [np.asarray(s) for s in stacks]
    if not arrs:
        return {"arrays": 0, "pages": 0, "page_size": 0, "nbytes": 0,
                "quantized": False}
    # export_pages layout: [L, n_pages, K, page_size(, H)] per array;
    # the kps/vps scale arrays of an int8 blob share the page axes.
    lead = arrs[0]
    return {
        "arrays": len(arrs),
        "pages": int(lead.shape[1]) if lead.ndim >= 2 else 0,
        "page_size": int(lead.shape[3]) if lead.ndim >= 4 else 0,
        "nbytes": int(sum(a.nbytes for a in arrs)),
        "quantized": len(arrs) == 4,
    }


class PageAllocator:
    """Host-side page accounting: free list + per-page refcounts.

    All methods are O(pages touched); nothing here talks to the device.
    Thread-unsafe by design — the scheduler's worker thread is the only
    caller (same single-writer discipline as every other slot structure).

    Invariants (property-tested in tests/test_paged_kv.py):
    - every page is either on the free list (refcount 0) or live
      (refcount >= 1) — never both, never neither;
    - `release` on a refcount-0 page raises (double free is a bug);
    - a shared page (refcount > 1) is never handed out by `alloc`.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages <= 0:
            raise ValueError(f"num_pages must be positive, got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free: "deque[int]" = deque(range(self.num_pages))
        self._ref = [0] * self.num_pages
        #: zero-copy shares taken (prefix publish + hit mappings): the
        #: counter that proves hits SHARED pages instead of copying them.
        self.shares = 0
        #: copy-on-write page copies (non-page-aligned boundaries only).
        self.cow_copies = 0
        #: Pages WITHHELD from allocation (still on the free list, still
        #: refcount 0 — the partition invariant is untouched): the
        #: `kv:pressure` chaos seam shrinks the effective pool through
        #: this, so allocation failure under pressure is injectable
        #: without faking device state. 0 outside pressure episodes.
        self.withheld = 0
        #: Pressure-relief lifecycle counters (ISSUE 10): victims
        #: preempted mid-decode, prefix-cache entries evicted by the
        #: watermark sweep, and pages spilled to / restored from host
        #: copies under LSOT_KV_SPILL.
        self.preemptions = 0
        self.evictions = 0
        self.spilled_pages = 0
        self.restored_pages = 0
        #: Per-page resident-PREFIX reference counts (ISSUE 14): how many
        #: live prefix-cache entries currently map each page. Chained
        #: entries overlap on their leading pages, so the scheduler's
        #: "bytes held by the prefix cache" figure needs the UNIQUE page
        #: set, not a per-entry sum — `prefix_resident_pages` counts pages
        #: with at least one entry reference, in O(1) via the nonzero
        #: tally. Distinct from `_ref` on purpose: a page can be prefix-
        #: resident and slot-mapped at once, and eviction accounting must
        #: not disturb the free-list/refcount partition invariant.
        self._prefix_ref = [0] * self.num_pages
        self._prefix_resident = 0

    # ------------------------------------------------------------- queries

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_available(self) -> int:
        """Free pages actually grantable right now: the free list minus
        the pressure-withheld reserve. What `alloc`/`can_alloc` consult."""
        return max(0, len(self._free) - self.withheld)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def pages_shared(self) -> int:
        """Pages currently mapped by more than one owner."""
        return sum(1 for r in self._ref if r > 1)

    def refcount(self, page: int) -> int:
        return self._ref[page]

    def is_shared(self, page: int) -> bool:
        return self._ref[page] > 1

    def can_alloc(self, n: int) -> bool:
        return self.pages_available >= n

    # ----------------------------------------------------------- mutations

    def withhold(self, n: int) -> None:
        """Reserve `n` free-list pages against allocation (the
        `kv:pressure` fault seam: the pool LOOKS n pages smaller until
        the pressure episode ends). Withheld pages never leave the free
        list, so the free-list/refcount partition — and `check()` — hold
        throughout; only `pages_available` shrinks. `withhold(0)` lifts
        the pressure."""
        if n < 0:
            raise ValueError(f"withhold({n})")
        self.withheld = min(int(n), self.num_pages)

    def note_preempt(self) -> None:
        """Count a mid-decode victim preemption (the scheduler released
        the victim's pages through `release` — this is the event tally
        /metrics and the bench pressure pass read)."""
        self.preemptions += 1

    def note_evictions(self, n: int) -> None:
        """Count prefix-cache entries evicted by the WATERMARK sweep
        (proactive pressure relief, distinct from `_alloc_pages`'s
        on-demand eviction which the scheduler does not tally — the
        watermark's whole point is firing before demand does)."""
        self.evictions += int(n)

    def note_spill(self, n: int) -> None:
        """Count pages copied to host at preemption (LSOT_KV_SPILL=1)."""
        self.spilled_pages += int(n)

    def note_restore(self, n: int) -> None:
        """Count spilled pages copied back at resume. A completed
        spill-resume cycle leaves spilled == restored for that request —
        the reconciliation the property tests pin."""
        self.restored_pages += int(n)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh exclusive pages, or None (all-or-nothing: a request that
        cannot fully fit must not hold a partial grab and deadlock against
        another partial holder). Withheld pages (kv:pressure) are not
        grantable."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if self.pages_available < n:
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            if self._ref[p] != 0:
                raise PageAccountingError(
                    f"free-list page {p} has refcount {self._ref[p]}"
                )
            self._ref[p] = 1
        return pages

    def share(self, pages: List[int], count: bool = True) -> None:
        """Take one additional reference on each page (zero-copy mapping:
        prefix-cache publish and hit both land here). `count=False` for
        TRANSIENT holds (e.g. pinning a matched entry across an allocation
        that may fail, or a boundary page held only until its COW copy):
        `shares` must count mappings that persist — it is the artifact's
        "sharing, not copying" proof and must not inflate under retries."""
        for p in pages:
            if self._ref[p] <= 0:
                raise PageAccountingError(
                    f"share of dead page {p} (refcount {self._ref[p]})"
                )
        for p in pages:
            self._ref[p] += 1
        if count:
            self.shares += len(pages)

    def note_shares(self, n: int) -> None:
        """Promote n transient holds (share(count=False)) to counted
        zero-copy mappings once they are known to persist."""
        self.shares += n

    def prefix_hold(self, pages: List[int]) -> None:
        """Mark pages as mapped by one more resident prefix-cache entry
        (publish). Idempotent per entry, not per page — chained entries
        legitimately hold the same leading pages more than once."""
        for p in pages:
            if self._prefix_ref[p] == 0:
                self._prefix_resident += 1
            self._prefix_ref[p] += 1

    def prefix_drop(self, pages: List[int]) -> None:
        """Drop one prefix-entry reference per page (entry eviction).
        A negative count is an accounting bug, not a recoverable state."""
        for p in pages:
            if self._prefix_ref[p] <= 0:
                raise PageAccountingError(
                    f"prefix_drop of page {p} with no prefix reference"
                )
            self._prefix_ref[p] -= 1
            if self._prefix_ref[p] == 0:
                self._prefix_resident -= 1

    @property
    def prefix_resident_pages(self) -> int:
        """UNIQUE pages currently held by at least one prefix-cache
        entry — the registry's resident-bytes numerator (× page_bytes)."""
        return self._prefix_resident

    def release(self, pages: List[int]) -> List[int]:
        """Drop one reference per page; pages reaching refcount 0 return to
        the free list. Returns the freed subset."""
        for p in pages:
            if self._ref[p] <= 0:
                raise PageAccountingError(
                    f"release of dead page {p} (refcount {self._ref[p]})"
                )
        freed = []
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)
                freed.append(p)
        return freed

    def cow(self, page: int) -> Optional[int]:
        """Copy-on-write: exchange one reference on a SHARED page for a
        fresh exclusive page (the caller must device-copy the old page's
        content into the returned one before writing). Returns `page`
        unchanged when it is already exclusive (no copy needed), None when
        the pool has no free page for the copy."""
        if self._ref[page] <= 0:
            raise PageAccountingError(
                f"cow of dead page {page} (refcount {self._ref[page]})"
            )
        if self._ref[page] == 1:
            return page
        fresh = self.alloc(1)
        if fresh is None:
            return None
        self.release([page])
        self.cow_copies += 1
        return fresh[0]

    def note_cow(self) -> None:
        """Count a boundary-page copy performed OUTSIDE the refcount
        exchange (admission copies a hit's partial boundary page into an
        already-allocated fresh page — same event, different bookkeeping
        path)."""
        self.cow_copies += 1

    def stats(self) -> Dict[str, int]:
        """The /metrics + flight-recorder payload: a leaked page shows up
        as pages_in_use that never returns to pages_free."""
        return {
            "page_size": self.page_size,
            "pages_total": self.num_pages,
            "pages_free": self.pages_free,
            "pages_in_use": self.pages_in_use,
            "pages_shared": self.pages_shared,
            "pages_withheld": self.withheld,
            "prefix_resident_pages": self.prefix_resident_pages,
            "zero_copy_shares": self.shares,
            "cow_copies": self.cow_copies,
            "preemptions": self.preemptions,
            "evictions": self.evictions,
            "spilled_pages": self.spilled_pages,
            "restored_pages": self.restored_pages,
        }

    def check(self) -> None:
        """Assert the free-list/refcount partition (test helper)."""
        free = set(self._free)
        if len(free) != len(self._free):
            raise PageAccountingError("duplicate page on the free list")
        for p in range(self.num_pages):
            if (p in free) != (self._ref[p] == 0):
                raise PageAccountingError(
                    f"page {p}: refcount {self._ref[p]} vs free-list "
                    f"membership {p in free}"
                )
