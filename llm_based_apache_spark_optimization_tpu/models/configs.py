"""Model configurations for the Llama-family architectures the framework serves.

The reference app names three Ollama-hosted models (reference
`Flask/app.py:102-107,160-166`, `Model_Evaluation_&_Comparision.py:69,83`):
`duckdb-nsql` (a Llama-2-7B fine-tune for text-to-SQL), `llama3.2` (1B/3B,
GQA + tied embeddings + llama3 rope scaling) and `mistral` (7B, sliding-window
attention). All inference there happens inside llama.cpp; here the
architectures are first-class, defined once and instantiated as pure-JAX
functional models (see `models/llama.py`).

Configs are frozen/hashable so they can be passed as static arguments to
`jax.jit` — everything shape-affecting is compile-time constant, which is what
lets XLA tile the matmuls onto the MXU with static shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..ops.rope import (  # noqa: F401  (canonical home: ops/rope.py)
    RopeFreqFactors,
    RopeScaling,
    RopeScalingLike,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Architecture hyperparameters for one Llama-family model.

    Covers Llama-2 lineage (MHA, separate lm_head, theta=1e4 — the
    `duckdb-nsql` 7B shape), Llama-3.2 (GQA, tied embeddings, theta=5e5,
    llama3 rope scaling) and Mistral-7B (GQA + sliding-window attention).
    """

    name: str
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rope_scaling: Optional[RopeScalingLike] = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    sliding_window: Optional[int] = None  # Mistral-style local attention
    # Token ids — tokenizer-dependent; defaults are Llama-2 SentencePiece ids.
    bos_id: int = 1
    eos_id: int = 2
    pad_id: int = 0
    # Additional stop ids beyond eos_id. Llama-3.x chat checkpoints ship a
    # LIST of stop ids (`eos_token_id: [128001, 128008, 128009]` — the chat
    # turn ends at <|eot_id|>=128009, not <|end_of_text|>); collapsing to one
    # id makes chat completions run past the real stop (VERDICT r2 weak #7).
    extra_stop_ids: Tuple[int, ...] = ()

    def __post_init__(self):
        assert self.num_heads % self.num_kv_heads == 0, (
            f"num_heads={self.num_heads} not divisible by "
            f"num_kv_heads={self.num_kv_heads}"
        )

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads

    @property
    def stop_ids(self) -> Tuple[int, ...]:
        """The full stop set: eos_id plus any checkpoint-declared extras
        (e.g. llama3's <|eot_id|>). Engines default to this, not (eos_id,)."""
        return (self.eos_id,) + tuple(
            s for s in self.extra_stop_ids if s != self.eos_id
        )

    @property
    def num_params(self) -> int:
        """Approximate parameter count (embeddings + blocks + head)."""
        d, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        attn = d * self.num_heads * self.head_dim + 2 * d * self.num_kv_heads * self.head_dim
        attn += self.num_heads * self.head_dim * d
        mlp = 3 * d * f
        norms = 2 * d
        per_layer = attn + mlp + norms
        head = 0 if self.tie_embeddings else v * d
        return v * d + self.num_layers * per_layer + d + head


# --- Production shapes -----------------------------------------------------
# duckdb-nsql is a Llama-2-7B fine-tune (reference Project Report ch.7 ref [3],
# ollama.com/library/duckdb-nsql). Llama-2-7B architecture:
DUCKDB_NSQL_7B = LlamaConfig(
    name="duckdb-nsql-7b",
    vocab_size=32000,
    hidden_size=4096,
    intermediate_size=11008,
    num_layers=32,
    num_heads=32,
    num_kv_heads=32,
    head_dim=128,
    max_seq_len=4096,
    rope_theta=10000.0,
    norm_eps=1e-5,
)

LLAMA32_1B = LlamaConfig(
    name="llama3.2-1b",
    vocab_size=128256,
    hidden_size=2048,
    intermediate_size=8192,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    head_dim=64,
    max_seq_len=8192,
    rope_theta=500000.0,
    rope_scaling=RopeScaling(factor=32.0),
    norm_eps=1e-5,
    tie_embeddings=True,
    bos_id=128000,
    eos_id=128001,
    pad_id=128004,
    extra_stop_ids=(128008, 128009),  # <|eom_id|>, <|eot_id|> (chat stops)
)

LLAMA32_3B = LlamaConfig(
    name="llama3.2-3b",
    vocab_size=128256,
    hidden_size=3072,
    intermediate_size=8192,
    num_layers=28,
    num_heads=24,
    num_kv_heads=8,
    head_dim=128,
    max_seq_len=8192,
    rope_theta=500000.0,
    rope_scaling=RopeScaling(factor=32.0),
    norm_eps=1e-5,
    tie_embeddings=True,
    bos_id=128000,
    eos_id=128001,
    pad_id=128004,
    extra_stop_ids=(128008, 128009),
)

MISTRAL_7B = LlamaConfig(
    name="mistral-7b",
    vocab_size=32000,
    hidden_size=4096,
    intermediate_size=14336,
    num_layers=32,
    num_heads=32,
    num_kv_heads=8,
    head_dim=128,
    max_seq_len=8192,
    rope_theta=10000.0,
    norm_eps=1e-5,
    sliding_window=4096,
)

# --- Test / CI shapes ------------------------------------------------------
# Tiny config exercising every architectural feature (GQA, tied embeddings,
# llama3 rope scaling) at CPU-test size. head_dim=8 keeps CPU matmuls cheap.
TINY = LlamaConfig(
    name="tiny",
    vocab_size=320,  # >= ByteTokenizer's 259 so tiny end-to-end text tests work
    hidden_size=32,
    intermediate_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    head_dim=8,
    max_seq_len=128,
    rope_theta=10000.0,
    rope_scaling=RopeScaling(factor=8.0, original_max_position_embeddings=64),
    tie_embeddings=True,
    bos_id=1,
    eos_id=2,
    pad_id=0,
)

# Mid-size config for single-chip TPU smoke benchmarks when real 7B weights
# are not on disk: Llama-3.2-1B shape with a smaller vocab to bound HBM.
BENCH_1B = dataclasses.replace(LLAMA32_1B, name="bench-1b", vocab_size=32768,
                               bos_id=1, eos_id=2, pad_id=0,
                               extra_stop_ids=())

REGISTRY = {
    c.name: c
    for c in [DUCKDB_NSQL_7B, LLAMA32_1B, LLAMA32_3B, MISTRAL_7B, TINY, BENCH_1B]
}
