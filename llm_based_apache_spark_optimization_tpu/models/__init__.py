"""Llama-family model definitions (pure-functional JAX)."""

from .configs import (  # noqa: F401
    BENCH_1B,
    DUCKDB_NSQL_7B,
    LLAMA32_1B,
    LLAMA32_3B,
    MISTRAL_7B,
    REGISTRY,
    TINY,
    LlamaConfig,
    RopeScaling,
)
from .llama import forward, init_params  # noqa: F401
