"""Llama-family transformer as a pure-functional JAX model.

This is the in-tree replacement for the GGUF models llama.cpp executes for the
reference app (reference `Flask/app.py:102-107`, `FastAPI/app.py:85-90`): one
parameterized architecture covering duckdb-nsql-7B (Llama-2 shape), Llama-3.2
1B/3B (GQA, tied embeddings, llama3 rope scaling) and Mistral-7B
(sliding window) — see `models/configs.py`.

TPU-first design decisions:

- **Params are a plain pytree** (nested dict of `jax.Array`), not a module
  object: shardings attach via `jax.tree.map` + `NamedSharding`, the same tree
  flows through `jit`/`shard_map`/checkpointing with zero framework friction.
- **Per-layer weights are stacked on a leading [L, ...] axis**. For prefill
  the block stack runs under `jax.lax.scan`: XLA traces ONE block instead of
  L copies, so compile time and program size stay flat as models deepen.
- **Decode (T == 1) unrolls the layer loop instead.** Scanning the KV cache
  through xs/ys costs ~4x the cache size in HBM traffic PER DECODE STEP:
  the xs slice reads a layer's cache, `dynamic_update_slice` copies it, and
  the ys stacking writes it back — measured on v5e (bench-1b, B=32, S=1024)
  decode ran at 17.4 ms/step when weights+cache-read explain only ~4 ms.
  The unrolled loop writes each layer's fresh K/V as a tiny sliver into the
  stacked cache at a STATIC layer index and reads the layer's cache through
  a static slice; every update kills the previous buffer (single liveness
  chain), so XLA updates the cache in place and decode streams only weights
  + live cache. Unrolling costs compile time proportional to L — decode
  traces once per (B, bucket) signature, so the price is paid once.
- **One forward for prefill and decode**: the call is "run T tokens whose
  cache-write starts at per-sequence positions"; T=prompt_len is prefill, T=1
  is decode. Static shapes per (B, T) bucket, no dynamic control flow in jit.
- Matmuls run in the params dtype (bf16 on TPU -> MXU native); softmax, norms
  and rope run in f32; logits return in f32 for stable sampling.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import (
    attention_mask,
    gqa_attention,
    gqa_attention_quantized,
)
from ..ops.norm import rms_norm
from ..ops.pallas import (
    flash_gqa_attention,
    flash_gqa_attention_quantized,
    sharded_flash_gqa_attention,
    sharded_flash_gqa_attention_quantized,
)
from ..ops.quant import is_qtensor, mm, mm_stacked
from ..ops.ring_attention import ring_gqa_attention
from ..ops.rope import apply_rope, rope_cos_sin
from .configs import LlamaConfig

Params = Dict[str, jnp.ndarray]

# Cached forwards up to this many tokens take the unrolled layer loop (in-
# place cache slivers); longer ones (prefill) scan — the scan path's per-call
# cache restack amortizes over many tokens, and unrolling a long-T body would
# only grow the program. Covers decode (T=1) and speculative-verify windows.
_UNROLL_MAX_T = 32


def init_params(cfg: LlamaConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random-init params with the exact tree structure the weight loader fills.

    Init scale follows the standard 1/sqrt(fan_in) so random-weight smoke
    models produce finite logits at any depth.
    """
    d, f = cfg.hidden_size, cfg.intermediate_size
    nh, kh, hd, L = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim, cfg.num_layers
    keys = jax.random.split(key, 9)

    def w(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * fan_in**-0.5).astype(dtype)

    params: Params = {
        "embed": w(keys[0], (cfg.vocab_size, d), d),
        "blocks": {
            "wq": w(keys[1], (L, d, nh * hd), d),
            "wk": w(keys[2], (L, d, kh * hd), d),
            "wv": w(keys[3], (L, d, kh * hd), d),
            "wo": w(keys[4], (L, nh * hd, d), nh * hd),
            "wg": w(keys[5], (L, d, f), d),
            "wu": w(keys[6], (L, d, f), d),
            "wd": w(keys[7], (L, f, d), f),
            "ln_attn": jnp.ones((L, d), dtype),
            "ln_mlp": jnp.ones((L, d), dtype),
        },
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = w(keys[8], (cfg.vocab_size, d), d)
    return params


def fuse_blocks(params: Params) -> Params:
    """A params variant with same-input projections fused into one matmul:
    wq|wk|wv -> "wqkv" (MHA, equal shapes) or wk|wv -> "wkv" (GQA, where
    wq's out dim differs), and wg|wu -> "wgu".

    Prefill runs 7 medium matmuls per layer; fusing projections that share
    an input (h for QKV, h2 for gate/up) cuts kernel count and widens the
    MXU N dimension — one of the prefill-MFU levers (each output column is
    the same dot product, so results are exact: tests/test_model.py).

    Layout: the fused weight STACKS the projections on a new axis -2 —
    [L, D, C, O] — instead of concatenating out axes. Stacking is what
    makes the fusion tensor-parallel: the O axis shards over tp exactly
    like the unfused weights (parallel/sharding.param_specs) and the C
    split in forward is a device-local index, where a concatenated
    [L, D, C*O] axis would put projection boundaries mid-shard and force a
    reshard at every split. Works on bf16 trees, int8 QTensor trees
    (per-out-channel scales stack to [L, C, O]) and int4 packed trees
    (q4 [L, D/2, C, O] — the kernel flattens the contiguous (C, O) tail).
    """
    blocks = dict(params["blocks"])

    def out_dim(w):
        if is_qtensor(w):
            return w["q8"].shape[-1]
        if isinstance(w, dict) and "q4" in w:
            return w["q4"].shape[-1]
        return w.shape[-1]

    def stack(names):
        ws = [blocks.pop(n) for n in names]
        if is_qtensor(ws[0]):
            return {
                "q8": jnp.stack([w["q8"] for w in ws], axis=-2),
                "s": jnp.stack([w["s"] for w in ws], axis=-2),
            }
        if isinstance(ws[0], dict) and "q4" in ws[0]:
            return {
                "q4": jnp.stack([w["q4"] for w in ws], axis=-2),
                "s4": jnp.stack([w["s4"] for w in ws], axis=-2),
            }
        return jnp.stack(ws, axis=-2)

    if out_dim(blocks["wq"]) == out_dim(blocks["wk"]):  # MHA: one 3-stack
        blocks["wqkv"] = stack(("wq", "wk", "wv"))
    else:  # GQA: K/V share a shape, Q stays its own (wider) matmul
        blocks["wkv"] = stack(("wk", "wv"))
    blocks["wgu"] = stack(("wg", "wu"))
    out = dict(params)
    out["blocks"] = blocks
    return out


def maybe_fuse(params: Params, mesh) -> Params:
    """The engines' shared fuse_matmuls entry. The mesh argument is kept
    for call-site symmetry but no longer gates anything: the stacked fused
    layout TP-shards on its out axis (fuse_blocks docstring), so fusion
    composes with every mesh topology."""
    del mesh
    return fuse_blocks(params)


def split_blocks(params: Params) -> Params:
    """A params variant whose "blocks" is a per-layer LIST of trees (static
    slices of the stacked [L, ...] weights).

    Decode loops pass this to `forward` so the per-layer slices — and any
    layout conversions XLA decides the decode matmuls want — are anchored
    OUTSIDE the `lax.while_loop`/`lax.scan` body and run once per call
    instead of once per token (see forward's unrolled branch). Slices that
    need no layout change stay zero-copy bitcast views of the stacked
    buffer."""
    blocks = params["blocks"]
    n_layers = jax.tree.leaves(blocks)[0].shape[0]
    out = dict(params)
    out["blocks"] = [
        jax.tree.map(lambda a, _l=l: a[_l], blocks) for l in range(n_layers)
    ]
    return out


def _update_cache(cache: jnp.ndarray, new: jnp.ndarray, start: jnp.ndarray) -> jnp.ndarray:
    """Write `new` [B, T, K, H] into `cache` [B, K, S, H] at per-batch offsets.

    vmap of dynamic_update_slice lowers to an efficient batched scatter; each
    sequence writes a contiguous [T, H] block per KV head starting at its own
    position along the S axis.
    """
    return jax.vmap(
        lambda c, n, s: lax.dynamic_update_slice(c, n, (0, s, 0))
    )(cache, new.transpose(0, 2, 1, 3), start.astype(jnp.int32))


def _update_scale_layer(
    scales: jnp.ndarray, new: jnp.ndarray, start: jnp.ndarray, layer: int
) -> jnp.ndarray:
    """Write per-slot quant scales `new` [B, T, K] into the stacked scale
    tensor [L, B, K, S] at a static layer index and per-batch offsets (the
    int8-KV companion of `_update_cache_layer`; same per-row static-index
    DUS chain, same in-place reasoning)."""
    b = new.shape[0]
    upd = new.transpose(0, 2, 1)  # [B, K, T]
    start = start.astype(jnp.int32)
    for row in range(b):
        scales = lax.dynamic_update_slice(
            scales, upd[row][None, None].astype(scales.dtype),
            (layer, row, 0, start[row]),
        )
    return scales


def _update_cache_layer(
    cache: jnp.ndarray, new: jnp.ndarray, start: jnp.ndarray, layer: int
) -> jnp.ndarray:
    """Write `new` [B, T, K, H] into the STACKED cache [L, B, K, S, H] at a
    static layer index and per-batch offsets.

    Used by the unrolled decode path: the update is a tiny sliver and each
    call's result replaces the previous cache value (single liveness chain),
    so XLA performs the write in place instead of copying the layer.

    Expressed as a chain of per-row dynamic_update_slices with STATIC
    (layer, row) indices — only the slot offset is dynamic. Both batched
    alternatives copy the whole cache every call on TPU: a vmapped DUS
    transposes [L, B, ...] to batch-leading layout and back around the
    update (~32 full-cache `copy_bitcast_fusion`s per decode step), and a
    single `lax.scatter` picks a non-standard operand layout that forces a
    full-cache layout-conversion copy per layer. The static-index DUS chain
    is layout-preserving, so XLA aliases every link in place."""
    b = new.shape[0]
    upd = new.transpose(0, 2, 1, 3)[:, None, None]  # [B, 1, 1, K, T, H]
    start = start.astype(jnp.int32)
    for row in range(b):
        cache = lax.dynamic_update_slice(
            cache, upd[row].astype(cache.dtype), (layer, row, 0, start[row], 0)
        )
    return cache


# The paged write path lives in ops/pallas/paged_write.py: an XLA
# reference scatter (`paged_write_reference`, the pre-kernel path
# verbatim — bit-identical CPU/einsum serving) and the fused Pallas
# scatter-through-table kernel the T=1 pallas decode path swaps in
# (`fused_page_write` / the int8-quantizing variant).


def forward(
    cfg: LlamaConfig,
    params: Params,
    tokens: jnp.ndarray,      # [B, T] int32
    positions: jnp.ndarray,   # [B, T] int32 — absolute position of each token
    cache: Optional[Dict[str, jnp.ndarray]] = None,  # {"k","v"}: [L, B, K, S, H]
                              # or paged {"kp","vp": [L, P, K, PS, H],
                              # "ptab": [B, NP] i32} (engine/paged_kv.py)
    logit_indices: Optional[jnp.ndarray] = None,  # [B] int32 — unembed only these T-indices
    attn_impl: str = "xla",  # "xla" | "pallas" | "ring"; resolve via ops.pallas.attention_impl
    mesh=None,  # required for attn_impl="ring" (context-parallel prefill)
    kv_lens: Optional[jnp.ndarray] = None,  # [B] i32 — live KV slots per row
                                            # (pallas impl: bounds HBM
                                            # streaming; 0 parks a row)
    q_lens: Optional[jnp.ndarray] = None,   # [B] i32 — live query cols per
                                            # row (paged ragged windows:
                                            # dead cols write nothing and
                                            # read zeros)
) -> Tuple[jnp.ndarray, Optional[Dict[str, jnp.ndarray]]]:
    """Run T tokens through the stack; returns (logits f32, cache').

    With `cache=None` a transient [B, T] cache is used (pure prefill-only
    forward, e.g. for scoring); with a cache dict, K/V are written at
    `positions[:, 0] + t` and attention reads the full cache buffer.

    `logit_indices=None` returns full [B, T, V] logits. Passing per-sequence
    indices [B] gathers the hidden state *before* the unembed matmul and
    returns [B, 1, V] — during prefill only the last real token's logits are
    ever sampled, and skipping the [B, T, V] unembed saves a T-times-larger
    matmul and its f32 output buffer (V=128k makes this the dominant prefill
    cost at long T).
    """
    b, t = tokens.shape
    emb = params["embed"]
    if is_qtensor(emb):  # ops/quant.quantize_unembed: per-row int8 table
        rows = jnp.take(emb["q8"], tokens, axis=0).astype(jnp.float32)
        x = (rows * jnp.take(emb["s"], tokens, axis=0)[..., None]).astype(
            params["final_norm"].dtype
        )
    else:
        x = jnp.take(emb, tokens, axis=0)  # [B, T, D]
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling)
    start = positions[:, 0]

    quant_cache = cache is not None and "k8" in cache
    paged_cache = cache is not None and "kp" in cache
    if cache is None:
        kv_size = t
    elif quant_cache:
        kv_size = cache["k8"].shape[3]
    elif paged_cache:
        # Virtual contiguous length: logical pages × page size. The table
        # maps logical position p to pool page ptab[b, p // PS], offset
        # p % PS; unmapped entries only ever sit past a row's live length,
        # where causality masks them.
        kv_size = cache["ptab"].shape[1] * cache["kp"].shape[3]
    else:
        kv_size = cache["k"].shape[3]
    # Default is the always-correct einsum path: a bare forward() cannot see
    # whether its inputs are TP-sharded, and the pallas kernel requires
    # unsharded operands (or an explicit shard_map) — callers that know the
    # placement (engine/generate.py) pass the resolved impl explicitly.
    impl = attn_impl
    if impl == "ring" and mesh is None:
        raise ValueError('attn_impl="ring" requires a mesh with an "sp" axis')
    # int8 KV cache: einsum path for any small-T window; the pallas flash
    # kernel additionally supports T=1 decode (flash_gqa_attention_quantized
    # — int8 streaming AND per-row kv_lens bounding stacked).
    if quant_cache and not (
        (impl == "xla" and t <= _UNROLL_MAX_T)
        or (impl == "pallas" and t == 1)
    ):
        raise ValueError(
            "an int8 KV cache needs the einsum impl and the unrolled "
            f"small-T path (T <= {_UNROLL_MAX_T}), or the pallas impl at "
            "T=1 (decode): the prefill scan streams bf16 caches (engine "
            "prefill fills bf16, then quantizes once — engine/generate.py)"
        )
    if paged_cache and not (
        t <= _UNROLL_MAX_T and impl in ("xla", "pallas")
    ):
        raise ValueError(
            "a paged KV cache serves the unrolled small-T path only "
            f"(T <= {_UNROLL_MAX_T}; decode, verify windows, and mixed "
            "ragged prefill+decode rounds): longer prefill runs a "
            "contiguous transient/row cache and packs or scatters its K/V "
            "into pool pages (engine/generate.py, serve/scheduler.py)."
        )
    mask = (
        attention_mask(positions, kv_size, cfg.sliding_window)
        if impl == "xla"
        else None
    )

    nh, kh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def qkv(p, x):
        h = rms_norm(x, p["ln_attn"], cfg.norm_eps)
        # mm()/mm_stacked() transparently handle int8 QTensors and int4
        # packed trees (ops/quant.py); mesh routes int4 through its
        # shard_map wrapper with the weight's Megatron partition.
        if "wqkv" in p:  # fused MHA tree: one stacked [D, 3, O] matmul
            fused = mm_stacked(h, p["wqkv"], mesh)  # [B, T, 3, O]
            q = fused[..., 0, :].reshape(b, t, nh, hd)
            k = fused[..., 1, :].reshape(b, t, kh, hd)
            v = fused[..., 2, :].reshape(b, t, kh, hd)
        elif "wkv" in p:  # fused GQA tree: Q alone + stacked [D, 2, KO]
            q = mm(h, p["wq"], mesh).reshape(b, t, nh, hd)
            kv = mm_stacked(h, p["wkv"], mesh)  # [B, T, 2, KO]
            k = kv[..., 0, :].reshape(b, t, kh, hd)
            v = kv[..., 1, :].reshape(b, t, kh, hd)
        else:
            q = mm(h, p["wq"], mesh).reshape(b, t, nh, hd)
            k = mm(h, p["wk"], mesh).reshape(b, t, kh, hd)
            v = mm(h, p["wv"], mesh).reshape(b, t, kh, hd)
        return apply_rope(q, cos, sin), apply_rope(k, cos, sin), v

    def attn_mlp(p, x, q, k_full, v_full, k_fresh, v_fresh):
        if impl == "pallas":
            if mesh is not None:
                # Per-device kernel over the tp-sharded KV heads / dp-sharded
                # batch (shard_map); single-device pallas_call otherwise.
                attn = sharded_flash_gqa_attention(
                    mesh, q, k_full, v_full, positions, cfg.sliding_window,
                    kv_lens,
                )
            else:
                attn = flash_gqa_attention(
                    q, k_full, v_full, positions, cfg.sliding_window, kv_lens
                )
        elif impl == "ring":
            # Context-parallel self-attention over the fresh K/V of this call's
            # tokens (ring over the mesh "sp" axis; sequence axis sharded).
            # Correct only for prefill-from-position-0: the cache holds nothing
            # earlier than these tokens, so self-attention == cache attention.
            # K/V are still written to the cache for later decode steps.
            attn = ring_gqa_attention(
                mesh, q, k_fresh, v_fresh, positions,
                sliding_window=cfg.sliding_window,
            )
        else:
            attn = gqa_attention(q, k_full, v_full, mask)
        return post_attn(p, x, attn)

    def post_attn(p, x, attn):
        x = x + mm(attn.reshape(b, t, nh * hd), p["wo"], mesh, "row")
        h2 = rms_norm(x, p["ln_mlp"], cfg.norm_eps)
        if "wgu" in p:  # fused tree: gate|up stacked in one matmul
            gu = mm_stacked(h2, p["wgu"], mesh)  # [B, T, 2, F]
            g_out, u_out = gu[..., 0, :], gu[..., 1, :]
        else:
            g_out, u_out = mm(h2, p["wg"], mesh), mm(h2, p["wu"], mesh)
        gate = jax.nn.silu(g_out.astype(jnp.float32)).astype(x.dtype)
        x = x + mm(gate * u_out, p["wd"], mesh, "row")
        return x

    def block(x, layer_in):
        p, k_cache, v_cache = layer_in
        q, k, v = qkv(p, x)
        if k_cache is None:
            # Match the cache layout: [B, T, K, H] -> [B, K, T, H].
            k_full, v_full = k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)
            k_out = v_out = None
        else:
            k_full = _update_cache(k_cache, k, start)
            v_full = _update_cache(v_cache, v, start)
            k_out, v_out = k_full, v_full
        x = attn_mlp(p, x, q, k_full, v_full, k, v)
        return x, (k_out, v_out)

    unroll = t <= _UNROLL_MAX_T and impl != "ring" and cache is not None
    if isinstance(params["blocks"], (list, tuple)) and not unroll:
        raise ValueError(
            f"split_blocks params are only valid for the unrolled decode "
            f"path (T <= {_UNROLL_MAX_T}, cached, non-ring impl); pass the "
            f"stacked tree for prefill/ring/no-cache forwards"
        )
    if cache is None:
        # scan with no cache arrays: feed Nones via a python loop over stacked
        # params is wasteful; instead run scan with dummy empty caches.
        def block_nocache(x, p):
            y, _ = block(x, (p, None, None))
            return y, None
        x, _ = lax.scan(block_nocache, x, params["blocks"])
        new_cache = None
    elif unroll:
        # Decode (and small-T cached forwards, e.g. speculative-verify
        # windows): unrolled layer loop with in-place sliver writes into the
        # stacked cache (static layer indices). Scanning the cache through
        # xs/ys copies each layer's cache several times PER STEP — see the
        # module docstring for the measured cost.
        #
        # `params["blocks"]` may be a pre-sliced per-layer list
        # (split_blocks, used by decode loops): slicing the stacked weights
        # inside a `lax.while_loop` body leaves the layout conversions XLA
        # wants for the attention matmuls inside the loop (its invariant
        # code motion won't hoist buffers that large — profiled ~0.47
        # ms/step of repeated weight re-layout copies); pre-sliced params
        # anchor those conversions outside the loop, once per call.
        blocks = params["blocks"]
        new_cache = dict(cache)
        for l in range(cfg.num_layers):
            p = (blocks[l] if isinstance(blocks, (list, tuple))
                 else jax.tree.map(lambda a, _l=l: a[_l], blocks))
            q, k, v = qkv(p, x)
            if quant_cache:
                # int8 KV: quantize the fresh sliver (absmax over H), write
                # value+scale with the same static-index DUS chains, attend
                # with the int8-streaming einsum
                # (ops/attention.gqa_attention_quantized).
                from ..ops.quant import quantize_kv

                kq = quantize_kv(k)  # values [B, T, K, H], scales [B, T, K]
                vq = quantize_kv(v)
                new_cache["k8"] = _update_cache_layer(
                    new_cache["k8"], kq["q8"], start, l)
                new_cache["ks"] = _update_scale_layer(
                    new_cache["ks"], kq["s"], start, l)
                new_cache["v8"] = _update_cache_layer(
                    new_cache["v8"], vq["q8"], start, l)
                new_cache["vs"] = _update_scale_layer(
                    new_cache["vs"], vq["s"], start, l)
                if impl == "pallas":  # T == 1 (validated above)
                    fn = (sharded_flash_gqa_attention_quantized
                          if mesh is not None
                          else flash_gqa_attention_quantized)
                    args = (mesh,) if mesh is not None else ()
                    attn = fn(
                        *args, q, new_cache["k8"][l], new_cache["ks"][l],
                        new_cache["v8"][l], new_cache["vs"][l], positions,
                        cfg.sliding_window, kv_lens,
                    )
                else:
                    attn = gqa_attention_quantized(
                        q, new_cache["k8"][l], new_cache["ks"][l],
                        new_cache["v8"][l], new_cache["vs"][l], mask,
                    )
                x = post_attn(p, x, attn)
            elif paged_cache:
                # Paged pool: write the sliver through the page table,
                # then attend. The T=1 pallas path runs BOTH sides fused:
                # the scatter-through-table write kernel (K+V in one
                # launch, DMA slivers only — ops/pallas/paged_write) and
                # the ragged-paged read kernel whose DMA index map does
                # the gather; the xla/einsum path keeps the XLA reference
                # scatter (bit-identical to the pre-kernel write) and the
                # contiguous-view gather (any small T, e.g. verify
                # windows). An int8 pool ({"kps","vps"} scale arrays)
                # quantizes the fresh sliver on the way in — inside the
                # write kernel on the pallas path — and dequantizes on
                # the way out: in the read kernel's DMA'd tiles, or via
                # the int8-streaming einsum attention on the reference
                # path. Under a mesh, writes stay on the XLA scatter
                # (GSPMD partitions it over the pool's tp-sharded head
                # axis) and pallas reads go through the shard_map
                # wrappers, mirroring the contiguous branch.
                ptab = cache["ptab"]
                quant_paged = "kps" in cache
                use_write_kernel = impl == "pallas" and mesh is None
                if quant_paged:
                    if use_write_kernel:
                        from ..ops.pallas import fused_page_write_quantized

                        (new_cache["kp"], new_cache["kps"],
                         new_cache["vp"], new_cache["vps"]) = \
                            fused_page_write_quantized(
                                new_cache["kp"], new_cache["kps"],
                                new_cache["vp"], new_cache["vps"],
                                k, v, positions, ptab, l, q_lens=q_lens)
                    else:
                        from ..ops.pallas import (
                            paged_write_reference_quantized,
                        )

                        (new_cache["kp"], new_cache["kps"],
                         new_cache["vp"], new_cache["vps"]) = \
                            paged_write_reference_quantized(
                                new_cache["kp"], new_cache["kps"],
                                new_cache["vp"], new_cache["vps"],
                                k, v, positions, ptab, l, q_lens)
                else:
                    if use_write_kernel:
                        from ..ops.pallas import fused_page_write

                        new_cache["kp"], new_cache["vp"] = fused_page_write(
                            new_cache["kp"], new_cache["vp"], k, v,
                            positions, ptab, l, q_lens=q_lens)
                    else:
                        from ..ops.pallas import paged_write_reference

                        new_cache["kp"] = paged_write_reference(
                            new_cache["kp"], k, positions, ptab, l, q_lens)
                        new_cache["vp"] = paged_write_reference(
                            new_cache["vp"], v, positions, ptab, l, q_lens)
                if impl == "pallas":  # ragged windows (T·G bound validated
                                      # in the kernel wrapper)
                    if quant_paged:
                        from ..ops.pallas import (
                            ragged_paged_attention_quantized,
                            sharded_ragged_paged_attention_quantized,
                        )

                        if mesh is not None:
                            attn = sharded_ragged_paged_attention_quantized(
                                mesh, q, new_cache["kp"][l],
                                new_cache["kps"][l], new_cache["vp"][l],
                                new_cache["vps"][l], ptab, positions,
                                cfg.sliding_window, kv_lens, q_lens,
                            )
                        else:
                            attn = ragged_paged_attention_quantized(
                                q, new_cache["kp"][l], new_cache["kps"][l],
                                new_cache["vp"][l], new_cache["vps"][l],
                                ptab, positions, cfg.sliding_window,
                                kv_lens, q_lens,
                            )
                    else:
                        from ..ops.pallas import (
                            ragged_paged_attention,
                            sharded_ragged_paged_attention,
                        )

                        if mesh is not None:
                            attn = sharded_ragged_paged_attention(
                                mesh, q, new_cache["kp"][l],
                                new_cache["vp"][l], ptab, positions,
                                cfg.sliding_window, kv_lens, q_lens,
                            )
                        else:
                            attn = ragged_paged_attention(
                                q, new_cache["kp"][l], new_cache["vp"][l],
                                ptab, positions, cfg.sliding_window,
                                kv_lens, q_lens,
                            )
                elif quant_paged:
                    from ..ops.pallas import gather_page_scales, gather_pages

                    attn = gqa_attention_quantized(
                        q,
                        gather_pages(new_cache["kp"][l], ptab),
                        gather_page_scales(new_cache["kps"][l], ptab),
                        gather_pages(new_cache["vp"][l], ptab),
                        gather_page_scales(new_cache["vps"][l], ptab),
                        mask,
                    )
                else:
                    from ..ops.pallas import gather_pages

                    attn = gqa_attention(
                        q,
                        gather_pages(new_cache["kp"][l], ptab),
                        gather_pages(new_cache["vp"][l], ptab),
                        mask,
                    )
                x = post_attn(p, x, attn)
            else:
                new_cache["k"] = _update_cache_layer(
                    new_cache["k"], k, start, l)
                new_cache["v"] = _update_cache_layer(
                    new_cache["v"], v, start, l)
                x = attn_mlp(p, x, q, new_cache["k"][l], new_cache["v"][l],
                             k, v)
    else:
        x, (k_new, v_new) = lax.scan(
            block, x, (params["blocks"], cache["k"], cache["v"])
        )
        new_cache = {"k": k_new, "v": v_new}

    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if logit_indices is not None:
        x = jnp.take_along_axis(
            x, logit_indices.astype(jnp.int32)[:, None, None], axis=1
        )  # [B, 1, D]
    unembed = params["embed"] if cfg.tie_embeddings else params["lm_head"]
    if is_qtensor(unembed):
        # int8 streams straight into the dot (never .astype the table —
        # ops/quant.py's measured rule); per-row scales rescale the logit
        # columns in the f32 epilogue.
        logits = jnp.einsum(
            "btd,vd->btv", x, unembed["q8"],
            preferred_element_type=jnp.float32,
        ) * unembed["s"][None, None, :]
    else:
        logits = jnp.einsum("btd,vd->btv", x, unembed,
                            preferred_element_type=jnp.float32)
    return logits, new_cache
