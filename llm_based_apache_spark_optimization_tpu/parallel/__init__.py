"""Device mesh, TP/DP sharding specs, collective-by-construction parallelism."""

from .mesh import make_mesh  # noqa: F401
from .multihost import (  # noqa: F401
    global_mesh,
    init_distributed,
    is_primary,
    process_local_batch,
)
from .sharding import (  # noqa: F401
    batch_spec,
    cache_spec,
    constrain_cache,
    param_specs,
    shard_batch,
    specs_for_params,
    shard_params,
    validate_tp,
)
