"""Device mesh, sharding specs, tensor/sequence parallelism, collectives."""
