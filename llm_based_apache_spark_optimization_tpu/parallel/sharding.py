"""Sharding rules: how the Llama param tree, KV cache and batches partition.

Replaces the role of NCCL/MPI in GPU serving stacks — sharding specs are the
*whole* communication story here: annotate placements, and XLA GSPMD inserts
the all-reduce after row-parallel matmuls (wo, wd) and any resharding moves,
compiled onto ICI (SURVEY.md §2.4, §5 "Distributed communication backend").

Megatron-style tensor parallelism over the "tp" axis:

  wq/wk/wv [L, D, heads*H]  column-parallel  -> shard last dim
  wo       [L, N*H, D]      row-parallel     -> shard first (contracted) dim
  wg/wu    [L, D, F]        column-parallel  -> shard last dim
  wd       [L, F, D]        row-parallel     -> shard contracted dim
  norms                     replicated
  embed / lm_head           VOCAB-sharded over tp (specs_for_params): the
                            unembed's FLOPs are negligible at decode but its
                            table STREAMING is not (7B bf16: ~260 MB/step,
                            ~4% of int8-quantized decode bytes; 22% of an
                            int4 tree's) — row-sharding splits that across
                            the mesh, the logits come out vocab-sharded with
                            no collective, and sampling's argmax/top-k pulls
                            a ~1 MB/step all-gather XLA inserts on its own.
                            The embedding gather over the sharded table is
                            a few rows of traffic either way.

KV cache [L, B, K, S, H] shards batch over "dp", KV heads over "tp" and
cache slots over "sp" — each chip holds only its own heads' slice of its
own sequence window, which is what makes the 7B batch=32 cache fit
(engine/kvcache.py sizing note) and makes context length scale with the
sp axis (cache_spec docstring).

Constraint: num_heads and num_kv_heads must divide by tp (checked in
`validate_tp`).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import LlamaConfig

Pytree = Any


def validate_tp(cfg: LlamaConfig, tp: int) -> None:
    if cfg.num_heads % tp or cfg.num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"num_kv_heads={cfg.num_kv_heads} ({cfg.name})"
        )


def param_specs(cfg: LlamaConfig, quantized: bool = False,
                q_unembed: bool = False) -> Dict[str, Any]:
    """PartitionSpec tree matching models.llama.init_params exactly.

    With `quantized=True` the seven matmul weights become QTensor dicts
    (ops/quant.py): q8 shards exactly like the original weight; the
    per-output-channel scale keeps only the out axis, so it shards over tp
    for column-parallel weights and replicates for row-parallel ones (the
    scale multiply happens after GSPMD's all-reduce of the partial sums).
    `q_unembed` mirrors quantize_unembed's {"q8","s"} embed/lm_head dicts
    (vocab-sharded like the bf16 tables — module docstring).

    This flag form covers UNFUSED trees only (it is the shape-contract the
    checkpoint loaders pre-declare shardings from, before any tree exists);
    `specs_for_params` derives specs from an actual tree and additionally
    handles int4 and fused layouts. The two MUST agree on placement for
    shared layouts: the loaders stream weights straight to these specs,
    and a disagreement would make every engine init reshuffle the tables
    across the mesh.
    """
    def w(spec: P) -> Any:
        return {"q8": spec, "s": P(spec[0], spec[2])} if quantized else spec

    def table() -> Any:
        return ({"q8": P("tp", None), "s": P("tp")} if q_unembed
                else P("tp", None))

    specs: Dict[str, Any] = {
        "embed": table(),
        "blocks": {
            "wq": w(P(None, None, "tp")),
            "wk": w(P(None, None, "tp")),
            "wv": w(P(None, None, "tp")),
            "wo": w(P(None, "tp", None)),
            "wg": w(P(None, None, "tp")),
            "wu": w(P(None, None, "tp")),
            "wd": w(P(None, "tp", None)),
            "ln_attn": P(None, None),
            "ln_mlp": P(None, None),
        },
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = table()
    return specs


# Row-parallel block weights shard their CONTRACTION axis; everything else
# named here is column-parallel (out axis over tp).
_ROW_PARALLEL = ("wo", "wd")


def specs_for_params(params: Pytree, tp: int = 1) -> Pytree:
    """PartitionSpec tree derived leaf-by-leaf from an ACTUAL params tree —
    bf16 / int8 QTensor / int4 packed-nibble weights, fused (stacked
    wqkv/wkv/wgu, [L, D, C, O]) and unfused layouts alike.

    Rules (Megatron split, module docstring):
    - column-parallel weights shard the out (last) axis over tp; their
      per-out-channel / per-(group, out) scales shard with it;
    - row-parallel weights (wo/wd) shard the contraction axis; int8 scales
      replicate (applied after GSPMD's psum), int4 group scales shard WITH
      their groups (applied inside the kernel, before the explicit psum —
      ops/pallas/int4mm.sharded_int4_matmul);
    - stacked fused weights are always column-parallel: out axis over tp,
      the C (projection) axis replicated — the device-local split is the
      point of the stacked layout (models/llama.fuse_blocks);
    - embed/lm_head tables shard their VOCAB axis over tp (splits the
      unembed's per-step table streaming — module docstring); norms
      replicate.

    `tp` is used only for the int4 row-parallel group-alignment check: a
    shard must hold whole quant groups (quantize_params_int4 defaults to
    tp-safe groups; a hand-built tree with misaligned groups fails here
    with a clear error instead of silently wrong math).
    """
    from ..ops.quant import is_q4tensor, is_qtensor

    def wspec(name: str, w: Any) -> Any:
        row = name in _ROW_PARALLEL
        if is_qtensor(w):
            if w["q8"].ndim == 4:  # stacked fused [L, D, C, O]
                return {"q8": P(None, None, None, "tp"),
                        "s": P(None, None, "tp")}
            return ({"q8": P(None, "tp", None), "s": P(None, None)} if row
                    else {"q8": P(None, None, "tp"), "s": P(None, "tp")})
        if is_q4tensor(w):
            if w["q4"].ndim == 4:  # stacked fused [L, D/2, C, O]
                return {"q4": P(None, None, None, "tp"),
                        "s4": P(None, None, None, "tp")}
            if row:
                n_groups = w["s4"].shape[-2]
                if n_groups % tp:
                    raise ValueError(
                        f"int4 {name}: tp={tp} does not divide the "
                        f"{n_groups} quant groups — a tensor-parallel "
                        f"shard would split a group (requantize with "
                        f"ops.quant.tp_safe_group)"
                    )
                return {"q4": P(None, "tp", None), "s4": P(None, "tp", None)}
            return {"q4": P(None, None, "tp"), "s4": P(None, None, "tp")}
        if w.ndim == 4:  # stacked fused bf16 [L, D, C, O]
            return P(None, None, None, "tp")
        return P(None, "tp", None) if row else P(None, None, "tp")

    def table(t: Any) -> Any:
        # Vocab axis over tp (module docstring): splits the unembed table's
        # per-step HBM streaming across the mesh; int8 tables shard their
        # per-row scales with their rows.
        return {"q8": P("tp", None), "s": P("tp")} if is_qtensor(t) \
            else P("tp", None)

    specs: Dict[str, Any] = {
        "embed": table(params["embed"]),
        "blocks": {
            k: (P(None, None) if k.startswith("ln_") else wspec(k, v))
            for k, v in params["blocks"].items()
        },
        "final_norm": P(None),
    }
    if "lm_head" in params:
        specs["lm_head"] = table(params["lm_head"])
    return specs


def cache_spec() -> P:
    """[L, B, K, S, H]: batch over dp, KV heads over tp, SLOTS over sp.

    Sequence-sharding the decode cache is what makes long context a
    capacity story the mesh solves: an sp-way mesh holds sp× the context
    one chip's HBM fits (7B int8-KV at 128k tokens is ~34 GB — no single
    v5e holds it; an sp=4 slice does). Verified lowering on a virtual
    dp=1×sp=2×tp=2 mesh (decode step, einsum impl): the per-token cache
    writes stay LOCAL dynamic-update-slices (0 all-gathers, 0
    all-to-alls in the compiled HLO — GSPMD masks the write to the shard
    owning the slot), and attention's softmax/value reductions over the
    sharded S axis lower to all-reduces of [B, 1, heads·H]-sized
    partials — a flash-decoding-style combine, KBs per step on ICI.
    Exact-parity-tested against the single-device engine. (The forced
    pallas decode kernel's shard_map expects S-replicated K/V and will
    all-gather per step under sp>1 — the auto einsum path is the sp
    decode impl.)"""
    return P(None, "dp", "tp", "sp", None)


def batch_spec(ndim: int = 2) -> P:
    """[B, ...] batches: rows over dp, remaining dims replicated."""
    return P("dp", *([None] * (ndim - 1)))


def shard_params(params: Pytree, cfg: LlamaConfig, mesh: Mesh) -> Pytree:
    """Place a (host or single-device) param tree onto the mesh. Specs are
    derived from the tree itself (specs_for_params), so every layout the
    model layer produces — quantized, int4, fused — shards here."""
    validate_tp(cfg, mesh.shape["tp"])
    specs = specs_for_params(params, tp=mesh.shape["tp"])
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_batch(tokens: Pytree, mesh: Mesh) -> Pytree:
    """Place [B, ...] host arrays row-sharded over dp, replicated otherwise."""
    def put(x):
        return jax.device_put(x, NamedSharding(mesh, batch_spec(x.ndim)))
    return jax.tree.map(put, tokens)


def paged_pool_spec(ndim: int = 5) -> P:
    """Pool arrays [L, P, K, PS(, H)]: KV heads over tp, every other axis
    replicated. The PAGE axis cannot shard — page tables hold global pool
    indices and any slot may map any page — and there is no dp/sp row to
    shard either (the pool is shared across all slots; dp means replica
    processes at the scheduler level). Per-position scale arrays (int8
    pool, ndim=4) drop the trailing H but keep heads-over-tp."""
    return (P(None, None, "tp", None, None) if ndim == 5
            else P(None, None, "tp", None))


def constrain_cache(cache: Pytree, mesh: Mesh) -> Pytree:
    """Pin the in-program KV cache layout (called inside jit).

    Handles every cache form: contiguous bf16 {"k","v"} [L, B, K, S, H]
    and int8 {"k8","ks","v8","vs"} (the [L, B, K, S] scale tensors drop
    the head axis from the spec but keep batch-over-dp / heads-over-tp /
    slots-over-sp), and the PAGED pool {"kp","vp"(,"kps","vps"),"ptab"}
    — pool KV heads over tp (paged_pool_spec), page tables replicated."""
    def put(x, spec):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    if isinstance(cache, dict) and "kp" in cache:
        def pin_paged(name, x):
            if name == "ptab":
                return put(x, P(None, None))
            return put(x, paged_pool_spec(x.ndim))

        return {k: pin_paged(k, v) for k, v in cache.items()}

    def pin(x):
        spec = cache_spec() if x.ndim == 5 else P(None, "dp", "tp", "sp")
        return put(x, spec)

    return jax.tree.map(pin, cache)
