"""Device mesh construction for TP×DP sharded inference.

The reference has no distributed layer at all — its only parallelism is
whatever llama.cpp does on one host (SURVEY.md §2.4). Here the mesh is the
foundation: every sharded object (params, KV cache, token batches) is placed
by `NamedSharding(mesh, PartitionSpec(...))` and XLA GSPMD compiles the
communication (all-reduce after row-parallel matmuls) onto ICI.

Axes:
  dp — data/request parallelism: batch dimension of serving requests.
  sp — sequence/context parallelism: the sequence axis of long prompts,
       attended via the ring kernel (ops/ring_attention.py) whose ppermute
       hops ride neighboring ICI links.
  tp — tensor parallelism: attention heads / MLP hidden dim (Megatron-style).

A v5e-8 slice is typically meshed as dp=2, tp=4 or dp=1, tp=8 (BASELINE.json
configs 4/5); sp enters only for long-context prefill (sp=1 otherwise).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    dp: int = 1,
    sp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, sp, tp) mesh over `devices` (default: all local devices).

    tp is placed on the fastest-varying axis so tensor-parallel collectives
    ride neighboring ICI links; sp sits between dp and tp so ring ppermute
    neighbors are one ICI hop apart for the common tp=1 long-context layout.
    """
    if devices is None:
        devices = jax.devices()
    if dp * sp * tp != len(devices):
        raise ValueError(
            f"dp*sp*tp = {dp * sp * tp} != device count {len(devices)}"
        )
    arr = np.asarray(devices).reshape(dp, sp, tp)
    return Mesh(arr, ("dp", "sp", "tp"))
