"""Device mesh construction for TP×DP sharded inference.

The reference has no distributed layer at all — its only parallelism is
whatever llama.cpp does on one host (SURVEY.md §2.4). Here the mesh is the
foundation: every sharded object (params, KV cache, token batches) is placed
by `NamedSharding(mesh, PartitionSpec(...))` and XLA GSPMD compiles the
communication (all-reduce after row-parallel matmuls) onto ICI.

Axes:
  dp — data/request parallelism: batch dimension of serving requests.
  tp — tensor parallelism: attention heads / MLP hidden dim (Megatron-style).

A v5e-8 slice is typically meshed as dp=2, tp=4 or dp=1, tp=8 (BASELINE.json
configs 4/5).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    dp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, tp) mesh over `devices` (default: all local devices).

    tp is placed on the fastest-varying axis so tensor-parallel collectives
    ride neighboring ICI links.
    """
    if devices is None:
        devices = jax.devices()
    if dp * tp != len(devices):
        raise ValueError(f"dp*tp = {dp * tp} != device count {len(devices)}")
    arr = np.asarray(devices).reshape(dp, tp)
    return Mesh(arr, ("dp", "tp"))
