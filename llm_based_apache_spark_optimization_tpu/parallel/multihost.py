"""Multi-host (multi-slice) runtime: the framework's DCN-scale story.

The reference has no distributed layer at all (SURVEY.md §2.4 — its only
transports are HTTP/py4j/MySQL-wire); the scaling analog in GPU stacks is
NCCL/MPI process groups. The TPU-native equivalent is JAX's distributed
runtime: every host runs the same program, `jax.distributed.initialize`
wires the hosts into one system, and a mesh built over `jax.devices()`
(which, after initialization, spans *all* hosts' chips) makes GSPMD compile
cross-host collectives — intra-slice traffic rides ICI, inter-slice rides
DCN. No NCCL, no MPI: placement specs are the whole communication story.

Layout convention: `global_mesh` keeps dp outermost so data parallelism
crosses slices over DCN (cheap, gradient/result-sized transfers — or in
this serving stack, independent requests), while sp/tp stay inside a slice
where the ring/all-reduce traffic belongs on ICI. This follows the standard
mesh recipe (the scaling-book ordering: DCN-friendly axes outermost).

Single-host runs need none of this: every entry point treats "no
coordinator configured, one process" as the degenerate case and becomes a
no-op, so the same code path serves laptop CI and a v5e pod.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join this process into the multi-host runtime; returns True if it did.

    Arguments default to the standard env vars (LSOT_COORDINATOR,
    LSOT_NUM_PROCESSES, LSOT_PROCESS_ID, falling back to JAX's own
    auto-detection on Cloud TPU where the metadata server provides them).
    Safe to call unconditionally: a single-process run with no coordinator
    is a no-op.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("LSOT_COORDINATOR")
    num_processes = num_processes or _int_env("LSOT_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("LSOT_PROCESS_ID")
    if coordinator_address is None and num_processes is None:
        return False  # single-process mode
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def global_mesh(
    dp: int = 1,
    sp: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """(dp, sp, tp) mesh over every device in the system (all hosts).

    dp is outermost so its boundaries align with host/slice boundaries and
    cross-slice traffic stays DCN-friendly; sp/tp vary fastest so their
    collectives stay on ICI within a slice. Works identically single-host
    (where it matches `mesh.make_mesh`).
    """
    if devices is None:
        devices = jax.devices()  # global list after init_distributed
    if dp * sp * tp != len(devices):
        raise ValueError(
            f"dp*sp*tp = {dp * sp * tp} != global device count {len(devices)}"
        )
    arr = np.asarray(devices).reshape(dp, sp, tp)
    return Mesh(arr, ("dp", "sp", "tp"))


def process_local_batch(global_batch, mesh: Mesh):
    """Assemble a dp-sharded global array from per-host local batch rows.

    Each host feeds only its own requests (`global_batch` here is the host's
    local [B_local, ...] numpy array); the result is a global jax.Array of
    shape [B_local * num_processes, ...] sharded over dp without any host
    ever materializing the full batch — the multi-host analog of
    `sharding.shard_batch`.
    """
    spec = P("dp", *([None] * (np.ndim(global_batch) - 1)))
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), np.asarray(global_batch)
    )


def is_primary() -> bool:
    """True on the process that should do singleton work (logging, serving
    the HTTP frontend, writing history rows)."""
    return jax.process_index() == 0
