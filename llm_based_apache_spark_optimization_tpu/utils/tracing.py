"""Request-scoped tracing: span trees, head sampling, Chrome-trace export.

The serving stack's existing instruments are all *aggregates* —
`MetricsRegistry` windowed percentiles, flat `CounterSet`s, the watchdog's
heartbeat. None of them can answer "where did THIS request's 400 ms go?"
— the question every per-request latency regression, stuck stream, or
deadline burn starts with. This module is the per-request answer:

- `RequestTrace` — one request's span tree. A `request_id` is generated at
  admission (the HTTP layer), echoed in the response (`X-Request-Id` and
  the JSON body) and in the structured request log line, and the trace
  object itself travels with the request: contextvar propagation inside
  the HTTP thread (service → pipeline → SQL backend), explicit
  `submit(trace=...)` across the scheduler's thread boundary (the worker
  thread records queue-wait / prefill / per-decode-round spans into the
  same tree). Spans are recorded with `time.perf_counter()` pairs and
  anchored to wall-clock once per trace, so cross-thread spans line up.
- `Tracer` — head sampling + export. `LSOT_TRACE_SAMPLE` is the sampled
  fraction (0 = off, 1 = every request); an unsampled request costs one
  RNG draw at admission and ZERO span work everywhere else (`span()` on a
  None trace is a no-op context manager — bench's scheduler leg prices
  this). Sampled traces export per request as JSONL
  (`<dir>/requests.jsonl`) and as a per-request Chrome-trace file
  (`<request_id>.trace.json.gz`) that loads in Perfetto AND in
  `utils/traceprof.Trace` (same event model: "X" complete events under
  named thread lanes), and the last few live in an in-memory ring for
  `/debug/traces`.

Span naming convention (dotted stages, one lane per top-level prefix in
the Chrome export): `service.generate`, `sched.queue_wait`,
`sched.prefill`, `sched.decode`, `sched.round` (one per harvested decode
round, with accepted-token / speculation / grammar attrs),
`stream.deliver`, `sql.load`, `sql.exec`, `sql.write_csv`.

Everything is thread-safe: the HTTP thread and the scheduler worker
thread append spans to one trace concurrently.
"""

from __future__ import annotations

import contextlib
import contextvars
import gzip
import itertools
import json
import os
import random
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

__all__ = [
    "RequestTrace",
    "Tracer",
    "TRACER",
    "begin_or_ambient",
    "current",
    "decided",
    "new_request_id",
    "span",
    "stepwise",
    "use",
]

_rid_counter = itertools.count(1)
_rid_base = f"{os.getpid():x}-{int(time.time()) & 0xFFFFFF:x}"


def new_request_id() -> str:
    """Process-unique, sortable-enough request id: pid + boot stamp + a
    monotonic counter. Cheap (no uuid import, no entropy syscall) — it is
    generated for EVERY request, sampled or not, because the id is also
    the log-correlation handle."""
    return f"req-{_rid_base}-{next(_rid_counter):x}"


class RequestTrace:
    """One request's span tree. Flat storage (list of span dicts, each
    carrying its parent's name) — renders as a tree in `to_dict()` and as
    per-lane "X" events in `to_chrome()`. Appends take one small lock, so
    the scheduler worker and the HTTP thread can both record."""

    def __init__(self, request_id: str, model: str = "", attrs: Optional[Dict] = None):
        self.request_id = request_id
        self.model = model
        self.attrs = dict(attrs or {})
        # Anchor: one (wall, perf) pair taken at creation maps every
        # perf_counter stamp — from any thread — onto the wall clock for
        # the Chrome export's absolute `ts` values.
        self._wall0 = time.time()
        self._perf0 = time.perf_counter()
        self._lock = threading.Lock()
        self._spans: List[Dict] = []

    # ------------------------------------------------------------ recording

    def add_span(self, name: str, t0: float, t1: float, **attrs) -> None:
        """Record a completed span from explicit perf_counter stamps —
        the cross-thread path (the scheduler worker stamps floats on the
        request and flushes spans at retire)."""
        rec: Dict = {"name": name, "t0": t0, "t1": t1}
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self._spans.append(rec)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add_span(name, t0, time.perf_counter(), **attrs)

    def event(self, name: str, **attrs) -> None:
        """Zero-duration marker (rendered as a tiny span)."""
        t = time.perf_counter()
        self.add_span(name, t, t, **attrs)

    # -------------------------------------------------------------- export

    def to_dict(self) -> Dict:
        """JSONL record: spans sorted by start, durations in seconds,
        offsets relative to the trace origin. Dotted names ARE the tree:
        `sched.decode` nests under the request root beside `sql.exec`."""
        with self._lock:
            spans = sorted(self._spans, key=lambda s: s["t0"])
        return {
            "request_id": self.request_id,
            "model": self.model,
            "start_unix": round(self._wall0, 6),
            **({"attrs": self.attrs} if self.attrs else {}),
            "spans": [
                {
                    "name": s["name"],
                    "start_s": round(s["t0"] - self._perf0, 6),
                    "dur_s": round(s["t1"] - s["t0"], 6),
                    **({"attrs": s["attrs"]} if "attrs" in s else {}),
                }
                for s in spans
            ],
        }

    def to_chrome(self) -> Dict:
        """Chrome-trace JSON (Perfetto-loadable), one thread lane per
        top-level span prefix (`sched`, `sql`, `service`, ...). The event
        model matches what `utils/traceprof.Trace._ingest` parses: thread
        name metadata + "X" complete events with microsecond ts/dur —
        so the SAME parser that reads jax.profiler device traces
        round-trips these request traces (the lane names avoid its
        host-lane deny list)."""
        with self._lock:
            spans = sorted(self._spans, key=lambda s: s["t0"])
        lanes: Dict[str, int] = {}
        events: List[Dict] = [{
            "ph": "M", "name": "process_name", "pid": 1,
            "args": {"name": f"request {self.request_id}"},
        }]
        body: List[Dict] = []
        for s in spans:
            prefix = s["name"].split(".", 1)[0]
            tid = lanes.setdefault(prefix, len(lanes) + 1)
            dur_us = max(1.0, (s["t1"] - s["t0"]) * 1e6)  # 0-dur events drop
            body.append({
                "ph": "X", "name": s["name"], "pid": 1, "tid": tid,
                "ts": (self._wall0 + (s["t0"] - self._perf0)) * 1e6,
                "dur": dur_us,
                **({"args": s["attrs"]} if "attrs" in s else {}),
            })
        for prefix, tid in lanes.items():
            events.append({
                "ph": "M", "name": "thread_name", "pid": 1, "tid": tid,
                "args": {"name": f"lane:{prefix}"},
            })
        events.extend(body)
        return {"traceEvents": events, "displayTimeUnit": "ms"}


class Tracer:
    """Head-sampling trace factory + exporter.

    `begin()` returns a `RequestTrace` for the sampled fraction of
    requests and None otherwise — every downstream `span()` call on None
    is a no-op, which is what makes always-on tracing safe at high QPS.
    `finish()` exports (JSONL append + per-request gzipped Chrome trace
    when an export dir is configured) and keeps the last `ring` traces in
    memory for `/debug/traces`."""

    def __init__(self, sample: Optional[float] = None,
                 export_dir: Optional[str] = None, ring: int = 32,
                 seed: Optional[int] = None):
        if sample is None:
            sample = float(os.environ.get("LSOT_TRACE_SAMPLE", "0") or 0)
        if export_dir is None:
            export_dir = os.environ.get("LSOT_TRACE_EXPORT") or None
        self.sample = min(1.0, max(0.0, sample))
        self.export_dir = export_dir
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._ring: "deque[Dict]" = deque(maxlen=max(1, ring))
        self._exported = 0

    def begin(self, request_id: Optional[str] = None, model: str = "",
              **attrs) -> Optional[RequestTrace]:
        if self.sample <= 0.0:
            return None
        if self.sample < 1.0 and self._rng.random() >= self.sample:
            return None
        return RequestTrace(request_id or new_request_id(), model=model,
                            attrs=attrs)

    def finish(self, trace: Optional[RequestTrace]) -> Optional[Dict]:
        """Export a completed trace; None-safe (the unsampled fast path).
        Export failures are swallowed — tracing must never fail the
        request it is observing."""
        if trace is None:
            return None
        doc = trace.to_dict()
        with self._lock:
            self._ring.append(doc)
            self._exported += 1
            # The shared requests.jsonl append stays under the lock too:
            # the threaded WSGI server finishes traces concurrently, and
            # a doc line longer than one os.write (hundreds of
            # sched.round spans) would otherwise interleave with another
            # thread's line and corrupt the JSONL. Export is sampled and
            # off the request hot path, so holding the lock for the
            # write is cheap. The per-request Chrome file needs no lock
            # (unique path per request_id).
            if self.export_dir:
                try:
                    os.makedirs(self.export_dir, exist_ok=True)
                    path = os.path.join(self.export_dir, "requests.jsonl")
                    with open(path, "a", encoding="utf-8") as f:
                        f.write(json.dumps(doc) + "\n")
                except OSError:
                    pass
        if self.export_dir:
            try:
                chrome = os.path.join(
                    self.export_dir, f"{trace.request_id}.trace.json.gz"
                )
                with gzip.open(chrome, "wt", encoding="utf-8") as f:
                    json.dump(trace.to_chrome(), f)
            except OSError:
                pass
        return doc

    def recent(self, n: Optional[int] = None) -> List[Dict]:
        """Last sampled traces (newest last) for `/debug/traces`."""
        with self._lock:
            out = list(self._ring)
        return out[-n:] if n else out

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "sample": self.sample,
                "export_dir": self.export_dir,
                "exported": self._exported,
                "ring": len(self._ring),
            }

    def reconfigure(self, sample: Optional[float] = None,
                    export_dir: Optional[str] = None) -> None:
        """App-startup wiring seam (LSOT_TRACE_SAMPLE / LSOT_TRACE_EXPORT
        resolve through AppConfig, which may be built after import)."""
        if sample is not None:
            self.sample = min(1.0, max(0.0, float(sample)))
        if export_dir is not None:
            self.export_dir = export_dir or None


#: Process-wide tracer the serving layer begins/finishes requests on.
TRACER = Tracer()

#: The active request's trace within one thread of control (HTTP handler →
#: service → pipeline → SQL backend). The scheduler worker thread is NOT
#: under this contextvar — the trace crosses that boundary explicitly via
#: `submit(trace=...)`.
_CURRENT: "contextvars.ContextVar[object]" = (
    contextvars.ContextVar("lsot_trace", default=None)
)

#: Stored in the contextvar when an upstream layer drew the sampling
#: decision and the answer was "not sampled". Distinct from the default
#: None ("nobody decided yet") so a downstream entry point — the service
#: under the HTTP layer — doesn't re-draw and double the effective
#: sample rate.
_UNSAMPLED = object()


def current() -> Optional[RequestTrace]:
    v = _CURRENT.get()
    return None if v is _UNSAMPLED else v  # type: ignore[return-value]


def decided() -> bool:
    """True when this thread of control already carries a sampling
    decision (sampled trace OR explicit unsampled marker)."""
    return _CURRENT.get() is not None


@contextlib.contextmanager
def use(trace: Optional[RequestTrace]) -> Iterator[Optional[RequestTrace]]:
    """Install `trace` as the thread's current trace for the block.
    None records the decision as made-but-unsampled (see `decided()`)."""
    token = _CURRENT.set(trace if trace is not None else _UNSAMPLED)
    try:
        yield trace
    finally:
        _CURRENT.reset(token)


def begin_or_ambient(request_id: Optional[str] = None,
                     model: str = "") -> tuple:
    """The service entry-point sampling dance (generate/generate_stream):
    honor an upstream decision when one exists (`decided()` — the HTTP
    layer sampled, or explicitly didn't), else head-sample HERE so the
    span tree exists for every entry path, drawn exactly once. Returns
    `(tr, own, rid)`: `tr` is the trace to record into (None when
    unsampled), `own` is non-None only when THIS call drew the sample —
    the caller owns its export (`TRACER.finish(own)`) — and `rid` is the
    effective request id."""
    ambient = current()
    own = TRACER.begin(request_id=request_id, model=model) \
        if not decided() else None
    tr = ambient if ambient is not None else own
    rid = request_id or (tr.request_id if tr is not None else "")
    return tr, own, rid


def stepwise(inner: Iterator, trace: Optional[RequestTrace]) -> Iterator:
    """Yield `inner`'s items, advancing it under `use(trace)` but NEVER
    holding the context across our own yields: generators share the
    thread's context, so a contextvar set held across a yield leaks into
    the consumer's frame between steps — a consumer interleaving two
    sampled streams would record request B's spans into request A's tree
    (and suppress B's own sampling draw). THE shared workaround for the
    generator/contextvar hazard; hand-rolling it is how it regresses."""
    while True:
        with use(trace):
            try:
                item = next(inner)
            except StopIteration:
                return
        yield item


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[None]:
    """Record a span on the current trace; free no-op when unsampled
    (one contextvar read, no perf_counter call)."""
    tr = _CURRENT.get()
    if tr is None or tr is _UNSAMPLED:
        yield
        return
    with tr.span(name, **attrs):
        yield
