"""Prometheus text exposition for the /metrics payload.

`/metrics?format=prometheus` renders the SAME data the JSON endpoint
serves — per-model request aggregates, serving-layer stats, process-wide
resilience counters — in the exposition format (text/plain; version
0.0.4) every scrape stack ingests, plus the fixed-bucket TTFT / TPOT /
queue-wait / latency histograms `MetricsRegistry` now keeps beside its
windowed percentiles (histograms aggregate across scrapes and replicas;
windowed percentiles cannot). Both serving systems in the vLLM/TGI
comparison (PAPERS.md) ship this surface as table stakes.

Rendering rules (no client library — the format is 20 lines of spec):

- metric names: `lsot_` + snake_case path; `# HELP`/`# TYPE` emitted once
  per name, all samples of one name contiguous (the exposition grammar
  requires it).
- per-model scalar aggregates become gauges/counters labeled
  `{model="..."}`; nested serving stats flatten with `_`-joined paths
  (`lsot_serving_prefix_cache_hits`); booleans render 0/1; non-numeric
  leaves are skipped (they stay JSON-only).
- resilience counters: `lsot_resilience_events_total{event="retries"}`;
  breaker states: `lsot_breaker_open{dependency="sql backend"}`.
- histograms: standard `_bucket{le=...}` / `_sum` / `_count` triplets
  with the model × replica × request-class label set.

The golden test (tests/test_prometheus.py) scrapes a live fake-backend
app and validates names/types/label sets with a minimal in-test parser —
no new dependency.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional

from .observability import HistogramSet

__all__ = ["render_prometheus", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_]")

#: JSON aggregate key -> (metric suffix, TYPE). Counters keep their
#: monotonic semantics; windowed percentiles are gauges by nature.
_MODEL_KEYS = {
    "requests": ("requests_total", "counter"),
    "output_tokens": ("output_tokens_total", "counter"),
    "p50_latency_s": ("p50_latency_seconds", "gauge"),
    "p95_latency_s": ("p95_latency_seconds", "gauge"),
    "avg_decode_tok_s": ("decode_tokens_per_second", "gauge"),
    "ttft_p50_s": ("ttft_p50_seconds", "gauge"),
    "ttft_p95_s": ("ttft_p95_seconds", "gauge"),
    "queue_wait_p50_s": ("queue_wait_p50_seconds", "gauge"),
    "queue_wait_p95_s": ("queue_wait_p95_seconds", "gauge"),
}


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _num(v) -> Optional[float]:
    """Numeric leaf or None (strings/None/lists stay JSON-only).
    bools render 0/1 — `busy`, breaker `open` flags."""
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)) and math.isfinite(v):
        return float(v)
    return None


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(v)


class _Emitter:
    """Groups samples by metric name so HELP/TYPE appear once and all
    samples of a name are contiguous (the exposition grammar)."""

    def __init__(self):
        self._order: List[str] = []
        self._meta: Dict[str, str] = {}
        self._samples: Dict[str, List[str]] = {}

    def add(self, name: str, labels: Dict[str, str], value: float,
            mtype: str = "gauge", suffix: str = "") -> None:
        name = _NAME_OK.sub("_", name)
        if name not in self._meta:
            self._order.append(name)
            self._meta[name] = mtype
            self._samples[name] = []
        self._samples[name].append(
            f"{name}{suffix}{_labels(labels)} {_fmt(value)}"
        )

    def render(self) -> str:
        out: List[str] = []
        for name in self._order:
            out.append(f"# HELP {name} lsot serving metric {name}")
            out.append(f"# TYPE {name} {self._meta[name]}")
            out.extend(self._samples[name])
        return "\n".join(out) + "\n"


def _flatten_serving(emit: _Emitter, model: str, prefix: str, node) -> None:
    """Nested serving stats -> gauges with `_`-joined names. List entries
    (e.g. per-replica heartbeat snapshots, pool load views) are labeled
    `replica` — the entry's own "replica" name when it carries one, else
    "r{i}" — the SAME vocabulary the histogram families use, so the two
    can be joined/grouped on the label."""
    if isinstance(node, dict):
        for k, v in node.items():
            _flatten_serving(emit, model, f"{prefix}_{k}", v)
        return
    if isinstance(node, list):
        for i, v in enumerate(node):
            if isinstance(v, dict):
                name = v.get("replica")
                rep = name if isinstance(name, str) and name else f"r{i}"
                for k, inner in v.items():
                    n = _num(inner)
                    if n is not None:
                        emit.add(_NAME_OK.sub("_", f"{prefix}_{k}"),
                                 {"model": model, "replica": rep}, n)
        return
    n = _num(node)
    if n is not None:
        emit.add(_NAME_OK.sub("_", prefix), {"model": model}, n)


def _emit_perf(emit: _Emitter, model: str, perf: Dict) -> None:
    """The roofline-ledger gauges (ISSUE 12): `serving.perf` becomes
    lsot_mfu / lsot_hbm_util / lsot_perf_compute_bound labeled
    model × replica × PHASE (prefill|decode|draft|verify) — the live
    per-replica prefill/decode hardware-asymmetry signal. Accepts one
    replica's ledger ({"replica", "phases"}) or a pool's
    ({"replicas": [...]})."""
    ledgers = perf.get("replicas") if isinstance(perf.get("replicas"),
                                                 list) else [perf]
    for led in ledgers:
        if not isinstance(led, dict):
            continue
        rep = led.get("replica") or "r0"
        for k in ("peak_tflops", "peak_hbm_gbs"):
            n = _num(led.get(k))
            if n is not None:
                emit.add(f"lsot_perf_{k}", {"model": model, "replica": rep},
                         n)
        for phase, ph in (led.get("phases") or {}).items():
            if not isinstance(ph, dict):
                continue
            labels = {"model": model, "replica": rep, "phase": str(phase)}
            for key, name in (("mfu", "lsot_mfu"),
                              ("hbm_util", "lsot_hbm_util"),
                              ("tflops", "lsot_perf_tflops"),
                              ("gbs", "lsot_perf_hbm_gbs"),
                              ("rounds", "lsot_perf_rounds")):
                n = _num(ph.get(key))
                if n is not None:
                    emit.add(name, labels, n,
                             "counter" if key == "rounds" else "gauge")
            if "bound" in ph:
                emit.add("lsot_perf_compute_bound", labels,
                         1.0 if ph["bound"] == "compute-bound" else 0.0)


def _emit_handoff(emit: _Emitter, model: str, ho: Dict) -> None:
    """The prefill→decode handoff families (ISSUE 13): `serving.handoff`
    becomes lsot_handoff_* counters labeled model × replica ×
    phase_role — exports/imports/in-place fallbacks, page and byte
    volume each way, and the summed wait for a decode slot (the
    between-legs latency a disaggregated deployment tunes). Accepts one
    replica's stats dict or a pool's ({"replicas": [...]})."""
    stats = ho.get("replicas") if isinstance(ho.get("replicas"),
                                             list) else [ho]
    for rec in stats:
        if not isinstance(rec, dict):
            continue
        labels = {"model": model,
                  "replica": str(rec.get("replica") or "r0"),
                  "phase_role": str(rec.get("phase_role") or "mixed")}
        for key, name, mtype in (
                ("exports", "lsot_handoff_exports_total", "counter"),
                ("imports", "lsot_handoff_imports_total", "counter"),
                ("inplace_fallbacks",
                 "lsot_handoff_inplace_fallbacks_total", "counter"),
                ("pages_out", "lsot_handoff_pages_out_total", "counter"),
                ("pages_in", "lsot_handoff_pages_in_total", "counter"),
                ("bytes_out", "lsot_handoff_bytes_out_total", "counter"),
                ("bytes_in", "lsot_handoff_bytes_in_total", "counter"),
                ("wait_s_sum", "lsot_handoff_wait_seconds_sum", "counter"),
                ("wait_count", "lsot_handoff_wait_count", "counter"),
                ("queued_handoffs", "lsot_handoff_queued", "gauge"),
        ):
            n = _num(rec.get(key))
            if n is not None:
                emit.add(name, labels, n, mtype)


def _emit_transport(emit: _Emitter, model: str, tr: Dict) -> None:
    """The replica-transport families (ISSUE 15): `serving.transport`
    becomes lsot_transport_* counters labeled model × replica ×
    ENDPOINT (the rpc op — submit/requeue/ping/…) for the per-call
    counters, and model × replica for the lease/connection lifecycle —
    rpc volume, retries, timeouts, errors, lease misses/expiries,
    reconnects, and the 0/1 unreachable flag a partition trips. Accepts
    one transport's stats dict or a pool's ({"replicas": [...]})."""
    stats = tr.get("replicas") if isinstance(tr.get("replicas"),
                                             list) else [tr]
    for rec in stats:
        if not isinstance(rec, dict):
            continue
        rep = str(rec.get("replica") or "r0")
        for op, counters in sorted((rec.get("endpoints") or {}).items()):
            if not isinstance(counters, dict):
                continue
            labels = {"model": model, "replica": rep, "endpoint": str(op)}
            for key, name in (("rpcs", "lsot_transport_rpcs_total"),
                              ("retries", "lsot_transport_retries_total"),
                              ("timeouts", "lsot_transport_timeouts_total"),
                              ("errors", "lsot_transport_errors_total")):
                n = _num(counters.get(key))
                if n is not None:
                    emit.add(name, labels, n, "counter")
        labels = {"model": model, "replica": rep,
                  "kind": str(rec.get("kind") or "transport")}
        for key, name, mtype in (
                ("lease_misses", "lsot_transport_lease_misses", "gauge"),
                ("lease_expiries",
                 "lsot_transport_lease_expiries_total", "counter"),
                ("reconnects", "lsot_transport_reconnects_total",
                 "counter"),
                ("unreachable", "lsot_transport_unreachable", "gauge"),
        ):
            n = _num(rec.get(key))
            if n is not None:
                emit.add(name, labels, n, mtype)


def _emit_prefix(emit: _Emitter, model: str, pv: Dict) -> None:
    """The prefix-cache telemetry families (ISSUE 14): `serving.prefix`
    becomes lsot_prefix_* counters/gauges labeled model × replica —
    hits/misses/evictions/ghost-reinsertions, reused tokens, the priced
    prefill seconds the hits saved, the live hit-rate EWMA, and what the
    cache currently holds (entries / tokens / device bytes). Accepts one
    replica's block or a pool's ({"replicas": [...]})."""
    stats = pv.get("replicas") if isinstance(pv.get("replicas"),
                                             list) else [pv]
    for rec in stats:
        if not isinstance(rec, dict):
            continue
        labels = {"model": model,
                  "replica": str(rec.get("replica") or "r0")}
        for key, name, mtype in (
                ("hits", "lsot_prefix_hits_total", "counter"),
                ("misses", "lsot_prefix_misses_total", "counter"),
                ("evictions", "lsot_prefix_evictions_total", "counter"),
                ("reinserts", "lsot_prefix_reinserts_total", "counter"),
                ("reused_tokens", "lsot_prefix_reused_tokens_total",
                 "counter"),
                ("blocks_reused", "lsot_prefix_blocks_reused_total",
                 "counter"),
                ("prefill_s_saved",
                 "lsot_prefix_saved_prefill_seconds_total", "counter"),
                ("hit_rate", "lsot_prefix_hit_rate", "gauge"),
                ("hit_rate_ewma", "lsot_prefix_hit_rate_ewma", "gauge"),
                ("resident_entries", "lsot_prefix_resident_entries",
                 "gauge"),
                ("resident_tokens", "lsot_prefix_resident_tokens",
                 "gauge"),
                ("resident_bytes", "lsot_prefix_resident_bytes", "gauge"),
        ):
            n = _num(rec.get(key))
            if n is not None:
                emit.add(name, labels, n, mtype)


def _emit_fleet(emit: _Emitter, model: str, fl: Dict) -> None:
    """The elastic-membership families (ISSUE 17): `serving.fleet`
    becomes lsot_fleet_* gauges/counters labeled model — live fleet
    size and serving/elastic counts, join/retire lifecycle totals, the
    drain-duration ledger scale-down rides, and the pushed-handoff
    pump's depth/bytes/latency (wire-receive → pool placement)."""
    labels = {"model": model}
    for key, name, mtype in (
            ("size", "lsot_fleet_size", "gauge"),
            ("serving", "lsot_fleet_serving", "gauge"),
            ("elastic", "lsot_fleet_elastic", "gauge"),
            ("joins", "lsot_fleet_joins_total", "counter"),
            ("retires", "lsot_fleet_retires_total", "counter"),
            ("drain_s_sum", "lsot_fleet_drain_seconds_sum", "counter"),
            ("drain_count", "lsot_fleet_drain_count", "counter"),
            ("pushed", "lsot_fleet_pushed_handoffs_total", "counter"),
            ("push_bytes", "lsot_fleet_pushed_handoff_bytes_total",
             "counter"),
            ("pump_depth", "lsot_fleet_pump_depth", "gauge"),
            ("push_placed", "lsot_fleet_push_placed_total", "counter"),
            ("push_place_p50_ms", "lsot_fleet_push_place_p50_ms",
             "gauge"),
            ("push_place_p95_ms", "lsot_fleet_push_place_p95_ms",
             "gauge"),
    ):
        n = _num(fl.get(key))
        if n is not None:
            emit.add(name, labels, n, mtype)


def _emit_models(emit: _Emitter, model: str, mv: Dict) -> None:
    """The multi-model fleet families (ISSUE 16): `serving.models`
    becomes lsot_model_* gauges/counters labeled model (the BACKEND
    whose stats block carried the view) × served_model (the co-resident
    checkpoint the row attributes to) — per-model queue depth, decode
    occupancy, throughput, and the partitioned KV-page arena each
    checkpoint holds. Only present on multi-model fleets: a
    single-model pool's stats omit the block entirely, keeping its
    /metrics byte-identical."""
    for rec in mv.get("models") or []:
        if not isinstance(rec, dict):
            continue
        labels = {"model": model,
                  "served_model": str(rec.get("model") or "")}
        for key, name, mtype in (
                ("replicas", "lsot_model_replicas", "gauge"),
                ("placeable", "lsot_model_placeable_replicas", "gauge"),
                ("queued", "lsot_model_queue_depth", "gauge"),
                ("active_slots", "lsot_model_active_slots", "gauge"),
                ("pending_new_tokens", "lsot_model_pending_new_tokens",
                 "gauge"),
                ("backlog_s", "lsot_model_backlog_seconds", "gauge"),
                ("placements", "lsot_model_placements_total", "counter"),
                ("tokens_total", "lsot_model_output_tokens_total",
                 "counter"),
                ("tok_s", "lsot_model_tokens_per_second", "gauge"),
                ("kv_pages_total", "lsot_model_kv_pages_total", "gauge"),
                ("kv_pages_in_use", "lsot_model_kv_pages_in_use",
                 "gauge"),
        ):
            n = _num(rec.get(key))
            if n is not None:
                emit.add(name, labels, n, mtype)


def _emit_slo(emit: _Emitter, slo: Dict) -> None:
    """The rolling-SLO families (ISSUE 12): per-replica + fleet quantile
    gauges, bad-fraction/burn-rate gauges per window arm, and the 0/1
    burning flag /readyz keys degraded off."""
    for m, obj in (slo.get("objectives") or {}).items():
        n = _num((obj or {}).get("threshold_s"))
        if n is not None:
            emit.add("lsot_slo_objective_seconds", {"metric": m}, n)
    views = [(r.get("replica") or "r0", r.get("metrics") or {})
             for r in slo.get("replicas") or [] if isinstance(r, dict)]
    views.append(("fleet", slo.get("fleet") or {}))
    for rep, metrics in views:
        for m, v in metrics.items():
            if not isinstance(v, dict):
                continue
            labels = {"metric": str(m), "replica": rep}
            for q in ("p50", "p90", "p99"):
                n = _num(v.get(q))
                if n is not None:
                    emit.add(f"lsot_slo_{q}_seconds", labels, n)
            n = _num(v.get("count"))
            if n is not None:
                emit.add("lsot_slo_observations", labels, n)
            for key, win in (("bad_frac", "long"),
                             ("bad_frac_short", "short")):
                n = _num(v.get(key))
                if n is not None:
                    emit.add("lsot_slo_bad_fraction",
                             {**labels, "window": win}, n)
            for key, win in (("burn_rate", "long"),
                             ("burn_rate_short", "short")):
                n = _num(v.get(key))
                if n is not None:
                    emit.add("lsot_slo_burn_rate",
                             {**labels, "window": win}, n)
            if "burning" in v:
                emit.add("lsot_slo_burning", labels,
                         1.0 if v["burning"] else 0.0)


def _emit_qos_admission(emit: _Emitter, qos: Dict) -> None:
    """The front-door lsot_tenant_* families (ISSUE 18): per-(tenant,
    class) admit/shed counters, cumulative shed wait, and live bucket
    levels. Labels are bounded upstream (top-K + "_other" fold in
    serve/qos.py), so a tenant-id flood cannot balloon the payload."""
    for key, name in (
            ("admitted", "lsot_tenant_admitted_total"),
            ("shed", "lsot_tenant_shed_total"),
            ("shed_wait_s", "lsot_tenant_shed_wait_seconds_total"),
    ):
        for label, v in (qos.get(key) or {}).items():
            tenant, sep, cls = str(label).rpartition("/")
            n = _num(v)
            if n is not None:
                emit.add(name,
                         {"tenant": tenant if sep else str(label),
                          "qos": cls if sep else ""},
                         n, "counter")
    for label, v in (qos.get("bucket_level") or {}).items():
        tenant, sep, cls = str(label).rpartition("/")
        n = _num(v)
        if n is not None:
            emit.add("lsot_tenant_bucket_level",
                     {"tenant": tenant if sep else str(label),
                      "qos": cls if sep else ""}, n)


def _emit_repair(emit: _Emitter, rep: Dict) -> None:
    """The self-healing-SQL lsot_repair_* families (ISSUE 20). Label
    cardinality is bounded by construction: the only labeled family is
    lsot_repair_errors_total{class=...}, whose classes come from the
    fixed five-value taxonomy (app/repair.REPAIR_CLASSES); the "recent"
    flight rows are /metrics JSON only and never become series."""
    for key, name in (
            ("repair_rounds", "lsot_repair_rounds_total"),
            ("repaired", "lsot_repair_repaired_total"),
            ("unrepairable", "lsot_repair_unrepairable_total"),
            ("breaker_skips", "lsot_repair_breaker_skips_total"),
            ("deadline_stops", "lsot_repair_deadline_stops_total"),
    ):
        n = _num(rep.get(key))
        if n is not None:
            emit.add(name, {}, n, "counter")
    for key, v in rep.items():
        if not key.startswith("diagnosed_"):
            continue
        n = _num(v)
        if n is not None:
            emit.add("lsot_repair_errors_total",
                     {"class": key[len("diagnosed_"):]}, n, "counter")


def _emit_qos_sched(emit: _Emitter, model: str, qv: Dict) -> None:
    """Scheduler-side WFQ view (ISSUE 18): per-replica virtual time and
    ready/page-wait depths, plus per-tenant submitted/preempted/
    quarantined counters — first-class families on the shared model ×
    replica × tenant vocabulary instead of path-flattened names (tenant
    ids must be label VALUES, never metric names)."""
    reps = qv.get("replicas")
    if isinstance(reps, list):
        views = [(str(r.get("replica") or f"r{i}"), r)
                 for i, r in enumerate(reps) if isinstance(r, dict)]
    else:
        views = [("r0", qv)]
    for rep, v in views:
        labels = {"model": model, "replica": rep}
        for key, name in (
                ("virtual_time", "lsot_qos_virtual_time"),
                ("ready", "lsot_qos_ready_depth"),
                ("page_wait", "lsot_qos_page_wait_depth"),
        ):
            n = _num(v.get(key))
            if n is not None:
                emit.add(name, labels, n)
        for key, name, mtype in (
                ("submitted", "lsot_tenant_submitted_total", "counter"),
                ("preempted", "lsot_tenant_preempted_total", "counter"),
                ("weights", "lsot_tenant_weight", "gauge"),
                ("backlog", "lsot_tenant_backlog", "gauge"),
        ):
            d = v.get(key)
            if not isinstance(d, dict):
                continue
            for tenant, cnt in d.items():
                n = _num(cnt)
                if n is not None:
                    emit.add(name, {**labels, "tenant": str(tenant)},
                             n, mtype)
    q = qv.get("quarantined")
    if isinstance(q, dict):
        for tenant, cnt in q.items():
            n = _num(cnt)
            if n is not None:
                emit.add("lsot_tenant_quarantined_total",
                         {"model": model, "tenant": str(tenant)},
                         n, "counter")


def render_prometheus(snapshot: Dict,
                      histograms: Optional[HistogramSet] = None) -> str:
    """Render `GenerationService.metrics_snapshot()` (+ the registry's
    histogram set) as Prometheus exposition text."""
    emit = _Emitter()
    resilience = snapshot.get("resilience") or {}
    for model, agg in snapshot.items():
        if model in ("resilience", "slo", "qos", "repair") \
                or not isinstance(agg, dict):
            continue
        for key, (suffix, mtype) in _MODEL_KEYS.items():
            n = _num(agg.get(key))
            if n is not None:
                emit.add(f"lsot_{suffix}", {"model": model}, n, mtype)
        serving = agg.get("serving")
        if isinstance(serving, dict):
            # The roofline ledger renders as first-class phase × replica
            # gauges (not path-flattened serving gauges) so dashboards
            # join lsot_mfu/lsot_hbm_util on the same label vocabulary
            # as the latency histograms.
            serving = dict(serving)
            perf = serving.pop("perf", None)
            if isinstance(perf, dict):
                _emit_perf(emit, model, perf)
            # Handoff traffic renders as first-class replica × phase_role
            # families (not path-flattened gauges) so dashboards join
            # lsot_handoff_* on the same label vocabulary as lsot_mfu.
            ho = serving.pop("handoff", None)
            if isinstance(ho, dict):
                _emit_handoff(emit, model, ho)
            # Replica-transport traffic renders as first-class
            # replica × endpoint families (ISSUE 15) so dashboards join
            # lsot_transport_* on the shared replica vocabulary.
            tr = serving.pop("transport", None)
            if isinstance(tr, dict):
                _emit_transport(emit, model, tr)
            # Prefix-cache telemetry renders as first-class
            # model × replica families (not path-flattened gauges) so
            # dashboards join lsot_prefix_* on the same label vocabulary
            # as lsot_mfu / the latency histograms. The flat
            # serving.prefix_cache sums keep their historical
            # lsot_serving_prefix_cache_* names below.
            pv = serving.pop("prefix", None)
            if isinstance(pv, dict):
                _emit_prefix(emit, model, pv)
            # Multi-model fleet stats render as first-class
            # model × served_model families (ISSUE 16) so dashboards
            # split queue depth / tok/s / KV pages by co-resident
            # checkpoint.
            mv = serving.pop("models", None)
            if isinstance(mv, dict):
                _emit_models(emit, model, mv)
            # Elastic-membership stats render as first-class model-level
            # families (ISSUE 17) so dashboards watch fleet size /
            # join-retire churn / pushed-handoff latency directly.
            fl = serving.pop("fleet", None)
            if isinstance(fl, dict):
                _emit_fleet(emit, model, fl)
            # WFQ/tenant scheduler stats render as first-class model ×
            # replica × tenant families (ISSUE 18): tenant ids must be
            # label values, never path-flattened metric names.
            qv = serving.pop("qos", None)
            if isinstance(qv, dict):
                _emit_qos_sched(emit, model, qv)
            _flatten_serving(emit, model, "lsot_serving", serving)
    if resilience:
        breakers = resilience.get("breakers") or {}
        for event, count in resilience.items():
            n = _num(count)
            if n is not None:
                emit.add("lsot_resilience_events_total", {"event": event},
                         n, "counter")
        for dep, state in breakers.items():
            if isinstance(state, dict):
                is_open = state.get("state") == "open"
                fails = _num(state.get("failures"))
            else:
                is_open = state == "open"
                fails = None
            emit.add("lsot_breaker_open", {"dependency": dep},
                     1.0 if is_open else 0.0)
            if fails is not None:
                emit.add("lsot_breaker_failures", {"dependency": dep}, fails)
    slo = snapshot.get("slo")
    if isinstance(slo, dict):
        _emit_slo(emit, slo)
    qos = snapshot.get("qos")
    if isinstance(qos, dict):
        _emit_qos_admission(emit, qos)
    rep = snapshot.get("repair")
    if isinstance(rep, dict):
        _emit_repair(emit, rep)
    if histograms is not None:
        for name, series in sorted(histograms.snapshot().items()):
            name = _NAME_OK.sub("_", name)
            for s in series:
                labels = dict(s.get("labels", {}))
                for le, c in s["buckets"].items():
                    emit.add(name, {**labels, "le": _fmt(float(le))},
                             c, "histogram", suffix="_bucket")
                emit.add(name, {**labels, "le": "+Inf"}, s["count"],
                         "histogram", suffix="_bucket")
                emit.add(name, labels, s["sum"], "histogram", suffix="_sum")
                emit.add(name, labels, s["count"], "histogram",
                         suffix="_count")
    return emit.render()
