"""Observability: per-request metrics, stage timers, and profiler capture.

The reference's only instruments are `print()` statements and one wall-clock
bracket in its eval harness (SURVEY.md §5 "Tracing/profiling",
`Model_Evaluation_&_Comparision.py:42-44`). Here the serving stack gets real
counters:

- `StageTimer` — wall-clock spans around pipeline stages (prefill vs decode,
  SQL exec, persistence), cheap enough to always be on.
- `RequestMetrics` / `MetricsRegistry` — per-request records (prompt/output
  tokens, decode tok/s, end-to-end latency) with process-lifetime aggregates
  (count, p50/p95 latency, aggregate tok/s), surfaced by the app's
  `/metrics` endpoint and printed by the bench harness.
- `trace_capture` — `jax.profiler` trace of a code region, gated behind the
  LSOT_TRACE_DIR env var: zero overhead when unset, a TensorBoard-loadable
  trace directory when set.

Everything is thread-safe: the serving layer calls this from request
threads and the continuous-batching scheduler loop alike.
"""

from __future__ import annotations

import bisect
import contextlib
import dataclasses
import json
import logging
import os
import random
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

log = logging.getLogger("lsot.metrics")


#: App-startup override (AppConfig.request_log → reconfigure_request_log);
#: None falls through to the LSOT_REQUEST_LOG env read below.
_LOG_SAMPLE_OVERRIDE: Optional[float] = None


def _request_log_sample() -> float:
    """LSOT_REQUEST_LOG: fraction of requests whose JSON log line is
    emitted (default 1.0 = all, 0 disables). The line used to be
    unconditional — string-formatting + I/O per request at high QPS."""
    if _LOG_SAMPLE_OVERRIDE is not None:
        return _LOG_SAMPLE_OVERRIDE
    try:
        return min(1.0, max(0.0, float(
            os.environ.get("LSOT_REQUEST_LOG", "1") or 0.0
        )))
    except ValueError:
        return 1.0


def reconfigure_request_log(sample: float) -> None:
    """App-startup wiring seam (AppConfig.request_log): set the log-line
    sampling fraction for registries constructed after this call AND for
    the module-level `registry` — so `AppConfig(request_log=0.0)` is
    honored, not a silent no-op."""
    global _LOG_SAMPLE_OVERRIDE
    _LOG_SAMPLE_OVERRIDE = min(1.0, max(0.0, float(sample)))
    registry._log_sample = _LOG_SAMPLE_OVERRIDE


class StageTimer:
    """Accumulates named wall-clock spans: `with timer.stage("prefill"): ...`.

    Re-entering a stage name accumulates (decode chunks sum into one
    "decode" figure)."""

    def __init__(self):
        self._spans: Dict[str, float] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._spans[name] = self._spans.get(name, 0.0) + dt

    @property
    def spans(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._spans)


@dataclasses.dataclass
class RequestMetrics:
    model: str
    prompt_tokens: int
    output_tokens: int
    latency_s: float
    # This request's share of *distinct* wall-clock. For a request served in
    # a batch of B, latency_s is the batch wall (what the caller truly
    # waited) while wall_share_s is wall/B — aggregate tok/s must divide by
    # distinct time, not by the same wall counted B times (mirrors
    # evalh.ModelReport.wall_clock_s). 0.0 means "same as latency_s"
    # (sequential request).
    wall_share_s: float = 0.0
    # Time to first token (submit -> first accepted token harvested), the
    # metric streaming exists for. 0.0 = not measured (backends without a
    # first-token seam: the one-XLA-program engine, fakes).
    ttft_s: float = 0.0
    # Queue wait (submit -> slot admission) on the scheduler path: the
    # share of latency that is BACKLOG, not compute. 0.0 = not measured.
    queue_wait_s: float = 0.0
    # Request class for the histogram label set: "" (plain), or any of
    # "constrained"/"speculative"/"constrained+speculative" — the classes
    # whose latency profiles an operator prices separately.
    rclass: str = ""
    # Which scheduler replica served it (SchedulerPool attribution);
    # "" when there is no replica notion (engine, fakes).
    replica: str = ""
    # Trace-correlation handle (utils/tracing.py): echoed in the request
    # log line so a log line and an exported trace join on one id.
    request_id: str = ""
    stages: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def distinct_wall_s(self) -> float:
        return self.wall_share_s or self.latency_s

    @property
    def decode_tok_s(self) -> float:
        decode = self.stages.get("decode")
        span = decode if decode else self.latency_s
        return self.output_tokens / span if span > 0 else 0.0

    def to_dict(self) -> Dict:
        out = {
            "model": self.model,
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "latency_s": round(self.latency_s, 4),
            "decode_tok_s": round(self.decode_tok_s, 2),
            "stages": {k: round(v, 4) for k, v in self.stages.items()},
        }
        if self.ttft_s:
            out["ttft_s"] = round(self.ttft_s, 4)
        if self.queue_wait_s:
            out["queue_wait_s"] = round(self.queue_wait_s, 4)
        if self.rclass:
            out["class"] = self.rclass
        if self.replica:
            out["replica"] = self.replica
        if self.request_id:
            out["request_id"] = self.request_id
        return out

    @property
    def tpot_s(self) -> float:
        """Time per output token AFTER the first (the streaming cadence
        metric): (latency - ttft) / (n - 1). Falls back to latency/n when
        no TTFT was measured; 0.0 when nothing decoded."""
        if self.output_tokens <= 0:
            return 0.0
        if self.ttft_s and self.output_tokens > 1:
            return max(0.0, self.latency_s - self.ttft_s) / (
                self.output_tokens - 1
            )
        return self.latency_s / self.output_tokens


#: Fixed latency buckets (seconds) shared by the TTFT/TPOT/queue-wait/
#: latency histograms: Prometheus-style cumulative `le` bounds spanning
#: sub-ms CPU fakes to minute-long chip decodes. FIXED (not windowed
#: percentiles) on purpose — histograms aggregate across scrapes and
#: replicas; percentiles don't.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Prometheus-shaped cumulative histogram: fixed `le` buckets +
    sum + count. Thread-safe; observe() is a bisect + increments."""

    __slots__ = ("_lock", "buckets", "_counts", "_sum", "_count")

    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS_S):
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> Dict[str, object]:
        """Cumulative counts per upper bound (Prometheus `le` semantics:
        bucket[le] counts observations <= le, ending at +Inf == count)."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, out = 0, {}
        for le, c in zip(self.buckets, counts):
            cum += c
            out[le] = cum
        return {"buckets": out, "sum": s, "count": total}


class HistogramSet:
    """Named histograms keyed by a label tuple — the exposition feed for
    `/metrics?format=prometheus`. Keys are (name, ((label, value), ...))
    so one set holds e.g. lsot_ttft_seconds across model × replica ×
    request-class without pre-registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Histogram] = {}

    def observe(self, name: str, value: float, **labels: str) -> None:
        key = (name, tuple(sorted((k, str(v)) for k, v in labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram()
        h.observe(value)

    def snapshot(self) -> Dict[str, List[Dict]]:
        """{name: [{labels: {...}, buckets/sum/count}, ...]} — the shape
        utils/prometheus.py renders."""
        with self._lock:
            items = list(self._hists.items())
        out: Dict[str, List[Dict]] = {}
        for (name, labels), h in items:
            out.setdefault(name, []).append(
                {"labels": dict(labels), **h.snapshot()}
            )
        return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class MetricsRegistry:
    """Process-lifetime request aggregates, keyed by model name.

    Bounded memory: keeps only the last `window` per-request records per
    model for percentiles; counters are exact over the full lifetime.
    """

    def __init__(self, window: int = 1024,
                 request_log_sample: Optional[float] = None):
        self._window = window
        self._lock = threading.Lock()
        self._recent: Dict[str, List[RequestMetrics]] = {}
        self._count: Dict[str, int] = {}
        self._tokens: Dict[str, int] = {}
        self._time: Dict[str, float] = {}
        # Fixed-bucket histograms beside the windowed percentiles:
        # histograms AGGREGATE (across scrapes, replicas, processes) where
        # a windowed p95 cannot — the Prometheus exposition renders these.
        self.histograms = HistogramSet()
        # Per-request log-line sampling (LSOT_REQUEST_LOG; satellite of
        # ISSUE 6): the JSON line was emitted unconditionally at INFO,
        # paying json.dumps + handler I/O per request at high QPS even
        # when nobody was reading it.
        self._log_sample = (request_log_sample if request_log_sample
                            is not None else _request_log_sample())
        self._log_rng = random.Random(0)

    def record(self, m: RequestMetrics) -> None:
        with self._lock:
            recent = self._recent.setdefault(m.model, [])
            recent.append(m)
            if len(recent) > self._window:
                del recent[: len(recent) - self._window]
            self._count[m.model] = self._count.get(m.model, 0) + 1
            self._tokens[m.model] = self._tokens.get(m.model, 0) + m.output_tokens
            self._time[m.model] = self._time.get(m.model, 0.0) + m.distinct_wall_s
        # "r0" matches the single-scheduler flight-recorder default and
        # the pool's "r{i}" scheme: one replica-label vocabulary across
        # the histogram and serving-gauge families.
        labels = {"model": m.model, "replica": m.replica or "r0",
                  "class": m.rclass or "plain"}
        self.histograms.observe("lsot_request_latency_seconds",
                                m.latency_s, **labels)
        # TPOT is the post-first-token cadence: undefined for a 1-token
        # completion, where the latency/n fallback would record the FULL
        # request latency (queue + prefill + TTFT) as a "per token" time
        # and skew the histogram's tail by orders of magnitude.
        if m.output_tokens > 1:
            self.histograms.observe("lsot_tpot_seconds", m.tpot_s, **labels)
        if m.ttft_s:
            self.histograms.observe("lsot_ttft_seconds", m.ttft_s, **labels)
        if m.queue_wait_s:
            self.histograms.observe("lsot_queue_wait_seconds",
                                    m.queue_wait_s, **labels)
        # Rolling SLO engine (utils/slo.py): the same TTFT/TPOT/queue-wait
        # observations feed the windowed burn-rate sketches, per replica.
        # Lazy import (slo imports this module's bucket bounds) and gated
        # on `enabled`, so the no-objective hot path pays one attribute
        # read.
        from . import slo as _slo

        eng = _slo.ENGINE
        if eng.enabled:
            rep = m.replica or "r0"
            if m.ttft_s:
                eng.observe("ttft", m.ttft_s, replica=rep)
            if m.output_tokens > 1:
                eng.observe("tpot", m.tpot_s, replica=rep)
            if m.queue_wait_s:
                eng.observe("queue_wait", m.queue_wait_s, replica=rep)
        # Level check BEFORE the json.dumps (the formatting was the cost,
        # not the logging call), then the sampling knob.
        if self._log_sample > 0.0 and log.isEnabledFor(logging.INFO):
            if self._log_sample >= 1.0 or \
                    self._log_rng.random() < self._log_sample:
                log.info("request %s", json.dumps(m.to_dict()))

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            out = {}
            for model, recent in self._recent.items():
                lats = sorted(r.latency_s for r in recent)
                toks = sum(r.output_tokens for r in recent)
                # Distinct wall-clock: batch members contribute wall/B each,
                # so batched throughput isn't understated by ~batch_size.
                span = sum(r.distinct_wall_s for r in recent)
                out[model] = {
                    "requests": self._count[model],
                    "output_tokens": self._tokens[model],
                    "p50_latency_s": round(_percentile(lats, 0.50), 4),
                    "p95_latency_s": round(_percentile(lats, 0.95), 4),
                    "avg_decode_tok_s": round(toks / span, 2) if span else 0.0,
                }
                # TTFT percentiles over the requests that measured one
                # (scheduler-path requests; the single-program engine has
                # no first-token seam and reports none).
                ttfts = sorted(r.ttft_s for r in recent if r.ttft_s)
                if ttfts:
                    out[model]["ttft_p50_s"] = round(_percentile(ttfts, 0.50), 4)
                    out[model]["ttft_p95_s"] = round(_percentile(ttfts, 0.95), 4)
                # Queue-wait percentiles (scheduler-path requests): how
                # much of the latency was backlog, not compute.
                qws = sorted(r.queue_wait_s for r in recent if r.queue_wait_s)
                if qws:
                    out[model]["queue_wait_p50_s"] = round(
                        _percentile(qws, 0.50), 4)
                    out[model]["queue_wait_p95_s"] = round(
                        _percentile(qws, 0.95), 4)
            return out


# Default process-wide registry the serving layer records into.
registry = MetricsRegistry()


class CounterSet:
    """Named monotonic counters (thread-safe) for low-cardinality event
    counts the per-request registry cannot express: retries, sheds,
    breaker trips, injected faults. Snapshot is a plain dict for the
    /metrics payload."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


#: Process-wide fault-tolerance counters (serve/resilience.py writes them:
#: retries/retry_giveups, shed, deadline_expired, breaker_trips/
#: breaker_open_shed/breaker_closes, faults_injected) — merged into the
#: /metrics payload by GenerationService.metrics_snapshot.
resilience = CounterSet()

#: Process-wide self-healing-SQL counters (app/repair.py writes them:
#: repair_rounds, repaired, unrepairable, breaker_skips, deadline_stops,
#: plus one diagnosed_<class> counter per taxonomy class — a FIXED
#: five-entry vocabulary, so cardinality is bounded by construction) —
#: merged into the /metrics payload under the reserved "repair" key by
#: GenerationService.metrics_snapshot and rendered as the lsot_repair_*
#: Prometheus families.
repair = CounterSet()


@contextlib.contextmanager
def trace_capture(name: str = "lsot") -> Iterator[None]:
    """jax.profiler trace of the enclosed region when LSOT_TRACE_DIR is set.

    The resulting directory loads in TensorBoard/XProf and shows XLA op
    timelines on the TPU — the profiling story SURVEY.md §5 requires.
    """
    trace_dir = os.environ.get("LSOT_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, name)):
        yield
