"""Observability: per-request metrics, stage timers, and profiler capture.

The reference's only instruments are `print()` statements and one wall-clock
bracket in its eval harness (SURVEY.md §5 "Tracing/profiling",
`Model_Evaluation_&_Comparision.py:42-44`). Here the serving stack gets real
counters:

- `StageTimer` — wall-clock spans around pipeline stages (prefill vs decode,
  SQL exec, persistence), cheap enough to always be on.
- `RequestMetrics` / `MetricsRegistry` — per-request records (prompt/output
  tokens, decode tok/s, end-to-end latency) with process-lifetime aggregates
  (count, p50/p95 latency, aggregate tok/s), surfaced by the app's
  `/metrics` endpoint and printed by the bench harness.
- `trace_capture` — `jax.profiler` trace of a code region, gated behind the
  LSOT_TRACE_DIR env var: zero overhead when unset, a TensorBoard-loadable
  trace directory when set.

Everything is thread-safe: the serving layer calls this from request
threads and the continuous-batching scheduler loop alike.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import threading
import time
from typing import Dict, Iterator, List, Optional

log = logging.getLogger("lsot.metrics")


class StageTimer:
    """Accumulates named wall-clock spans: `with timer.stage("prefill"): ...`.

    Re-entering a stage name accumulates (decode chunks sum into one
    "decode" figure)."""

    def __init__(self):
        self._spans: Dict[str, float] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._spans[name] = self._spans.get(name, 0.0) + dt

    @property
    def spans(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._spans)


@dataclasses.dataclass
class RequestMetrics:
    model: str
    prompt_tokens: int
    output_tokens: int
    latency_s: float
    # This request's share of *distinct* wall-clock. For a request served in
    # a batch of B, latency_s is the batch wall (what the caller truly
    # waited) while wall_share_s is wall/B — aggregate tok/s must divide by
    # distinct time, not by the same wall counted B times (mirrors
    # evalh.ModelReport.wall_clock_s). 0.0 means "same as latency_s"
    # (sequential request).
    wall_share_s: float = 0.0
    # Time to first token (submit -> first accepted token harvested), the
    # metric streaming exists for. 0.0 = not measured (backends without a
    # first-token seam: the one-XLA-program engine, fakes).
    ttft_s: float = 0.0
    stages: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def distinct_wall_s(self) -> float:
        return self.wall_share_s or self.latency_s

    @property
    def decode_tok_s(self) -> float:
        decode = self.stages.get("decode")
        span = decode if decode else self.latency_s
        return self.output_tokens / span if span > 0 else 0.0

    def to_dict(self) -> Dict:
        out = {
            "model": self.model,
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "latency_s": round(self.latency_s, 4),
            "decode_tok_s": round(self.decode_tok_s, 2),
            "stages": {k: round(v, 4) for k, v in self.stages.items()},
        }
        if self.ttft_s:
            out["ttft_s"] = round(self.ttft_s, 4)
        return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class MetricsRegistry:
    """Process-lifetime request aggregates, keyed by model name.

    Bounded memory: keeps only the last `window` per-request records per
    model for percentiles; counters are exact over the full lifetime.
    """

    def __init__(self, window: int = 1024):
        self._window = window
        self._lock = threading.Lock()
        self._recent: Dict[str, List[RequestMetrics]] = {}
        self._count: Dict[str, int] = {}
        self._tokens: Dict[str, int] = {}
        self._time: Dict[str, float] = {}

    def record(self, m: RequestMetrics) -> None:
        with self._lock:
            recent = self._recent.setdefault(m.model, [])
            recent.append(m)
            if len(recent) > self._window:
                del recent[: len(recent) - self._window]
            self._count[m.model] = self._count.get(m.model, 0) + 1
            self._tokens[m.model] = self._tokens.get(m.model, 0) + m.output_tokens
            self._time[m.model] = self._time.get(m.model, 0.0) + m.distinct_wall_s
        log.info("request %s", json.dumps(m.to_dict()))

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            out = {}
            for model, recent in self._recent.items():
                lats = sorted(r.latency_s for r in recent)
                toks = sum(r.output_tokens for r in recent)
                # Distinct wall-clock: batch members contribute wall/B each,
                # so batched throughput isn't understated by ~batch_size.
                span = sum(r.distinct_wall_s for r in recent)
                out[model] = {
                    "requests": self._count[model],
                    "output_tokens": self._tokens[model],
                    "p50_latency_s": round(_percentile(lats, 0.50), 4),
                    "p95_latency_s": round(_percentile(lats, 0.95), 4),
                    "avg_decode_tok_s": round(toks / span, 2) if span else 0.0,
                }
                # TTFT percentiles over the requests that measured one
                # (scheduler-path requests; the single-program engine has
                # no first-token seam and reports none).
                ttfts = sorted(r.ttft_s for r in recent if r.ttft_s)
                if ttfts:
                    out[model]["ttft_p50_s"] = round(_percentile(ttfts, 0.50), 4)
                    out[model]["ttft_p95_s"] = round(_percentile(ttfts, 0.95), 4)
            return out


# Default process-wide registry the serving layer records into.
registry = MetricsRegistry()


class CounterSet:
    """Named monotonic counters (thread-safe) for low-cardinality event
    counts the per-request registry cannot express: retries, sheds,
    breaker trips, injected faults. Snapshot is a plain dict for the
    /metrics payload."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + n

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


#: Process-wide fault-tolerance counters (serve/resilience.py writes them:
#: retries/retry_giveups, shed, deadline_expired, breaker_trips/
#: breaker_open_shed/breaker_closes, faults_injected) — merged into the
#: /metrics payload by GenerationService.metrics_snapshot.
resilience = CounterSet()


@contextlib.contextmanager
def trace_capture(name: str = "lsot") -> Iterator[None]:
    """jax.profiler trace of the enclosed region when LSOT_TRACE_DIR is set.

    The resulting directory loads in TensorBoard/XProf and shows XLA op
    timelines on the TPU — the profiling story SURVEY.md §5 requires.
    """
    trace_dir = os.environ.get("LSOT_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, name)):
        yield
