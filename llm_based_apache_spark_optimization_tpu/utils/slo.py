"""Rolling SLO engine: streaming quantile sketches + multi-window burn rates.

The histograms in `utils/observability.py` aggregate over the process
LIFETIME — good for dashboards, useless for "are we violating the latency
objective RIGHT NOW": an hour of healthy traffic drowns five bad minutes.
This module is the serving stack's live SLO view:

- `QuantileSketch` — a fixed-bucket cumulative sketch (same
  `LATENCY_BUCKETS_S` bounds as the Prometheus histograms): `observe` is
  a bisect + increments, `quantile(q)` answers within one bucket's width
  (it returns the upper bound of the bucket holding rank ceil(q·n) — the
  documented error bound the tests pin), and sketches MERGE by
  elementwise addition, so per-replica sketches roll up to a fleet view
  losslessly (the property windowed percentiles fundamentally lack).
- `SLOEngine` — rolling time-sliced windows over TTFT / TPOT /
  queue-wait, evaluated against configured objectives
  (`LSOT_SLO_TTFT_MS` / `LSOT_SLO_TPOT_MS` / `LSOT_SLO_QUEUE_WAIT_MS`,
  window `LSOT_SLO_WINDOW_S`, good-fraction target `LSOT_SLO_TARGET`)
  with MULTI-WINDOW burn rates (the SRE alerting recipe): the burn rate
  is (bad fraction) / (error budget); `burning` requires BOTH the long
  window and the short window (window/12, the fast-detect arm) above
  1.0 — a long-window burn alone is "warning" (old incident, already
  recovering), a short-window spike alone is noise that has not yet
  consumed real budget. Per-replica state rides the same engine
  (observations carry the shared r{i} label vocabulary), so
  `health()` can mark exactly the burning replica degraded and the
  pool's placement view can route around it.

One process-wide `ENGINE` (like `observability.registry`), fed by
`MetricsRegistry.record` and reconfigured at app startup from AppConfig.
Zero overhead when no objective is configured: `enabled` is False and
the registry skips the calls.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .observability import LATENCY_BUCKETS_S

__all__ = ["ENGINE", "QuantileSketch", "SLOEngine", "reconfigure"]

#: Metrics the engine tracks (seconds; the knob names are milliseconds
#: because operators think in ms for these).
METRICS = ("ttft", "tpot", "queue_wait")


class QuantileSketch:
    """Fixed-bucket cumulative quantile sketch, mergeable across
    replicas/windows. Bucket-error bound: `quantile(q)` returns the
    upper bound of the bucket containing rank ceil(q·count), so the
    true q-quantile lies within that bucket (exact ≤ answer, and answer
    is the tightest bound the bucketing can give). Values past the last
    bound report the last bound (documented saturation — pick bounds
    that cover the objective)."""

    __slots__ = ("bounds", "counts", "count", "sum")

    def __init__(self, bounds: Sequence[float] = LATENCY_BUCKETS_S):
        self.bounds: Tuple[float, ...] = tuple(sorted(bounds))
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # +inf last
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        if other.bounds != self.bounds:
            raise ValueError("cannot merge sketches with different bounds")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum += other.sum
        return self

    def quantile(self, q: float) -> float:
        """Upper bucket bound holding rank ceil(q·count); 0.0 empty."""
        if self.count <= 0:
            return 0.0
        rank = min(self.count, max(1, -int(-q * self.count // 1)))  # ceil
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return self.bounds[i] if i < len(self.bounds) \
                    else self.bounds[-1]
        return self.bounds[-1]

    def frac_over(self, threshold: float) -> float:
        """Fraction of observations STRICTLY over `threshold` (bucket
        resolution: counts every bucket whose lower range lies above it —
        exact when the threshold is a bucket bound, which is why the
        engine snaps objectives onto bounds at construction)."""
        if self.count <= 0:
            return 0.0
        idx = bisect.bisect_left(self.bounds, threshold)
        # buckets [0, idx] hold values <= bounds[idx] >= threshold when
        # threshold is a bound; everything after is over.
        over = sum(self.counts[idx + 1:]) if idx < len(self.bounds) \
            else self.counts[-1]
        return over / self.count

    def snapshot(self) -> Dict[str, object]:
        return {"count": self.count, "sum": round(self.sum, 6),
                "p50": self.quantile(0.50), "p90": self.quantile(0.90),
                "p99": self.quantile(0.99)}


class _Rolling:
    """Time-sliced ring of sketches: the window is `slices` equal
    sub-spans, observations land in the current slice, and a window
    query merges the slices young enough — O(slices) memory, no
    timestamps stored per observation."""

    def __init__(self, window_s: float, slices: int,
                 bounds: Sequence[float],
                 time_fn: Callable[[], float]):
        self.window_s = float(window_s)
        self.slices = max(1, int(slices))
        self.slice_s = self.window_s / self.slices
        self.bounds = tuple(bounds)
        self._time = time_fn
        # slot index -> (epoch, sketch); epoch = int(now / slice_s).
        self._ring: List[Optional[Tuple[int, QuantileSketch]]] = \
            [None] * self.slices

    def _slot(self, epoch: int) -> QuantileSketch:
        i = epoch % self.slices
        cur = self._ring[i]
        if cur is None or cur[0] != epoch:
            sk = QuantileSketch(self.bounds)
            self._ring[i] = (epoch, sk)
            return sk
        return cur[1]

    def observe(self, v: float) -> None:
        self._slot(int(self._time() / self.slice_s)).observe(v)

    def merged(self, window_s: Optional[float] = None) -> QuantileSketch:
        """Union sketch of the slices inside `window_s` (default: the
        full window)."""
        now_epoch = int(self._time() / self.slice_s)
        n = self.slices if window_s is None else max(
            1, min(self.slices, int(round(window_s / self.slice_s)))
        )
        out = QuantileSketch(self.bounds)
        for entry in self._ring:
            if entry is not None and now_epoch - entry[0] < n:
                out.merge(entry[1])
        return out


class SLOEngine:
    """Rolling objectives over TTFT/TPOT/queue-wait, per replica.

    Objectives are seconds thresholds (0 disables a metric's objective;
    its sketch still records, so /debug/slo shows quantiles even before
    an objective is configured). `target` is the good fraction (0.99 =
    1% error budget); burn rate = bad_frac / (1 - target). A replica is
    BURNING when both the long and the short (window/12) burn rates of
    any objective exceed 1.0; one of the two alone is a warning."""

    #: Short window divisor (the SRE multi-window fast arm).
    SHORT_DIV = 12

    def __init__(self, *, ttft_ms: float = 0.0, tpot_ms: float = 0.0,
                 queue_wait_ms: float = 0.0, window_s: float = 300.0,
                 target: float = 0.99, slices: int = 12,
                 bounds: Sequence[float] = LATENCY_BUCKETS_S,
                 time_fn: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._time = time_fn
        self.window_s = max(1.0, float(window_s))
        self.slices = max(self.SHORT_DIV, int(slices))
        self.target = min(0.9999, max(0.5, float(target)))
        bounds = tuple(sorted(bounds))
        self.objectives: Dict[str, float] = {}
        for metric, ms in (("ttft", ttft_ms), ("tpot", tpot_ms),
                           ("queue_wait", queue_wait_ms)):
            if ms and ms > 0:
                # Snap the threshold UP onto a sketch bound so frac_over
                # is exact at bucket resolution (never flags a value the
                # operator's threshold would not have).
                thr = ms / 1000.0
                i = bisect.bisect_left(bounds, thr)
                self.objectives[metric] = (bounds[i] if i < len(bounds)
                                           else bounds[-1])
        self._bounds = bounds
        # (metric, replica) -> _Rolling.
        self._rolling: Dict[Tuple[str, str], _Rolling] = {}

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target

    # ------------------------------------------------------------ feeding

    def observe(self, metric: str, seconds: float,
                replica: str = "r0") -> None:
        if metric not in METRICS:
            return
        key = (metric, replica or "r0")
        # The whole observe runs under the engine lock: ring rotation and
        # the sketch's counter increments are read-modify-writes, and
        # concurrent request-completion threads would otherwise drop or
        # miscount observations under exactly the high-QPS conditions the
        # engine exists to measure. One uncontended lock per request
        # TERMINAL (not per round) — far off the decode hot path.
        with self._lock:
            roll = self._rolling.get(key)
            if roll is None:
                roll = self._rolling[key] = _Rolling(
                    self.window_s, self.slices, self._bounds, self._time
                )
            roll.observe(seconds)

    # ----------------------------------------------------------- reading

    def _metric_view(self, sketch_long: QuantileSketch,
                     sketch_short: QuantileSketch,
                     metric: str) -> Dict[str, object]:
        out: Dict[str, object] = {**sketch_long.snapshot()}
        thr = self.objectives.get(metric)
        if thr is not None:
            bad_long = sketch_long.frac_over(thr)
            bad_short = sketch_short.frac_over(thr)
            burn_long = bad_long / self.error_budget
            burn_short = bad_short / self.error_budget
            out.update({
                "objective_s": thr,
                "bad_frac": round(bad_long, 6),
                "bad_frac_short": round(bad_short, 6),
                "burn_rate": round(burn_long, 3),
                "burn_rate_short": round(burn_short, 3),
                "burning": bool(sketch_long.count and sketch_short.count
                                and burn_long > 1.0 and burn_short > 1.0),
                "warning": bool(sketch_long.count
                                and (burn_long > 1.0 or burn_short > 1.0)),
            })
        return out

    def replica_report(self, replica: str) -> Dict[str, object]:
        short_s = self.window_s / self.SHORT_DIV
        metrics: Dict[str, object] = {}
        # Merges run under the same lock observes take: a half-applied
        # counter increment mid-merge would desync count vs buckets.
        with self._lock:
            views = [
                (m, roll.merged(), roll.merged(short_s))
                for m in METRICS
                if (roll := self._rolling.get((m, replica))) is not None
            ]
        for m, long_sk, short_sk in views:
            metrics[m] = self._metric_view(long_sk, short_sk, m)
        burning = any(v.get("burning") for v in metrics.values()
                      if isinstance(v, dict))
        warning = any(v.get("warning") for v in metrics.values()
                      if isinstance(v, dict))
        return {
            "replica": replica,
            "metrics": metrics,
            "state": ("burning" if burning
                      else "warning" if warning else "ok"),
        }

    def replica_burning(self, replica: str) -> bool:
        return self.replica_report(replica)["state"] == "burning"

    def replicas(self) -> List[str]:
        with self._lock:
            return sorted({r for (_, r) in self._rolling})

    def report(self) -> Dict[str, object]:
        """The /debug/slo payload: config, per-replica views, and the
        fleet roll-up (replica sketches MERGED, not averaged — the
        mergeability the fixed buckets buy)."""
        short_s = self.window_s / self.SHORT_DIV
        reps = self.replicas()
        per = [self.replica_report(r) for r in reps]
        fleet: Dict[str, object] = {}
        for m in METRICS:
            long_sk = QuantileSketch(self._bounds)
            short_sk = QuantileSketch(self._bounds)
            with self._lock:
                rollers = [self._rolling[(m, r)] for r in reps
                           if (m, r) in self._rolling]
                for roll in rollers:
                    long_sk.merge(roll.merged())
                    short_sk.merge(roll.merged(short_s))
            if rollers:
                fleet[m] = self._metric_view(long_sk, short_sk, m)
        burning = [p["replica"] for p in per if p["state"] == "burning"]
        warning = [p["replica"] for p in per if p["state"] == "warning"]
        return {
            "enabled": self.enabled,
            "objectives": {m: {"threshold_s": t, "target": self.target}
                           for m, t in self.objectives.items()},
            "window_s": self.window_s,
            "short_window_s": round(short_s, 3),
            "replicas": per,
            "fleet": fleet,
            "burning": burning,
            "state": ("burning" if burning
                      else "warning" if warning or any(
                          v.get("warning") for v in fleet.values()
                          if isinstance(v, dict)) else "ok"),
        }

    def burning(self) -> List[str]:
        """Replica labels currently burning an objective (the health()
        degraded feed)."""
        return [r for r in self.replicas() if self.replica_burning(r)]


def _engine_from_env() -> SLOEngine:
    def _f(name: str, default: str) -> float:
        try:
            return float(os.environ.get(name, default) or 0.0)
        except ValueError:
            return float(default)

    return SLOEngine(
        ttft_ms=_f("LSOT_SLO_TTFT_MS", "0"),
        tpot_ms=_f("LSOT_SLO_TPOT_MS", "0"),
        queue_wait_ms=_f("LSOT_SLO_QUEUE_WAIT_MS", "0"),
        window_s=_f("LSOT_SLO_WINDOW_S", "300"),
        target=_f("LSOT_SLO_TARGET", "0.99"),
    )


#: Process-wide engine the serving layer feeds (MetricsRegistry.record)
#: and the /debug/slo, /metrics, health() surfaces read.
ENGINE: SLOEngine = _engine_from_env()


def reconfigure(*, ttft_ms: float = 0.0, tpot_ms: float = 0.0,
                queue_wait_ms: float = 0.0, window_s: float = 300.0,
                target: float = 0.99) -> SLOEngine:
    """App-startup wiring seam (AppConfig.slo_*): swap the process
    engine — same pattern as `tracing.TRACER.reconfigure`, so
    `AppConfig(slo_ttft_ms=500)` is honored, not a silent no-op.
    Returns the new engine (tests use the return to drive a clock)."""
    global ENGINE
    ENGINE = SLOEngine(ttft_ms=ttft_ms, tpot_ms=tpot_ms,
                       queue_wait_ms=queue_wait_ms, window_s=window_s,
                       target=target)
    return ENGINE
