"""Config, logging, timing/tracing utilities."""
