"""Device-time profiling from jax.profiler traces — no TensorBoard needed.

Wall-clock around a jitted call on this repo's tunneled TPU includes a
~65 ms host↔device dispatch+sync floor, which silently dominates short
programs and understates MFU/bandwidth (round-3 artifact: prefill "MFU 7%"
was mostly tunnel latency). The profiler's trace.json.gz records actual
device op timelines; `tensorboard_plugin_profile`'s converter is broken in
this image, so this module parses the Chrome-trace JSON directly:

    with device_trace() as tr:
        fn(args)          # any number of dispatches
    tr.device_time_s()    # summed device-op wall, overlaps merged
    tr.top_ops(10)        # [(name, seconds, count)] hottest first

Works on CPU and TPU backends (tests run it on CPU). Event model: each
trace "X" (complete) event on a device-lane thread contributes its `dur`;
lanes are identified by their process name containing the device prefix
(e.g. "/device:TPU:0" / "TFRT-CPU"). Device time is reported two ways:
summed op time (`op_time_s`, counts parallel lanes twice) and merged
busy time (`device_time_s`, union of intervals — the honest denominator
for MFU on one chip).
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple


class Trace:
    def __init__(self):
        self.ops: Dict[str, List[float]] = {}
        self.intervals: List[Tuple[float, float]] = []

    # ------------------------------------------------------------- loading

    def load_dir(self, trace_dir: str) -> "Trace":
        for path in glob.glob(
            os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
        ):
            with gzip.open(path, "rt") as f:
                self._ingest(json.load(f))
        return self

    def _ingest(self, doc: dict) -> None:
        events = doc.get("traceEvents", [])
        # Lane = (pid, tid). Host threads share the device PID (on the CPU
        # backend the 'python' dispatch thread lives under '/host:CPU'
        # beside the real 'tf_XLAPjRtCpuClient/*' compute lane), so the
        # filter must be by THREAD name, not process name. Known op lanes:
        # TPU traces put per-op events on threads named 'XLA Ops' (the
        # 'XLA Modules' / 'Steps' lanes are whole-program spans that would
        # double-count); PjRt CPU puts them on 'tf_XLAPjRtCpuClient/...'.
        tid_name = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "thread_name":
                tid_name[(e.get("pid"), e.get("tid"))] = (
                    e.get("args", {}).get("name", "")
                )
        op_lanes = {
            lane for lane, name in tid_name.items()
            if "XLA Ops" in name or name.startswith("tf_")
        }
        if not op_lanes:
            # Unknown backend naming: fall back to everything except
            # obvious host / aggregate lanes.
            deny = ("python", "main", "profiler", "XLA Modules", "Steps",
                    "TraceMe", "Framework")
            op_lanes = {
                lane for lane, name in tid_name.items()
                if not any(d.lower() in name.lower() for d in deny)
            }
        for e in events:
            if (e.get("ph") != "X"
                    or (e.get("pid"), e.get("tid")) not in op_lanes):
                continue
            dur = float(e.get("dur", 0.0)) * 1e-6  # us -> s
            if dur <= 0.0:
                continue
            name = e.get("name", "?")
            self.ops.setdefault(name, []).append(dur)
            ts = float(e.get("ts", 0.0)) * 1e-6
            self.intervals.append((ts, ts + dur))

    # ------------------------------------------------------------ queries

    def op_time_s(self) -> float:
        """Summed op durations (parallel lanes double-count)."""
        return sum(sum(v) for v in self.ops.values())

    def device_time_s(self) -> float:
        """Union of op intervals — device busy wall-clock."""
        if not self.intervals:
            return 0.0
        merged = 0.0
        cur_a, cur_b = None, None
        for a, b in sorted(self.intervals):
            if cur_b is None or a > cur_b:
                if cur_b is not None:
                    merged += cur_b - cur_a
                cur_a, cur_b = a, b
            else:
                cur_b = max(cur_b, b)
        merged += cur_b - cur_a
        return merged

    def top_ops(self, n: int = 10) -> List[Tuple[str, float, int]]:
        rows = [
            (name, sum(durs), len(durs)) for name, durs in self.ops.items()
        ]
        rows.sort(key=lambda r: -r[1])
        return rows[:n]


# ---------------------------------------------------------------------------
# On-demand device profiling (/debug/profile): the fleet-wide single-capture
# guard + app-configured defaults. jax.profiler supports ONE trace at a time
# per process, and a capture is expensive enough that two overlapping ones
# would corrupt each other's artifacts — so schedulers (every replica, every
# model) funnel through this process-wide guard: at most one capture in
# flight, whoever holds it releases on finish/abort.

_capture_lock = threading.Lock()
_capture_owner: Optional[str] = None

#: App-startup overrides (AppConfig.profile_dir / profile_rounds via
#: `reconfigure_profile`); env fallbacks LSOT_PROFILE_DIR /
#: LSOT_PROFILE_ROUNDS keep the knobs usable without the app wiring.
_profile_dir_override: Optional[str] = None
_profile_rounds_override: Optional[int] = None


def reconfigure_profile(profile_dir: Optional[str] = None,
                        rounds: Optional[int] = None) -> None:
    """App-startup wiring seam (AppConfig.profile_dir/profile_rounds) —
    same pattern as `tracing.TRACER.reconfigure`, so the AppConfig knobs
    are honored, not silent no-ops."""
    global _profile_dir_override, _profile_rounds_override
    _profile_dir_override = profile_dir or None
    _profile_rounds_override = int(rounds) if rounds else None


def profile_defaults() -> Tuple[Optional[str], int]:
    """(artifact base dir or None, default rounds) for an on-demand
    capture. Dir precedence: reconfigure_profile > LSOT_PROFILE_DIR >
    the tracer's export dir (the capture lands NEXT TO the existing
    per-request trace exports) > None (caller tempdirs)."""
    d = _profile_dir_override or os.environ.get("LSOT_PROFILE_DIR") or None
    if not d:
        from .tracing import TRACER

        d = TRACER.export_dir or None
    if _profile_rounds_override:
        return d, _profile_rounds_override
    try:
        n = int(os.environ.get("LSOT_PROFILE_ROUNDS", "8"))
    except ValueError:
        n = 8
    return d, max(1, n)


def try_acquire_capture(owner: str) -> bool:
    """Claim the process-wide capture slot; False when someone holds it
    (the /debug/profile 409)."""
    global _capture_owner
    with _capture_lock:
        if _capture_owner is not None:
            return False
        _capture_owner = owner
        return True


def release_capture(owner: str) -> None:
    """Release the slot (idempotent; only the owner's release counts, so
    a late abort cannot free a successor's capture)."""
    global _capture_owner
    with _capture_lock:
        if _capture_owner == owner:
            _capture_owner = None


def capture_owner() -> Optional[str]:
    with _capture_lock:
        return _capture_owner


def find_profile_artifacts(trace_dir: str) -> List[str]:
    """The Perfetto-loadable artifacts a jax.profiler capture wrote under
    `trace_dir` (the same *.trace.json.gz files `Trace.load_dir` parses
    and scripts/obs_smoke.sh asserts non-empty)."""
    return sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ))


@contextlib.contextmanager
def device_trace(trace_dir: str | None = None):
    """Profile the enclosed region; yields a Trace filled on exit."""
    import jax

    tr = Trace()
    own = trace_dir is None
    d = trace_dir or tempfile.mkdtemp(prefix="lsot_trace_")
    try:
        with jax.profiler.trace(d):
            yield tr
        tr.load_dir(d)
    finally:
        if own:
            import shutil

            shutil.rmtree(d, ignore_errors=True)
