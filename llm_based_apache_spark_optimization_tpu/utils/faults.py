"""Deterministic fault injection at the serving stack's failure boundaries.

A fault-tolerance layer that has never seen a fault is untested by
definition (FlashInfer-Bench's thesis, PAPERS.md: a serving stack is only
trustworthy when its failure behavior is itself exercised by the harness).
This registry lets tests, the chaos evalh mode, and `scripts/chaos_smoke.sh`
make the out-of-process boundaries fail ON DEMAND, reproducibly:

    LSOT_FAULTS=ollama:connect:0.5,sql:exec:1 LSOT_FAULTS_SEED=0 pytest -m chaos

Spec grammar: comma-separated `site:point:probability` triples. The first
two fields name an injection site (`ollama:connect`, `sql:exec`,
`sql:load`, `sched:decode` — kills the loop at round issue, before any
token of the round exists — and `sched:crash` — kills it at harvest,
MID-BATCH, after tokens may already have streamed to clients: the
supervisor's replay-without-duplicates seam; grep for `FAULTS.check` to
enumerate); the probability is a float in (0, 1]. The RNG is seeded
(`LSOT_FAULTS_SEED`, default 0), so the same spec + seed + call sequence
replays the exact same fault schedule — chaos tests assert concrete
outcomes, not distributions.

Injection points call `FAULTS.check("site:point")`, which raises
`InjectedFault` (a ConnectionError subclass, so connect-phase retry
classifiers treat it exactly like a real refused connection) with the
configured probability. With no spec configured the check is one dict
lookup on an empty dict — effectively free on the serving path.

Determinism caveat: the registry draws from ONE seeded stream, so replay
is exact only when the injection points are hit in a deterministic order
(single-threaded harnesses, or probability 1). Concurrent chaos runs still
get the configured *rates*, just not a bit-exact schedule.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict

from .observability import resilience

__all__ = ["FAULTS", "FaultRegistry", "InjectedFault"]


class InjectedFault(ConnectionError):
    """A deliberately injected failure. Subclasses ConnectionError so the
    retry layers' connect-phase classifiers (and generic OSError handlers)
    treat it like the real outage it simulates."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at {site!r} (LSOT_FAULTS)")
        self.site = site


class FaultRegistry:
    """Seeded per-site fault probabilities + injected-fault counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._probs: Dict[str, float] = {}
        self._rng = random.Random(0)
        self._counts: Dict[str, int] = {}

    # ------------------------------------------------------------- config

    @staticmethod
    def parse(spec: str) -> Dict[str, float]:
        """`"ollama:connect:0.5,sql:exec:1"` -> {"ollama:connect": 0.5,
        "sql:exec": 1.0}. Raises ValueError on malformed entries — a typo'd
        chaos spec must fail the run, not silently inject nothing."""
        probs: Dict[str, float] = {}
        for entry in filter(None, (s.strip() for s in spec.split(","))):
            parts = entry.rsplit(":", 1)
            if len(parts) != 2 or ":" not in parts[0]:
                raise ValueError(
                    f"bad fault spec entry {entry!r} (want site:point:prob)"
                )
            site, prob_s = parts
            try:
                prob = float(prob_s)
            except ValueError:
                raise ValueError(
                    f"bad fault probability in {entry!r}"
                ) from None
            if not 0.0 < prob <= 1.0:
                raise ValueError(
                    f"fault probability must be in (0, 1], got {prob} "
                    f"in {entry!r}"
                )
            probs[site] = prob
        return probs

    def configure(self, spec: str, seed: int = 0) -> "FaultRegistry":
        """(Re)configure sites + reseed the stream; empty spec disables."""
        probs = self.parse(spec)
        with self._lock:
            self._probs = probs
            self._rng = random.Random(seed)
            self._counts = {}
        return self

    def configure_from_env(self) -> "FaultRegistry":
        return self.configure(
            os.environ.get("LSOT_FAULTS", ""),
            int(os.environ.get("LSOT_FAULTS_SEED", "0")),
        )

    def clear(self) -> None:
        with self._lock:
            self._probs = {}
            self._counts = {}

    @property
    def active(self) -> bool:
        return bool(self._probs)

    # ----------------------------------------------------------- checking

    def check(self, site: str) -> None:
        """Raise InjectedFault with the site's configured probability."""
        if not self._probs:  # fast path: injection off
            return
        with self._lock:
            prob = self._probs.get(site)
            if prob is None or self._rng.random() >= prob:
                return
            self._counts[site] = self._counts.get(site, 0) + 1
        resilience.inc("faults_injected")
        raise InjectedFault(site)

    def counts(self) -> Dict[str, int]:
        """Injected faults per site since configure()."""
        with self._lock:
            return dict(self._counts)


#: Process-wide registry every injection point consults; configured from
#: LSOT_FAULTS / LSOT_FAULTS_SEED at import (tests reconfigure directly).
FAULTS = FaultRegistry().configure_from_env()
