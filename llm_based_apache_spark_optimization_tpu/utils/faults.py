"""Deterministic fault injection at the serving stack's failure boundaries.

A fault-tolerance layer that has never seen a fault is untested by
definition (FlashInfer-Bench's thesis, PAPERS.md: a serving stack is only
trustworthy when its failure behavior is itself exercised by the harness).
This registry lets tests, the chaos evalh mode, and `scripts/chaos_smoke.sh`
make the out-of-process boundaries fail ON DEMAND, reproducibly:

    LSOT_FAULTS=ollama:connect:0.5,sql:exec:1 LSOT_FAULTS_SEED=0 pytest -m chaos

Spec grammar: comma-separated `site:point:probability[:seconds]` entries.
The first two fields name an injection site (`ollama:connect`,
`sql:exec`, `sql:load`, `sched:decode` — kills the loop at round issue,
before any token of the round exists — `sched:crash` — kills it at
harvest, MID-BATCH, after tokens may already have streamed to clients:
the supervisor's replay-without-duplicates seam — `sched:slot_stall` —
marks a request's slot as a silently no-progress decode lane, the
per-slot stall-retirement seam — `sched:wedge_r{i}` — the
replica-ADDRESSABLE fleet seam: every scheduler checks
`sched:wedge_<its replica label>` at round issue, so
`sched:wedge_r1:1:0.5` wedges exactly pool replica r1 (duration form)
or `sched:wedge_r1:1` crashes it (raising form) while its siblings run
untouched — the targeted-restart chaos trigger — plus the
duration-valued HANG sites below; grep for `FAULTS.check` to
enumerate); the probability is a float
in (0, 1]. The RNG is seeded (`LSOT_FAULTS_SEED`, default 0), so the
same spec + seed + call sequence replays the exact same fault schedule —
chaos tests assert concrete outcomes, not distributions.

**Duration-valued sites** (the optional 4th field, seconds > 0) model
HANGS instead of failures: a firing check SLEEPS for that long and then
returns instead of raising — the wedge that never raises is exactly what
the watchdog layer (serve/watchdog.py) exists to catch. `sched:hang:1:5`
wedges the decode loop 5 s at round issue (the supervisor's heartbeat
monitor must escalate it to a `SchedulerStalled` restart);
`ollama:stall:p:secs` and `sql:stall:p:secs` stall the out-of-process
boundaries so dependency timeouts/deadlines are exercised, not assumed.
Site names are always exactly two `:`-separated segments — the parser
relies on it to tell `site:point:prob:secs` from a malformed entry.

**Value-valued sites** reuse the same 4th field as a plain NUMBER the
injection point interprets itself, read through `FAULTS.value(site)`
(fires with the configured probability, returns the value, never raises
or sleeps). In-tree value sites: `kv:pressure:p:v` — the paged KV
scheduler shrinks its effective page pool by `v` (a fraction of the
pool when v < 1, an absolute page count otherwise) for every loop
iteration the site fires, forcing the allocation failures that drive
victim preemption (serve/scheduler.py; `evalh --chaos` pressure stage)
— and `net:delay:p:secs` — the replica-transport rpc envelope
(serve/remote.py) stalls that long on the wire, driving the
deadline-propagating timeout path.

**Network sites** (ISSUE 15, consumed at the CLIENT side of both
replica transports in serve/remote.py so one seeded schedule drives
loopback and socket fleets alike): `net:drop:p` — the RPC executes on
the server but the response is lost, so the retry must dedup against
the idempotency-token ledger (the no-double-generate proof);
`net:dup:p` — the request is delivered twice and the second delivery
must be absorbed by the same ledger; `net:delay:p:secs` — above;
`net:partition_r{i}:p` — replica-ADDRESSABLE, like `sched:wedge_r{i}`:
every RPC, token-stream delivery and lease ping to pool replica r{i}
fails while the site is configured, which is what drives the
lease-expiry → targeted-restart → journal-replay recovery path
(`evalh --chaos` stage 7). Drop/dup consult the non-raising
`FAULTS.fires(site)` draw; the partition's STATE (token-stream gating)
reads `FAULTS.site_active(site)`, which never draws — concurrent
stream deliveries must not perturb the seeded schedule.

**Per-class SQL error sites** (ISSUE 20): `sql:syntax`, `sql:schema`
and `sql:transient` fire inside `ResilientSQLBackend.execute` and raise
a REPRESENTATIVE engine error instead of the generic `InjectedFault` —
the exact strings a real sqlite engine produces for each class of the
repair taxonomy (app/repair.classify_sql_error), so chaos stage 10 and
the unit tests can exercise every taxonomy branch deterministically.
`sql:syntax`/`sql:schema` raise `InjectedSQLError` (a plain Exception:
deterministic engine answers, NEVER retried or breaker-counted);
`sql:transient` raises `InjectedFault` (a ConnectionError: the retry
ladder and breaker treat it like the lock-contention outage it
simulates). `SQL_FAULT_ERRORS` below is the site → message table.

**Fleet-membership site** (ISSUE 17): `fleet:spawn:p` fires inside the
autoscaler's scale-up attempt (serve/elastic.py) BEFORE the standby
worker is contacted — an injected spawn failure must degrade to "keep
serving at the current fleet size" (a counted non-event in
autoscaler.stats()), never wedge the control loop or lose a request
(`evalh --chaos` stage 8's partition-during-scale-up leg).

Injection points call `FAULTS.check("site:point")`, which raises
`InjectedFault` (a ConnectionError subclass, so connect-phase retry
classifiers treat it exactly like a real refused connection) — or, for a
duration-valued site, sleeps — with the configured probability. With no
spec configured the check is one dict lookup on an empty dict —
effectively free on the serving path.

Determinism caveat: the registry draws from ONE seeded stream, so replay
is exact only when the injection points are hit in a deterministic order
(single-threaded harnesses, or probability 1). Concurrent chaos runs still
get the configured *rates*, just not a bit-exact schedule.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, Tuple

from .observability import resilience

__all__ = ["FAULTS", "FaultRegistry", "InjectedFault", "InjectedSQLError",
           "SQL_FAULT_ERRORS"]


class InjectedFault(ConnectionError):
    """A deliberately injected failure. Subclasses ConnectionError so the
    retry layers' connect-phase classifiers (and generic OSError handlers)
    treat it like the real outage it simulates."""

    def __init__(self, site: str, message: str = ""):
        super().__init__(message or f"injected fault at {site!r} (LSOT_FAULTS)")
        self.site = site


class InjectedSQLError(Exception):
    """A deliberately injected DETERMINISTIC engine error (ISSUE 20):
    the message is a representative real-engine string for one class of
    the repair taxonomy. A plain Exception on purpose — retry ladders
    and breakers must treat it exactly like the syntax/schema error it
    simulates (no retry, no breaker count), so the only layer that acts
    on it is the repair loop's classifier."""

    def __init__(self, site: str, message: str):
        super().__init__(message)
        self.site = site


#: Per-class SQL fault sites (ISSUE 20): site → (exception class,
#: representative engine error string). The messages are the shapes
#: app/repair.classify_sql_error keys on, so configuring
#: `sql:syntax:1` drives the exact taxonomy branch a real engine would.
SQL_FAULT_ERRORS = {
    "sql:syntax": (InjectedSQLError, 'near "FORM": syntax error'),
    "sql:schema": (InjectedSQLError, "no such column: total_amout"),
    "sql:transient": (InjectedFault, "database is locked"),
}


class FaultRegistry:
    """Seeded per-site fault probabilities + injected-fault counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._probs: Dict[str, float] = {}
        self._durations: Dict[str, float] = {}
        self._rng = random.Random(0)
        self._counts: Dict[str, int] = {}
        # Injectable so hang-site tests assert the sleep without paying it.
        self._sleep = time.sleep

    # ------------------------------------------------------------- config

    @classmethod
    def parse(cls, spec: str) -> Dict[str, float]:
        """`"ollama:connect:0.5,sql:exec:1"` -> {"ollama:connect": 0.5,
        "sql:exec": 1.0} (probabilities only; duration fields are dropped
        — use parse_spec for both). Raises ValueError on malformed
        entries — a typo'd chaos spec must fail the run, not silently
        inject nothing."""
        return cls.parse_spec(spec)[0]

    @staticmethod
    def parse_spec(spec: str) -> Tuple[Dict[str, float], Dict[str, float]]:
        """Full parse: (probabilities, durations). An entry is
        `site:point:prob` (raising site) or `site:point:prob:secs`
        (duration-valued hang site: the check SLEEPS secs instead of
        raising). Site names are exactly two segments."""
        probs: Dict[str, float] = {}
        durations: Dict[str, float] = {}
        for entry in filter(None, (s.strip() for s in spec.split(","))):
            fields = entry.split(":")
            if len(fields) not in (3, 4):
                raise ValueError(
                    f"bad fault spec entry {entry!r} "
                    f"(want site:point:prob[:secs])"
                )
            site = f"{fields[0]}:{fields[1]}"
            if not fields[0] or not fields[1]:
                raise ValueError(
                    f"bad fault spec entry {entry!r} "
                    f"(want site:point:prob[:secs])"
                )
            try:
                prob = float(fields[2])
            except ValueError:
                raise ValueError(
                    f"bad fault probability in {entry!r}"
                ) from None
            if not 0.0 < prob <= 1.0:
                raise ValueError(
                    f"fault probability must be in (0, 1], got {prob} "
                    f"in {entry!r}"
                )
            if len(fields) == 4:
                try:
                    secs = float(fields[3])
                except ValueError:
                    raise ValueError(
                        f"bad hang duration in {entry!r}"
                    ) from None
                if secs <= 0.0:
                    raise ValueError(
                        f"hang duration must be positive, got {secs} "
                        f"in {entry!r}"
                    )
                durations[site] = secs
            probs[site] = prob
        return probs, durations

    def configure(self, spec: str, seed: int = 0) -> "FaultRegistry":
        """(Re)configure sites + reseed the stream; empty spec disables."""
        probs, durations = self.parse_spec(spec)
        with self._lock:
            self._probs = probs
            self._durations = durations
            self._rng = random.Random(seed)
            self._counts = {}
        return self

    def configure_from_env(self) -> "FaultRegistry":
        return self.configure(
            os.environ.get("LSOT_FAULTS", ""),
            int(os.environ.get("LSOT_FAULTS_SEED", "0")),
        )

    def clear(self) -> None:
        with self._lock:
            self._probs = {}
            self._durations = {}
            self._counts = {}

    @property
    def active(self) -> bool:
        return bool(self._probs)

    # ----------------------------------------------------------- checking

    def check(self, site: str) -> None:
        """Raise InjectedFault with the site's configured probability —
        or, for a duration-valued site (`site:point:prob:secs`), SLEEP
        that long and return: the hang that never raises, which the
        watchdog layer must detect from outside."""
        if not self._probs:  # fast path: injection off
            return
        with self._lock:
            prob = self._probs.get(site)
            if prob is None or self._rng.random() >= prob:
                return
            self._counts[site] = self._counts.get(site, 0) + 1
            secs = self._durations.get(site)
        resilience.inc("faults_injected")
        if secs is not None:
            # Outside the lock: a wedge must not block other sites' checks.
            self._sleep(secs)
            return
        sql_err = SQL_FAULT_ERRORS.get(site)
        if sql_err is not None:
            exc_cls, message = sql_err
            raise exc_cls(site, message)
        raise InjectedFault(site)

    def fires(self, site: str) -> bool:
        """Boolean draw: True with the site's configured probability
        (counted like check()), never raises or sleeps — for injection
        points that apply their own semantics to a PLAIN firing (the
        transport layer's `net:drop`/`net:dup`). False when the site is
        unconfigured; an unconfigured site draws nothing, so sites
        compose without perturbing each other's seeded schedules."""
        if not self._probs:  # fast path: injection off
            return False
        with self._lock:
            prob = self._probs.get(site)
            if prob is None or self._rng.random() >= prob:
                return False
            self._counts[site] = self._counts.get(site, 0) + 1
        resilience.inc("faults_injected")
        return True

    def site_active(self, site: str) -> bool:
        """Is the site configured at all? NO randomness — no draw, no
        count — so state-like consultations (is replica r1 currently
        partitioned?) can run from any thread at any rate without
        perturbing the seeded schedule the raising/boolean draws replay."""
        if not self._probs:
            return False
        with self._lock:
            return site in self._probs

    def value(self, site: str):
        """Value-valued check: with the site's configured probability,
        return its 4th-field number (never raises, never sleeps) — the
        injection point applies its own semantics (e.g. `kv:pressure`
        shrinks the effective page pool by the value). Returns None when
        the site is unconfigured, has no value field, or the draw does
        not fire. Counts like check() so chaos reports can still prove
        the site fired."""
        if not self._probs:  # fast path: injection off
            return None
        with self._lock:
            prob = self._probs.get(site)
            secs = self._durations.get(site)
            if prob is None or secs is None \
                    or self._rng.random() >= prob:
                return None
            self._counts[site] = self._counts.get(site, 0) + 1
        resilience.inc("faults_injected")
        return secs

    def counts(self) -> Dict[str, int]:
        """Injected faults per site since configure()."""
        with self._lock:
            return dict(self._counts)


#: Process-wide registry every injection point consults; configured from
#: LSOT_FAULTS / LSOT_FAULTS_SEED at import (tests reconfigure directly).
FAULTS = FaultRegistry().configure_from_env()
