"""Shared analytic roofline model: per-phase FLOPs, HBM bytes, and peaks.

Before PR 12 the analytic cost model lived in `bench.py` (the `_detail`
FLOP formulas, `_step_bytes`, and the `PEAKS` chip table) while the live
serving stack logged only walls and token counts — the prefill-vs-decode
hardware asymmetry the disaggregation ROADMAP item rests on (BENCH_r03:
prefill 0.07 MFU compute-bound, decode 0.87 HBM-util memory-bound) was a
bench-time artifact the scheduler could not see. This module is the ONE
definition both sides now price with:

- `peak_for(device_kind, quant)` — the in-tree chip table (bf16/int8
  TFLOP/s + HBM GB/s per TPU generation) with a CPU fallback: unknown
  device kinds get nominal host peaks (LSOT_PEAK_TFLOPS /
  LSOT_PEAK_HBM_GBS override them), so MFU/HBM-util are ALWAYS defined
  and the CPU fixture tests exercise the same code path a chip does.
  The absolute CPU numbers are nominal — the verdict and the
  round-over-round trend are the signal there, not the magnitude.
- per-phase work models (`flops_per_token`, `prefill_flops`,
  `decode_step_bytes`, `kv_bytes`, `draft_bytes`, `verify_flops`) over
  the model config: prefill, decode, draft, verify — bf16/int8 weights
  via `param_bytes`/`weight_bits`, bf16/int8 KV priced through
  `engine/kvcache.cache_bytes` (contiguous) or `engine/paged_kv.
  page_bytes` (paged pools, incl. the int8-page layout).
- `PerfModel` — the live ledger: the scheduler builds one at
  construction and stamps every harvested round with achieved MFU,
  HBM-bandwidth utilization, and a compute-vs-memory-bound `verdict`
  (whichever roof the round sat closer to is the one that binds).
  `round_attribution` is a handful of float ops — bench's
  `_obs_overhead` prices it against the <1%-of-round-cadence bar.

FLOP model (identical to bench's `_detail`, by construction): 2·P per
token for the dense matmuls plus 4·S·L·heads·head_dim for the attention
score/value contractions at context S. Decode HBM bytes per step: the
full weight set streamed once plus the K/V cache read at the current
context.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

__all__ = [
    "PEAKS",
    "PerfModel",
    "cpu_fallback_peaks",
    "decode_step_bytes",
    "draft_bytes",
    "flops_per_token",
    "kv_bytes",
    "peak_for",
    "prefill_flops",
    "verdict",
]

# Peak specs by TPU generation for MFU / bandwidth accounting:
# substring of device_kind (lowercased) -> (bf16 TFLOP/s, int8 TOP/s,
# HBM GB/s). Moved in-tree from bench.py so the serving stack and the
# bench can never disagree on a chip's roofline.
PEAKS: Dict[str, Tuple[float, float, float]] = {
    "v6": (918.0, 1836.0, 1640.0),
    "v5e": (197.0, 394.0, 819.0),
    "v5 lite": (197.0, 394.0, 819.0),
    "v5p": (459.0, 918.0, 2765.0),
    "v4": (275.0, 275.0, 1228.0),
}


def cpu_fallback_peaks() -> Tuple[float, float]:
    """Nominal host peaks for unknown device kinds (the CPU fixture):
    (FLOP/s, bytes/s). Overridable via LSOT_PEAK_TFLOPS /
    LSOT_PEAK_HBM_GBS so an operator benchmarking an unlisted chip can
    still get honest utilization numbers. Defaults are a generic server
    host (0.2 TFLOP/s, 50 GB/s) — on the CPU fixture the VERDICT and the
    trend are the signal, not the absolute MFU."""
    try:
        tf = float(os.environ.get("LSOT_PEAK_TFLOPS", "0.2"))
    except ValueError:
        tf = 0.2
    try:
        bw = float(os.environ.get("LSOT_PEAK_HBM_GBS", "50.0"))
    except ValueError:
        bw = 50.0
    return max(tf, 1e-9) * 1e12, max(bw, 1e-9) * 1e9


def peak_for(device_kind: str, quant: str = "") -> Tuple[float, float]:
    """(peak FLOP/s, peak HBM bytes/s) for a device kind; int8 weights
    ride the int8 TOP/s column. Unknown kinds (CPU, new chips) fall back
    to `cpu_fallback_peaks()` — never None, so every ledger entry carries
    a defined MFU/HBM-util."""
    dk = (device_kind or "").lower()
    for key, (bf16_tf, int8_tf, bw) in PEAKS.items():
        if key in dk:
            return (int8_tf if quant == "int8" else bf16_tf) * 1e12, bw * 1e9
    return cpu_fallback_peaks()


# ------------------------------------------------------------- work models


def attn_flops_per_token_per_ctx(cfg) -> int:
    """Attention score+value contraction FLOPs for ONE token attending to
    ONE context position: 4 · L · heads · head_dim (2 matmul FLOPs each
    for QK^T and PV)."""
    return 4 * cfg.num_layers * cfg.num_heads * cfg.head_dim


def flops_per_token(cfg, ctx: int) -> int:
    """Dense-stack + attention FLOPs for one generated token at context
    length `ctx` — bench `_detail`'s `flops_per_tok`, shared."""
    return 2 * cfg.num_params + attn_flops_per_token_per_ctx(cfg) * ctx


def prefill_flops(cfg, rows: int, tokens: int,
                  ctx_avg: Optional[int] = None) -> int:
    """FLOPs of one prefill forward: `rows` sequences × `tokens` each,
    attending on average to `ctx_avg` positions (a chunk starting at s0
    averages s0 + tokens/2; a from-zero prefill averages tokens/2 — the
    default, matching bench's `prefill_flops`)."""
    if ctx_avg is None:
        ctx_avg = tokens // 2
    return rows * tokens * (
        2 * cfg.num_params + attn_flops_per_token_per_ctx(cfg) * ctx_avg
    )


def kv_bytes(cfg, rows: int, ctx: int, *, itemsize: int = 2,
             kv_quant: Optional[str] = None, kv_layout: str = "contiguous",
             page_size: Optional[int] = None) -> int:
    """HBM bytes of the K/V state one decode step READS for `rows`
    sequences at context `ctx` — priced at the layout actually serving:

    - contiguous bf16/f32: `engine/kvcache.cache_bytes` (sublane
      rounding included — the bytes the device truly allocates/streams);
    - contiguous int8: int8 values + the per-slot f32 scales
      (cache_bytes at itemsize 1 + the scale rows), bench's 7b pricing;
    - paged: mapped pages only (`pages_for_tokens × page_bytes` per
      row) — the ragged kernel's kv_lens clamp means dead pages are
      never streamed, and `page_bytes` prices the int8-page layout
      (values + per-position scales) exactly like the pool allocator.
    """
    from ..engine.kvcache import cache_bytes

    if kv_layout == "paged":
        from ..engine.paged_kv import page_bytes, pages_for_tokens

        ps = page_size or 64
        return rows * pages_for_tokens(max(1, ctx), ps) * page_bytes(
            cfg, ps, itemsize, kv_quant
        )
    if kv_quant == "int8":
        return (cache_bytes(cfg, rows, ctx, 1)
                + cache_bytes(cfg, rows, ctx, 4) // cfg.head_dim)
    return cache_bytes(cfg, rows, ctx, itemsize)


def decode_step_bytes(cfg, rows: int, ctx: int, param_bytes: int, *,
                      itemsize: int = 2, kv_quant: Optional[str] = None,
                      kv_layout: str = "contiguous",
                      page_size: Optional[int] = None, tp: int = 1) -> int:
    """HBM bytes ONE decode step streams: the full weight shard plus the
    K/V read at context `ctx` (KV heads shard over tp alongside the
    weights, so per-device bytes divide)."""
    return (param_bytes + kv_bytes(
        cfg, rows, ctx, itemsize=itemsize, kv_quant=kv_quant,
        kv_layout=kv_layout, page_size=page_size,
    )) // max(1, tp)


def draft_bytes(cfg, rows: int, draft: int, hist_len: int) -> int:
    """HBM bytes of one prompt-lookup DRAFT pass: the on-device int32
    token-history gather (rows × hist_len reads to find the copy window,
    rows × draft writes). Drafting is table lookups — effectively zero
    FLOPs — so the phase is priced in bytes only; it exists so the
    four-phase model (prefill/decode/draft/verify) is complete, and so a
    model-based draft (ROADMAP) has a slot to grow into."""
    return 4 * rows * (hist_len + draft)


def verdict(mfu: float, hbm_util: float) -> str:
    """Which roof binds: a round running closer to the compute ceiling
    than the bandwidth ceiling is compute-bound (prefill's profile),
    closer to bandwidth is memory-bound (decode's). Ties break to
    memory-bound — the serving default for token-at-a-time decode."""
    return "compute-bound" if mfu > hbm_util else "memory-bound"


# ---------------------------------------------------------------- the model


class PerfModel:
    """Live per-round roofline ledger for one scheduler replica.

    Construction captures everything immutable — model shape, weight
    bytes/bits, KV layout/dtype pricing, tp, device peaks — so a
    per-round attribution is a handful of float multiplies.
    `round_attribution` is PURE (same inputs → same outputs; the
    flight-record reconciliation test recomputes records through it);
    `observe` additionally folds the attribution into per-phase EWMAs
    behind a tiny lock for the /metrics `serving.perf` view."""

    #: EWMA weight for the per-phase running view (recent rounds
    #: dominate; one slow round doesn't erase an hour of signal).
    ALPHA = 0.2

    PHASES = ("prefill", "decode", "draft", "verify")

    def __init__(self, cfg, *, param_bytes: int, weight_bits: int = 16,
                 kv_itemsize: int = 2, kv_quant: Optional[str] = None,
                 kv_layout: str = "contiguous",
                 page_size: Optional[int] = None, tp: int = 1,
                 device_kind: str = ""):
        self.cfg = cfg
        self.param_bytes = int(param_bytes)
        self.weight_bits = int(weight_bits)
        self.kv_itemsize = int(kv_itemsize)
        self.kv_quant = kv_quant
        self.kv_layout = kv_layout
        self.page_size = page_size
        self.tp = max(1, int(tp))
        self.device_kind = device_kind
        quant = "int8" if self.weight_bits <= 8 else ""
        self.peak_flops, self.peak_bw = peak_for(device_kind, quant)
        # Precomputed pricing coefficients: the per-round stamp runs on
        # the scheduler hot path inside the _obs_overhead <1% budget, so
        # phase_work must be pure arithmetic — no imports, no generic
        # helpers. Each closed form EQUALS the module-level function it
        # mirrors (kv_bytes / flops_per_token) bit for bit; a unit test
        # pins the equality across layouts/quants.
        self._two_p = 2 * cfg.num_params
        self._attn = attn_flops_per_token_per_ctx(cfg)
        if kv_layout == "paged":
            from ..engine.paged_kv import page_bytes

            self._ps = int(page_size or 64)
            self._page_b = page_bytes(cfg, self._ps, kv_itemsize, kv_quant)
            self._kv_per_pos = 0
        else:
            self._ps = 0
            self._page_b = 0
            lkh = 2 * cfg.num_layers * cfg.num_kv_heads
            if kv_quant == "int8":
                # int8 values + f32 per-position scales (the exact
                # cache_bytes(.,1) + cache_bytes(.,4)//head_dim split).
                self._kv_per_pos = lkh * cfg.head_dim + lkh * 4
            else:
                self._kv_per_pos = lkh * cfg.head_dim * kv_itemsize
        self._lock = threading.Lock()
        self._phases: Dict[str, Dict[str, float]] = {}
        # Prefill dispatches accumulate between harvests (the loop issues
        # chunks asynchronously and never times them individually); the
        # harvest flushes the pile against the inter-harvest wall.
        self._pending_prefill_flops = 0.0
        self._pending_prefill_bytes = 0.0

    # ------------------------------------------------------------- pricing

    def _kv_read_bytes(self, rows: int, ctx: int) -> int:
        """Hot-path twin of module-level `kv_bytes` (same closed form,
        precomputed coefficients — the equality is unit-tested)."""
        if self._page_b:
            ctx = max(1, ctx)
            return rows * ((ctx + self._ps - 1) // self._ps) * self._page_b
        return rows * (ctx + (-ctx % 8)) * self._kv_per_pos

    def phase_work(self, phase: str, *, rows: int, tokens: int,
                   ctx: int) -> Tuple[float, float]:
        """(FLOPs, HBM bytes) of one `phase` pass: `rows` sequences,
        `tokens` new positions each, at average context `ctx`. Decode is
        `tokens` steps each streaming weights+KV; verify is ONE forward
        over a tokens-wide window (weights stream once); prefill is one
        chunk forward; draft is the history gather."""
        if phase == "draft":
            return 0.0, float(4 * rows * (ctx + tokens))
        per_pass = (self.param_bytes
                    + self._kv_read_bytes(rows, ctx)) / self.tp
        if phase == "decode":
            flops = rows * tokens * (self._two_p + self._attn * ctx)
            return float(flops), float(tokens * per_pass)
        if phase == "verify":
            flops = rows * tokens * (self._two_p + self._attn * ctx)
            return float(flops), float(per_pass)
        if phase == "prefill":
            return (float(rows * tokens * (self._two_p + self._attn * ctx)),
                    float(per_pass))
        raise ValueError(f"unknown phase {phase!r}; choices {self.PHASES}")

    def round_attribution(self, phase: str, *, rows: int, tokens: int,
                          ctx: int, wall_s: float) -> Dict[str, float]:
        """One round's ledger entry: achieved TFLOP/s and GB/s, MFU,
        HBM-bandwidth utilization, and the binding-roof verdict. Pure —
        the tier-1 reconciliation test recomputes flight records through
        this exact function."""
        flops, hbm = self.phase_work(phase, rows=rows, tokens=tokens,
                                     ctx=ctx)
        if wall_s <= 0:
            return {"flops": flops, "hbm_bytes": hbm, "tflops": 0.0,
                    "gbs": 0.0, "mfu": 0.0, "hbm_util": 0.0,
                    "bound": "memory-bound"}
        flop_s, byte_s = flops / wall_s, hbm / wall_s
        mfu = flop_s / self.peak_flops
        util = byte_s / self.peak_bw
        return {
            "flops": flops,
            "hbm_bytes": hbm,
            "tflops": round(flop_s / 1e12, 4),
            "gbs": round(byte_s / 1e9, 2),
            "mfu": round(mfu, 6),
            "hbm_util": round(util, 6),
            "bound": verdict(mfu, util),
        }

    def mixed_attribution(self, *, rows: int, dec_tokens: int, dec_ctx: int,
                          pre_rows: int, pre_tokens: int, pre_ctx: int,
                          wall_s: float) -> Dict[str, float]:
        """One UNIFIED RAGGED round's ledger entry (ISSUE 19): the launch
        did the decode/verify rows' work AND `pre_rows` prompt chunks in
        the same program, so both phases' analytic work sums over ONE
        measured wall. Pure like round_attribution — the flight-record
        reconciliation test recomputes mixed records through this exact
        function (dec_tokens > 1 is a vanilla chunk round: `dec_tokens`
        weight streams; dec_tokens == draft+1 with one stream is the
        verify shape, which rides the decode pricing here because the
        scan steps dominate and the record keeps the raw inputs either
        way)."""
        d_flops, d_hbm = self.phase_work("decode", rows=rows,
                                         tokens=dec_tokens, ctx=dec_ctx)
        p_flops, p_hbm = self.phase_work("prefill", rows=pre_rows,
                                         tokens=pre_tokens, ctx=pre_ctx)
        flops, hbm = d_flops + p_flops, d_hbm + p_hbm
        if wall_s <= 0:
            return {"flops": flops, "hbm_bytes": hbm, "tflops": 0.0,
                    "gbs": 0.0, "mfu": 0.0, "hbm_util": 0.0,
                    "bound": "memory-bound"}
        flop_s, byte_s = flops / wall_s, hbm / wall_s
        mfu = flop_s / self.peak_flops
        util = byte_s / self.peak_bw
        return {
            "flops": flops,
            "hbm_bytes": hbm,
            "tflops": round(flop_s / 1e12, 4),
            "gbs": round(byte_s / 1e9, 2),
            "mfu": round(mfu, 6),
            "hbm_util": round(util, 6),
            "bound": verdict(mfu, util),
        }

    def observe_mixed(self, *, rows: int, dec_tokens: int, dec_ctx: int,
                      pre_rows: int, pre_tokens: int, pre_ctx: int,
                      wall_s: float) -> Dict[str, float]:
        """mixed_attribution + fold into a dedicated "mixed" EWMA key —
        stats() iterates the phase dict, so the mixed view appears beside
        prefill/decode the first time a ragged round harvests and never
        perturbs the alternating phases' EWMAs."""
        att = self.mixed_attribution(
            rows=rows, dec_tokens=dec_tokens, dec_ctx=dec_ctx,
            pre_rows=pre_rows, pre_tokens=pre_tokens, pre_ctx=pre_ctx,
            wall_s=wall_s,
        )
        self._fold("mixed", att)
        return att

    def prefill_saved(self, tokens: int) -> Tuple[float, float]:
        """(FLOPs, seconds) a prefix-cache hit of `tokens` reused tokens
        SAVED: the one-row prefill forward those tokens would have cost
        (average attention context tokens/2 — the from-zero prefill
        convention shared with `prefill_flops`), priced at whichever roof
        binds that forward. Pure float math — the admission path stamps
        it per hit inside the same <1%-of-cadence budget the flight
        record rides (ISSUE 14)."""
        if tokens <= 0:
            return 0.0, 0.0
        flops, hbm = self.phase_work("prefill", rows=1, tokens=tokens,
                                     ctx=tokens // 2)
        return flops, max(flops / self.peak_flops, hbm / self.peak_bw)

    # ------------------------------------------------------------- ledger

    def note_prefill(self, *, rows: int, tokens: int, ctx: int) -> None:
        """Accumulate one dispatched prefill chunk's analytic work; the
        next harvested round flushes the pile against the measured
        inter-harvest wall (chunks dispatch asynchronously — there is no
        honest per-chunk wall without the device profiler, which is what
        /debug/profile is for)."""
        flops, hbm = self.phase_work("prefill", rows=rows, tokens=tokens,
                                     ctx=ctx)
        with self._lock:
            self._pending_prefill_flops += flops
            self._pending_prefill_bytes += hbm

    def flush_prefill(self, interval_s: float) -> Optional[Dict[str, float]]:
        """Attribute accumulated prefill work over the inter-harvest
        interval; None when no chunk was dispatched since the last
        flush."""
        with self._lock:
            flops = self._pending_prefill_flops
            hbm = self._pending_prefill_bytes
            self._pending_prefill_flops = 0.0
            self._pending_prefill_bytes = 0.0
        if flops <= 0 and hbm <= 0:
            return None
        if interval_s <= 0:
            return None
        mfu = flops / interval_s / self.peak_flops
        util = hbm / interval_s / self.peak_bw
        att = {
            "flops": flops, "hbm_bytes": hbm,
            "tflops": round(flops / interval_s / 1e12, 4),
            "gbs": round(hbm / interval_s / 1e9, 2),
            "mfu": round(mfu, 6), "hbm_util": round(util, 6),
            "bound": verdict(mfu, util),
        }
        self._fold("prefill", att)
        return att

    def observe(self, phase: str, *, rows: int, tokens: int, ctx: int,
                wall_s: float) -> Dict[str, float]:
        """round_attribution + fold into the per-phase running view."""
        att = self.round_attribution(phase, rows=rows, tokens=tokens,
                                     ctx=ctx, wall_s=wall_s)
        self._fold(phase, att)
        return att

    def _fold(self, phase: str, att: Dict[str, float]) -> None:
        # Hot path (once per harvested round): no rounding here — the
        # stats() read rounds for presentation.
        a = self.ALPHA
        b = 1.0 - a
        with self._lock:
            ph = self._phases.get(phase)
            if ph is None:
                self._phases[phase] = {
                    "mfu": att["mfu"], "hbm_util": att["hbm_util"],
                    "tflops": att["tflops"], "gbs": att["gbs"],
                    "rounds": 1,
                }
            else:
                ph["mfu"] = b * ph["mfu"] + a * att["mfu"]
                ph["hbm_util"] = b * ph["hbm_util"] + a * att["hbm_util"]
                ph["tflops"] = b * ph["tflops"] + a * att["tflops"]
                ph["gbs"] = b * ph["gbs"] + a * att["gbs"]
                ph["rounds"] += 1

    def stats(self) -> Dict[str, object]:
        """The /metrics `serving.perf` payload: the model's pricing
        assumptions + per-phase EWMAs of the live roofline position."""
        with self._lock:
            phases = {
                k: {"mfu": round(v["mfu"], 6),
                    "hbm_util": round(v["hbm_util"], 6),
                    "tflops": round(v["tflops"], 4),
                    "gbs": round(v["gbs"], 2),
                    "rounds": v["rounds"],
                    "bound": verdict(v["mfu"], v["hbm_util"])}
                for k, v in self._phases.items()
            }
        return {
            "device_kind": self.device_kind,
            "peak_tflops": round(self.peak_flops / 1e12, 3),
            "peak_hbm_gbs": round(self.peak_bw / 1e9, 1),
            "param_bytes": self.param_bytes,
            "weight_bits": self.weight_bits,
            "kv_quant": self.kv_quant or "",
            "kv_layout": self.kv_layout,
            "tp": self.tp,
            "phases": phases,
        }
