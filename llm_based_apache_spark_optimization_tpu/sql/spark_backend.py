"""PySpark-backed SQL executor — the reference's engine, behind the same
protocol.

Used when `pyspark` is importable (it is not in the CI image; the SQLite
backend is the default there). Mirrors the reference's exact Spark usage:
`read.csv(header=True, inferSchema=True)` (`Flask/app.py:95`),
`createOrReplaceTempView` (`:113`), `spark.sql` (`:115`), and the
`coalesce(1)` single-file CSV export with part-file rename (`:119-129`).

The py4j-independent logic — schema tuple building, the part-file rename
dance, the empty-result header-only export — lives in module functions so
tests can drive it without a JVM (tests/test_sql.py uses a fake session;
a `pytest.importorskip("pyspark")` integration test covers the real one).
"""

from __future__ import annotations

import shutil
import tempfile
from pathlib import Path
from typing import Sequence, Tuple

from .backend import ResultTable, TableSchema


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401
        return True
    except ImportError:
        return False


def schema_from_dtypes(dtypes: Sequence[Tuple[str, str]]) -> TableSchema:
    """`df.dtypes` [(name, spark_type), ...] -> TableSchema.

    The reference builds its model-facing schema string from exactly this
    list (`FastAPI/app.py:79`); the empty-dataframe case (no columns) must
    yield empty tuples, not a zip() crash.
    """
    cols, types = zip(*dtypes) if dtypes else ((), ())
    return TableSchema(columns=tuple(cols), dtypes=tuple(types))


def collect_part_file(tmp_dir: str | Path, out_path: str | Path) -> str:
    """Move the single `part-*` file of a coalesce(1) CSV write to its final
    name and clean up the Spark output directory (the rename dance of
    reference `Flask/app.py:119-129`). Raises FileNotFoundError if Spark
    produced no part file (failed/empty write)."""
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = Path(tmp_dir)
    part = next(
        (p for p in sorted(tmp.iterdir()) if p.name.startswith("part-")), None
    )
    if part is None:
        raise FileNotFoundError(f"no part-* file under {tmp}")
    shutil.move(str(part), str(out))
    shutil.rmtree(tmp, ignore_errors=True)
    return str(out)


def write_header_only_csv(columns: Sequence[str], out_path: str | Path) -> str:
    """Empty result set: a successful query still exports a headed CSV
    (same shape the SQLite backend produces, incl. quoting) — Spark's
    createDataFrame([]) cannot infer types, so this path skips the JVM."""
    import csv

    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("w", newline="") as f:
        csv.writer(f).writerow(columns)
    return str(out)


class SparkBackend:
    def __init__(self, app_name: str = "llm-spark-opt-tpu", spark=None):
        """`spark=None` builds/reuses the real session (requires pyspark);
        tests inject a stand-in session through the parameter."""
        if spark is None:
            from pyspark.sql import SparkSession

            spark = SparkSession.builder.appName(app_name).getOrCreate()
        self._spark = spark
        self._dfs = {}

    def load_csv(self, path: str, view_name: str = "temp_view") -> TableSchema:
        if not Path(path).exists():
            raise FileNotFoundError(path)
        df = self._spark.read.csv(path, header=True, inferSchema=True)
        df.createOrReplaceTempView(view_name)
        self._dfs[view_name] = df
        return schema_from_dtypes(df.dtypes)

    def execute(self, sql: str) -> ResultTable:
        df = self._spark.sql(sql)
        rows = [tuple(r) for r in df.collect()]
        return ResultTable(columns=tuple(df.columns), rows=rows)

    def write_csv(self, result: ResultTable, out_path: str) -> str:
        if not result.rows:
            return write_header_only_csv(result.columns, out_path)
        # Re-create a DataFrame for the Spark write path so the export uses
        # the engine's own CSV writer (coalesce(1) + part-file rename).
        df = self._spark.createDataFrame(result.rows, schema=list(result.columns))
        tmp = tempfile.mkdtemp(prefix="spark_out_")
        df.coalesce(1).write.mode("overwrite").option("header", "true").csv(tmp)
        return collect_part_file(tmp, out_path)
