"""PySpark-backed SQL executor — the reference's engine, behind the same
protocol.

Used when `pyspark` is importable (it is not in the CI image; the SQLite
backend is the default there). Mirrors the reference's exact Spark usage:
`read.csv(header=True, inferSchema=True)` (`Flask/app.py:95`),
`createOrReplaceTempView` (`:113`), `spark.sql` (`:115`), and the
`coalesce(1)` single-file CSV export with part-file rename (`:119-129`).
"""

from __future__ import annotations

import shutil
import tempfile
from pathlib import Path

from .backend import ResultTable, TableSchema


def spark_available() -> bool:
    try:
        import pyspark  # noqa: F401
        return True
    except ImportError:
        return False


class SparkBackend:
    def __init__(self, app_name: str = "llm-spark-opt-tpu"):
        from pyspark.sql import SparkSession

        self._spark = SparkSession.builder.appName(app_name).getOrCreate()
        self._dfs = {}

    def load_csv(self, path: str, view_name: str = "temp_view") -> TableSchema:
        if not Path(path).exists():
            raise FileNotFoundError(path)
        df = self._spark.read.csv(path, header=True, inferSchema=True)
        df.createOrReplaceTempView(view_name)
        self._dfs[view_name] = df
        cols, dtypes = zip(*df.dtypes) if df.dtypes else ((), ())
        return TableSchema(columns=tuple(cols), dtypes=tuple(dtypes))

    def execute(self, sql: str) -> ResultTable:
        df = self._spark.sql(sql)
        rows = [tuple(r) for r in df.collect()]
        return ResultTable(columns=tuple(df.columns), rows=rows)

    def write_csv(self, result: ResultTable, out_path: str) -> str:
        # Re-create a DataFrame for the Spark write path so the export uses
        # the engine's own CSV writer (coalesce(1) + part-file rename).
        out = Path(out_path)
        out.parent.mkdir(parents=True, exist_ok=True)
        if not result.rows:
            # createDataFrame([]) cannot infer types; an empty result is a
            # successful query — write the header-only CSV directly (same
            # output shape the SQLite backend produces, incl. quoting).
            import csv

            with out.open("w", newline="") as f:
                csv.writer(f).writerow(result.columns)
            return str(out)
        df = self._spark.createDataFrame(result.rows, schema=list(result.columns))
        tmp = tempfile.mkdtemp(prefix="spark_out_")
        df.coalesce(1).write.mode("overwrite").option("header", "true").csv(tmp)
        part = next(p for p in Path(tmp).iterdir() if p.name.startswith("part-"))
        shutil.move(str(part), str(out))
        shutil.rmtree(tmp, ignore_errors=True)
        return str(out)
