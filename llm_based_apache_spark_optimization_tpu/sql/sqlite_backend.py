"""SQLite-backed SQL executor with Spark-compatible schema naming.

The in-tree default engine: no JVM, no py4j, no external processes. Type
inference mirrors Spark's `inferSchema=True` naming so the schema string the
NL→SQL model sees is identical to what Spark would produce for the same CSV
(reference `Flask/app.py:95-98`): integers → `int`/`bigint`, decimals →
`double`, ISO date-times → `timestamp`, everything else → `string`.

Dialect note: the generated workloads (SELECT/WHERE/GROUP BY/ORDER
BY/aggregates — the entire query surface in the reference's eval suite)
execute identically on SQLite and Spark SQL; engine-specific SQL surfaces the
same way it does in the reference — as an execution error routed to the
error-analysis model.
"""

from __future__ import annotations

import csv
import re
import sqlite3
from pathlib import Path
from typing import List, Optional, Tuple

from .backend import ResultTable, TableSchema

_INT32_MAX = 2**31 - 1
_TS_RE = re.compile(r"^\d{4}-\d{2}-\d{2}([ T]\d{2}:\d{2}(:\d{2}(\.\d+)?)?)?$")


def _infer_dtype(values: List[str]) -> str:
    """Spark-inferSchema-compatible dtype name for one column's strings."""
    saw_float = saw_int = saw_ts = False
    max_abs = 0
    for v in values:
        if v == "" or v is None:
            continue
        if not v.isascii() or "_" in v:
            # CPython's int()/float() accept '_' separators and non-ASCII
            # digits; Spark's inferSchema does not, and neither does the
            # native fast path (strtoll/strtod in native/src/csvscan.cpp).
            # Classify them as strings so all three agree.
            return "string"
        try:
            i = int(v)
            saw_int = True
            max_abs = max(max_abs, abs(i))
            continue
        except ValueError:
            pass
        try:
            float(v)
            saw_float = True
            continue
        except ValueError:
            pass
        if _TS_RE.match(v.strip()):
            saw_ts = True
            continue
        return "string"
    if saw_ts and not (saw_int or saw_float):
        return "timestamp"
    if saw_float:
        return "double"
    if saw_int:
        return "bigint" if max_abs > _INT32_MAX else "int"
    return "string"


_AFFINITY = {"int": "INTEGER", "bigint": "INTEGER", "double": "REAL",
             "timestamp": "TEXT", "string": "TEXT"}


class SQLiteBackend:
    """One backend instance = one session of views over an in-memory DB."""

    def __init__(self, db_path: str = ":memory:"):
        self._conn = sqlite3.connect(db_path, check_same_thread=False)

    def load_csv(self, path: str, view_name: str = "temp_view") -> TableSchema:
        p = Path(path)
        if not p.exists():
            raise FileNotFoundError(str(p))
        with p.open(newline="") as f:
            reader = csv.reader(f)
            try:
                header = next(reader)
            except StopIteration:
                raise ValueError(f"empty CSV: {path}")
            rows = list(reader)
        # Inference pass: the C++ scanner (native/src/csvscan.cpp) is the
        # fast path — this is the role of Spark's inferSchema native scan in
        # the reference (SURVEY.md §3.1). The Python pass below is the
        # behavioral reference and the fallback (no toolchain / ragged rows).
        from ..native import csv_scan

        scanned = csv_scan(p)
        if scanned is not None and len(scanned[0]) == len(header):
            dtypes = tuple(scanned[0])
        else:
            dtypes = tuple(
                _infer_dtype([r[i] if i < len(r) else "" for r in rows])
                for i in range(len(header))
            )
        cols = ", ".join(
            f'"{c}" {_AFFINITY[t]}' for c, t in zip(header, dtypes)
        )
        cur = self._conn.cursor()
        cur.execute(f'DROP TABLE IF EXISTS "{view_name}"')
        cur.execute(f'CREATE TABLE "{view_name}" ({cols})')
        placeholders = ", ".join("?" * len(header))
        norm = [
            tuple((r[i] if i < len(r) else None) if (i < len(r) and r[i] != "") else None
                  for i in range(len(header)))
            for r in rows
        ]
        cur.executemany(f'INSERT INTO "{view_name}" VALUES ({placeholders})', norm)
        self._conn.commit()
        return TableSchema(columns=tuple(header), dtypes=dtypes)

    def set_read_only(self) -> None:
        """Freeze the session: further statements may only read (sqlite
        `query_only` pragma). Used by eval execution-match scoring, which
        runs MODEL-GENERATED SQL against a shared fixture — a DELETE/DROP
        slipping through a string-level guard must still be refused by the
        engine itself."""
        self._conn.execute("PRAGMA query_only = ON")

    def execute(self, sql: str) -> ResultTable:
        cur = self._conn.cursor()
        cur.execute(sql)
        columns = tuple(d[0] for d in cur.description) if cur.description else ()
        return ResultTable(columns=columns, rows=cur.fetchall())

    def write_csv(self, result: ResultTable, out_path: str) -> str:
        out = Path(out_path)
        out.parent.mkdir(parents=True, exist_ok=True)
        with out.open("w", newline="") as f:
            w = csv.writer(f)
            w.writerow(result.columns)
            w.writerows(result.rows)
        return str(out)

    def close(self) -> None:
        self._conn.close()
