"""SQL execution backends: CSV → temp_view → SQL → single-file CSV export."""

from .backend import (  # noqa: F401
    ResilientSQLBackend,
    ResultTable,
    SQLBackend,
    TableSchema,
    is_transient_sql_error,
)
from .spark_backend import SparkBackend, spark_available  # noqa: F401
from .sqlite_backend import SQLiteBackend  # noqa: F401


def default_backend() -> SQLBackend:
    """Spark when installed (the reference's engine), else in-tree SQLite."""
    if spark_available():
        return SparkBackend()
    return SQLiteBackend()
