"""SQL execution backend protocol: CSV → temp view → SQL → CSV out.

This is the capability surface the reference gets from Apache Spark via py4j
(reference `Flask/app.py:95-129`, `FastAPI/app.py:68-133`): read a CSV with
header+schema inference, expose its schema as `"col (dtype)"` lines (the
text-to-SQL model's system prompt is built from exactly that string —
`FastAPI/app.py:79,85-89`), register it as the temp view `temp_view`, run a
SQL string against it, and export the result as ONE headed CSV file
(Spark's `coalesce(1)` + part-file rename dance, `FastAPI/app.py:118-133`).

Two implementations:
  - SQLiteBackend (sql/sqlite_backend.py): in-tree default, zero external
    engines — stdlib sqlite3 with Spark-compatible schema naming.
  - SparkBackend (sql/spark_backend.py): the real thing when pyspark is
    importable; the north star keeps Spark as the consumer of TPU-generated
    SQL (SURVEY.md §2.3).
"""

from __future__ import annotations

import dataclasses
import random
from typing import List, Optional, Protocol, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class TableSchema:
    """Column names + Spark-style dtype names (bigint/double/string/...)."""

    columns: Tuple[str, ...]
    dtypes: Tuple[str, ...]

    def prompt_lines(self) -> str:
        """The exact schema string fed to the NL→SQL system prompt
        (reference `FastAPI/app.py:79`)."""
        return "\n".join(f"{c} ({t})" for c, t in zip(self.columns, self.dtypes))


@dataclasses.dataclass(frozen=True)
class ResultTable:
    columns: Tuple[str, ...]
    rows: List[Tuple]


class SQLBackend(Protocol):
    def load_csv(self, path: str, view_name: str = "temp_view") -> TableSchema:
        """Read a headed CSV, infer types, register as `view_name`."""
        ...

    def execute(self, sql: str) -> ResultTable:
        """Run SQL against registered views; raises on engine errors."""
        ...

    def write_csv(self, result: ResultTable, out_path: str) -> str:
        """Write result as ONE headed CSV file (coalesce(1) semantics)."""
        ...


def is_transient_sql_error(e: BaseException) -> bool:
    """Infra-shaped SQL failures worth retrying (and breaker-counting):
    injected chaos faults, sqlite lock/busy contention, py4j/Spark
    connection drops. A syntax/semantic error is DETERMINISTIC — retrying
    replays the same failure and must instead go straight to the
    error-analysis path."""
    from ..utils.faults import InjectedFault

    if isinstance(e, InjectedFault):
        return True
    import sqlite3

    if isinstance(e, sqlite3.OperationalError):
        msg = str(e).lower()
        return "locked" in msg or "busy" in msg
    # Spark's py4j surfaces dead-gateway errors as generic Py4JError /
    # ConnectionError shapes; match by type name so the sqlite-only image
    # needs no pyspark import.
    if isinstance(e, ConnectionError):
        return True
    return type(e).__name__ in ("Py4JNetworkError", "Py4JJavaError") and \
        "connection" in str(e).lower()


class ResilientSQLBackend:
    """SQLBackend wrapper: fault injection seams + transient-error retry +
    a circuit breaker around `execute()` (serve/resilience.py).

    The retry replays only failures `is_transient_sql_error` classifies as
    infrastructure (the queries are SELECTs over temp views — idempotent by
    construction); deterministic engine errors propagate immediately to the
    error-analysis stage, exactly as before. The breaker counts only those
    infra failures: when the engine itself is down, requests shed with
    `CircuitOpen` instead of each burning a full retry ladder, and the
    pipeline degrades along its existing SQL-failure path. Chaos seams:
    `sql:load`, `sql:exec`, and the duration-valued `sql:stall`
    (utils/faults.py)."""

    def __init__(self, inner: SQLBackend, retry=None, breaker=None,
                 rng: Optional[random.Random] = None):
        from ..serve.resilience import CircuitBreaker, RetryPolicy

        self.inner = inner
        self._retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay_s=0.02, max_delay_s=0.5,
        )
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            "sql backend", failure_threshold=5, reset_after_s=10.0,
        )
        self._rng = rng if rng is not None else random.Random()

    def load_csv(self, path: str, view_name: str = "temp_view") -> TableSchema:
        from ..utils import tracing
        from ..utils.faults import FAULTS

        # No retry: load failures (missing file, malformed CSV) are
        # deterministic; the seam exists so chaos runs can fail the load
        # boundary too.
        with tracing.span("sql.load", view=view_name):
            FAULTS.check("sql:load")
            return self.inner.load_csv(path, view_name)

    def execute(self, sql: str) -> ResultTable:
        from ..utils import tracing
        from ..utils.faults import FAULTS

        if not self._breaker.allow():
            raise self._breaker.shed()

        def attempt() -> ResultTable:
            # `sql:stall:p:secs` (duration-valued): a SQL engine that is
            # up but SLOW — the check sleeps, then the query runs, so
            # caller-side deadlines see real elapsed time.
            FAULTS.check("sql:stall")
            FAULTS.check("sql:exec")
            # Per-class SQL error sites (ISSUE 20): each raises a
            # REPRESENTATIVE engine error for one branch of the repair
            # taxonomy — syntax/schema are deterministic engine answers
            # (no retry, breaker records success), transient is
            # lock-contention-shaped (retried, breaker-counted).
            FAULTS.check("sql:syntax")
            FAULTS.check("sql:schema")
            FAULTS.check("sql:transient")
            return self.inner.execute(sql)

        # The span covers the whole retry ladder (what the REQUEST paid),
        # not one attempt — retries are an attr, not separate spans.
        with tracing.span("sql.exec"):
            try:
                out = self._retry.call(
                    attempt, retryable=is_transient_sql_error, rng=self._rng,
                )
            except Exception as e:
                if is_transient_sql_error(e):
                    self._breaker.record_failure()
                else:
                    # The engine answered (with an error): it is up.
                    self._breaker.record_success()
                raise
            self._breaker.record_success()
            return out

    def write_csv(self, result: ResultTable, out_path: str) -> str:
        from ..utils import tracing

        with tracing.span("sql.write_csv"):
            return self.inner.write_csv(result, out_path)
