"""SQL execution backend protocol: CSV → temp view → SQL → CSV out.

This is the capability surface the reference gets from Apache Spark via py4j
(reference `Flask/app.py:95-129`, `FastAPI/app.py:68-133`): read a CSV with
header+schema inference, expose its schema as `"col (dtype)"` lines (the
text-to-SQL model's system prompt is built from exactly that string —
`FastAPI/app.py:79,85-89`), register it as the temp view `temp_view`, run a
SQL string against it, and export the result as ONE headed CSV file
(Spark's `coalesce(1)` + part-file rename dance, `FastAPI/app.py:118-133`).

Two implementations:
  - SQLiteBackend (sql/sqlite_backend.py): in-tree default, zero external
    engines — stdlib sqlite3 with Spark-compatible schema naming.
  - SparkBackend (sql/spark_backend.py): the real thing when pyspark is
    importable; the north star keeps Spark as the consumer of TPU-generated
    SQL (SURVEY.md §2.3).
"""

from __future__ import annotations

import dataclasses
from typing import List, Protocol, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class TableSchema:
    """Column names + Spark-style dtype names (bigint/double/string/...)."""

    columns: Tuple[str, ...]
    dtypes: Tuple[str, ...]

    def prompt_lines(self) -> str:
        """The exact schema string fed to the NL→SQL system prompt
        (reference `FastAPI/app.py:79`)."""
        return "\n".join(f"{c} ({t})" for c, t in zip(self.columns, self.dtypes))


@dataclasses.dataclass(frozen=True)
class ResultTable:
    columns: Tuple[str, ...]
    rows: List[Tuple]


class SQLBackend(Protocol):
    def load_csv(self, path: str, view_name: str = "temp_view") -> TableSchema:
        """Read a headed CSV, infer types, register as `view_name`."""
        ...

    def execute(self, sql: str) -> ResultTable:
        """Run SQL against registered views; raises on engine errors."""
        ...

    def write_csv(self, result: ResultTable, out_path: str) -> str:
        """Write result as ONE headed CSV file (coalesce(1) semantics)."""
        ...
