"""Ring attention (context parallelism) vs the dense causal reference.

Runs on the 8-device virtual CPU mesh (conftest.py). The dense golden is
ops.attention.gqa_attention with a full causal mask — the ring result must
match it to float tolerance for every mesh layout (pure sp, sp×dp, and
sp×tp×dp composition)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_based_apache_spark_optimization_tpu.ops import (
    attention_mask,
    gqa_attention,
    ring_gqa_attention,
)
from llm_based_apache_spark_optimization_tpu.parallel import make_mesh


def _rand_qkv(key, b, t, n, kh, h, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, n, h), dtype)
    k = jax.random.normal(kk, (b, t, kh, h), dtype)
    v = jax.random.normal(kv, (b, t, kh, h), dtype)
    return q, k, v


def _dense_golden(q, k, v, positions, sliding_window=None):
    # k/v arrive sequence-major [B, T, K, H] (the ring interface); the dense
    # reference reads the head-major cache layout [B, K, S, H].
    mask = attention_mask(positions, k.shape[1], sliding_window)
    return gqa_attention(
        q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), mask
    )


@pytest.mark.parametrize(
    "dp,sp,tp",
    [(1, 8, 1), (2, 4, 1), (1, 4, 2), (2, 2, 2)],
    ids=["sp8", "dp2sp4", "sp4tp2", "dp2sp2tp2"],
)
@pytest.mark.slow
def test_ring_matches_dense(dp, sp, tp):
    b, t, n, kh, h = 2 * dp, 8 * sp, 4, 2, 16
    q, k, v = _rand_qkv(jax.random.key(0), b, t, n, kh, h)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    golden = _dense_golden(q, k, v, positions)
    mesh = make_mesh(dp=dp, sp=sp, tp=tp)
    out = ring_gqa_attention(mesh, q, k, v, positions)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden), atol=2e-5)


def test_ring_sliding_window():
    b, t, n, kh, h = 2, 64, 4, 4, 8
    q, k, v = _rand_qkv(jax.random.key(1), b, t, n, kh, h)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    golden = _dense_golden(q, k, v, positions, sliding_window=16)
    mesh = make_mesh(sp=8)
    out = ring_gqa_attention(mesh, q, k, v, positions, sliding_window=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden), atol=2e-5)


def test_ring_rejects_indivisible_seq():
    mesh = make_mesh(sp=8)
    q = jnp.zeros((1, 12, 4, 8))
    kv = jnp.zeros((1, 12, 2, 8))
    pos = jnp.zeros((1, 12), jnp.int32)
    with pytest.raises(ValueError):
        ring_gqa_attention(mesh, q, kv, kv, pos)


def test_ring_under_jit_bf16():
    # The engine calls this inside jit with bf16 activations; make sure the
    # f32 online-softmax accumulators keep the result close to the f32 dense
    # reference even with bf16 inputs.
    b, t, n, kh, h = 1, 32, 8, 2, 16
    q, k, v = _rand_qkv(jax.random.key(2), b, t, n, kh, h)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    golden = _dense_golden(q, k, v, positions)
    mesh = make_mesh(sp=4, tp=2)
    fn = jax.jit(
        lambda q, k, v, p: ring_gqa_attention(
            mesh, q, k, v, p
        )
    )
    out = fn(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16), v.astype(jnp.bfloat16), positions)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(golden), atol=0.05
    )


@pytest.mark.slow
def test_sp_generate_matches_unsharded(tiny_model):
    """Full generate with ring prefill on a dp×sp×tp mesh == unsharded greedy."""
    from llm_based_apache_spark_optimization_tpu.engine import InferenceEngine

    cfg, params = tiny_model
    mesh = make_mesh(dp=2, sp=2, tp=2)
    prompts = [[1, 5, 9, 2], [1, 7, 3], [1, 11, 13, 17, 4], [1, 2, 3]]
    ref = InferenceEngine(cfg, params, prompt_bucket=8).generate(
        prompts, max_new_tokens=6
    )
    got = InferenceEngine(cfg, params, prompt_bucket=8, mesh=mesh).generate(
        prompts, max_new_tokens=6
    )
    assert got == ref
