"""Grammar-constrained SQL decoding (constrain/): the DFA and the reference
parser hold each other honest, the token-mask precompute happens exactly
once per (tokenizer, grammar) pair, and the engine + scheduler emit ONLY
grammar-valid Spark SQL when a constraint rides the request — including a
100%-grammar-valid end-to-end evalh run on the fixture suite.
"""

import random

import numpy as np
import pytest

from llm_based_apache_spark_optimization_tpu.constrain import (
    get_constraint,
    is_valid_spark_sql,
    parse_spark_sql,
    spark_sql_dfa,
)
from llm_based_apache_spark_optimization_tpu.constrain import masks as masks_mod
from llm_based_apache_spark_optimization_tpu.constrain.parser import (
    SqlSyntaxError,
)
from llm_based_apache_spark_optimization_tpu.evalh.fixtures import (
    FOUR_QUERY_SUITE,
    SINGLE_COMPLEX_CASE,
    TAXI_COLUMNS,
)
from llm_based_apache_spark_optimization_tpu.tokenizer import ByteTokenizer

EOS = 2
FIXTURE_SQL = [c.expected_sql for c in FOUR_QUERY_SUITE] + [
    SINGLE_COMPLEX_CASE.expected_sql
]

INVALID_SQL = [
    "",
    "hello world",
    "SELECT FROM taxi;",
    "DROP TABLE taxi;",
    "SELECT * FROM taxi WHERE",
    "SELECT * FROM from;",                      # keyword as identifier
    "SELECT * FROM taxi GROUP BY",
    "SELECT * FROM taxi;; --",
    "INSERT INTO taxi VALUES (1)",
    "SELECT a FROM t WHERE b > 2AND c < 1",     # glued number+keyword
    "SELECT Select FROM taxi",                  # keyword alias position
]


@pytest.fixture(scope="module")
def tok():
    return ByteTokenizer()


@pytest.fixture(scope="module")
def generic(tok):
    return get_constraint("spark_sql", tok, (EOS,))


@pytest.fixture(scope="module")
def schema(tok):
    return get_constraint(
        {"table": "taxi", "columns": list(TAXI_COLUMNS)}, tok, (EOS,)
    )


# ------------------------------------------------------- DFA vs parser ----


def test_fixture_suite_accepted_by_dfa_and_parser():
    dfa = spark_sql_dfa()
    sdfa = spark_sql_dfa("taxi", tuple(TAXI_COLUMNS))
    for sql in FIXTURE_SQL:
        assert dfa.accepts(sql), sql
        assert sdfa.accepts(sql), sql
        parse_spark_sql(sql)  # must not raise


def test_invalid_sql_rejected_by_both():
    dfa = spark_sql_dfa()
    for sql in INVALID_SQL:
        assert not dfa.accepts(sql), sql
        assert not is_valid_spark_sql(sql), sql


def test_parser_rejects_with_positions():
    with pytest.raises(SqlSyntaxError, match="expected FROM"):
        parse_spark_sql("SELECT a b FROM taxi")
    with pytest.raises(SqlSyntaxError, match="trailing"):
        parse_spark_sql("SELECT a FROM taxi; extra")


def test_null_and_like_predicates_accepted_by_both():
    """ROADMAP grammar-coverage slice: IS [NOT] NULL and [NOT] LIKE are in
    the language (grammar + parser; the token-mask compiler needed no
    changes — the keywords are plain letters already in the alphabet)."""
    dfa = spark_sql_dfa()
    sdfa = spark_sql_dfa("taxi", tuple(TAXI_COLUMNS))
    good = [
        "SELECT * FROM taxi WHERE tip_amount IS NULL;",
        "SELECT * FROM taxi WHERE tip_amount IS NOT NULL",
        "SELECT VendorID FROM taxi WHERE extra LIKE 'a%_b'",
        "SELECT VendorID FROM taxi WHERE extra NOT LIKE 'x%' "
        "AND fare_amount > 2",
        "select trip_distance from taxi where trip_distance is not null "
        "or extra like '%5' order by trip_distance desc limit 3;",
        "SELECT COUNT(*) AS n FROM taxi "
        "GROUP BY VendorID HAVING extra IS NULL",
    ]
    for sql in good:
        assert dfa.accepts(sql), sql
        assert sdfa.accepts(sql), sql
        parse_spark_sql(sql)  # must not raise


def test_null_and_like_invalid_forms_rejected_by_both():
    dfa = spark_sql_dfa()
    bad = [
        "SELECT * FROM taxi WHERE IS NULL",        # no operand
        "SELECT * FROM taxi WHERE a LIKE b",       # pattern must be a string
        "SELECT * FROM taxi WHERE a LIKE",         # missing pattern
        "SELECT * FROM taxi WHERE a IS",           # missing NULL
        "SELECT * FROM taxi WHERE a NOT NULL",     # NOT without LIKE/IS
        "SELECT * FROM taxi WHERE a ISNULL",       # keywords must separate
        "SELECT null FROM taxi",                   # NULL is reserved now
        "SELECT is FROM taxi",                     # IS is reserved now
    ]
    for sql in bad:
        assert not dfa.accepts(sql), sql
        assert not is_valid_spark_sql(sql), sql


def test_in_and_between_predicates_accepted_by_both():
    """Grammar-breadth slice (ISSUE 16 satellite): [NOT] IN (...) and
    [NOT] BETWEEN lo AND hi join the predicate language (grammar +
    parser; the token-mask compiler again needed no changes — the
    keywords are plain letters already in the alphabet)."""
    dfa = spark_sql_dfa()
    sdfa = spark_sql_dfa("taxi", tuple(TAXI_COLUMNS))
    good = [
        "SELECT * FROM taxi WHERE VendorID IN (1, 2, 3)",
        "SELECT * FROM taxi WHERE extra NOT IN ('a', 'b');",
        "SELECT VendorID FROM taxi WHERE fare_amount BETWEEN 2 AND 10",
        "SELECT VendorID FROM taxi WHERE fare_amount NOT BETWEEN -1 "
        "AND 0.5 AND extra = 'x'",
        "select * from taxi where trip_distance between 0.5 and 9.5 "
        "or VendorID in (1) order by trip_distance limit 3;",
        "SELECT COUNT(*) AS n FROM taxi "
        "GROUP BY VendorID HAVING VendorID IN (1, 2)",
        "SELECT * FROM taxi WHERE extra IN (tip_amount, 'c', 3)",
    ]
    for sql in good:
        assert dfa.accepts(sql), sql
        assert sdfa.accepts(sql), sql
        parse_spark_sql(sql)  # must not raise


def test_in_and_between_invalid_forms_rejected_by_both():
    dfa = spark_sql_dfa()
    bad = [
        "SELECT * FROM taxi WHERE a IN ()",          # empty list
        "SELECT * FROM taxi WHERE a IN 1, 2",        # parens required
        "SELECT * FROM taxi WHERE a IN (1,)",        # trailing comma
        "SELECT * FROM taxi WHERE a BETWEEN 1",      # missing AND hi
        "SELECT * FROM taxi WHERE a BETWEEN 1 OR 2",  # AND, not OR
        "SELECT * FROM taxi WHERE a BETWEEN AND 2",  # missing lo
        "SELECT * FROM taxi WHERE BETWEEN 1 AND 2",  # no operand
        "SELECT * FROM taxi WHERE a IN (SELECT b FROM taxi)",  # no subquery
        "SELECT * FROM taxi WHERE a IN (SUM(b))",    # no aggregates in list
        "SELECT in FROM taxi",                       # IN is reserved now
        "SELECT between FROM taxi",                  # BETWEEN reserved now
    ]
    for sql in bad:
        assert not dfa.accepts(sql), sql
        assert not is_valid_spark_sql(sql), sql


def test_paren_boolean_grouping_accepted_by_both():
    """Grammar-breadth slice (ISSUE 18 satellite): one level of
    parenthesized boolean grouping in WHERE/HAVING —
    `( pred OR pred ) AND pred` — joins the language (grammar + parser;
    the token-mask compiler again needed no changes — parens are
    punctuation already in the alphabet from IN-lists)."""
    dfa = spark_sql_dfa()
    sdfa = spark_sql_dfa("taxi", tuple(TAXI_COLUMNS))
    good = [
        "SELECT * FROM taxi WHERE ( VendorID = 1 OR VendorID = 2 ) "
        "AND fare_amount > 10",
        "SELECT * FROM taxi WHERE (extra = 'x' OR extra = 'y')",
        "SELECT * FROM taxi WHERE fare_amount > 2 AND "
        "(tip_amount IS NULL OR tip_amount < 1)",
        "SELECT * FROM taxi WHERE (VendorID IN (1, 2) AND extra "
        "LIKE 'a%') OR trip_distance BETWEEN 0.5 AND 2",
        "select * from taxi where (vendorid = 1) and (vendorid = 2) "
        "order by trip_distance limit 3;",
        "SELECT COUNT(*) AS n FROM taxi GROUP BY VendorID "
        "HAVING (VendorID = 1 OR VendorID = 2) AND COUNT(*) > 5",
    ]
    for sql in good:
        assert dfa.accepts(sql), sql
        assert sdfa.accepts(sql), sql
        parse_spark_sql(sql)  # must not raise


def test_paren_boolean_invalid_forms_rejected_by_both():
    dfa = spark_sql_dfa()
    bad = [
        "SELECT * FROM taxi WHERE ()",                  # empty group
        "SELECT * FROM taxi WHERE (a = 1",              # unbalanced open
        "SELECT * FROM taxi WHERE a = 1)",              # unbalanced close
        "SELECT * FROM taxi WHERE (a = 1) (b = 2)",     # missing connective
        "SELECT * FROM taxi WHERE (a = 1 OR) AND b = 2",  # dangling OR
        "SELECT * FROM taxi WHERE (AND a = 1)",         # leading connective
        # JOIN..ON keeps a bare predicate: no boolean grouping there.
        "SELECT * FROM taxi JOIN t ON (taxi.a = t.a) WHERE b = 1",
    ]
    for sql in bad:
        assert not dfa.accepts(sql), sql
        assert not is_valid_spark_sql(sql), sql


def test_paren_nesting_depth_is_dfa_bounded():
    """The DFA accepts exactly ONE grouping level (a regular language
    cannot count); the reference parser recurses and accepts deeper
    nesting — leniency in the safe direction (DFA ⊆ parser), asserted
    explicitly so a future grammar change cannot silently flip it."""
    nested = ("SELECT * FROM taxi WHERE ((VendorID = 1 OR VendorID = 2) "
              "AND extra = 'x') OR fare_amount > 9")
    dfa = spark_sql_dfa()
    assert not dfa.accepts(nested)
    assert is_valid_spark_sql(nested)


def test_schema_mode_blocks_unknown_identifiers():
    sdfa = spark_sql_dfa("taxi", tuple(TAXI_COLUMNS))
    # A column not in the schema cannot even be *spelled*.
    assert not sdfa.live_after("SELECT foo ")
    assert not sdfa.accepts("SELECT * FROM not_taxi;")
    # Schema casing plus all-lower/upper variants are allowed.
    assert sdfa.accepts("SELECT VendorID FROM taxi;")
    assert sdfa.accepts("SELECT vendorid FROM TAXI;")
    # Aliases after AS stay generic even in schema mode.
    assert sdfa.accepts("SELECT SUM(fare_amount) AS total FROM taxi;")


def test_random_dfa_walks_parse(generic, schema):
    """Sample completions straight from the token tables (the same masks
    the decode loops apply, including the budget-aware `need` rule) and
    assert EVERY walk is a complete parse under the independent
    recursive-descent parser — the hermetic twin of the engine e2e test.
    Worst-case policy included: always picking the allowed token with the
    LARGEST remaining need must still close within budget."""
    tok = ByteTokenizer()
    rng = random.Random(0)
    for cm in (generic, schema):
        for budget in (cm.min_new_tokens, 24, 60):
            for trial in range(8):
                s = cm.init_state
                rem = budget
                out = []
                while True:
                    allowed = np.where(cm.need[s] <= rem)[0]
                    assert allowed.size, (s, rem)
                    if trial == 0:
                        # Adversarial: maximal-need choice every step.
                        t = int(allowed[np.argmax(cm.need[s][allowed])])
                    else:
                        t = int(rng.choice(list(allowed)))
                    rem -= 1
                    if t == EOS:
                        break
                    out.append(t)
                    s = int(cm.next_state[s, t])
                    assert rem >= 1  # the stop id must still fit
                text = tok.decode(out)
                parse_spark_sql(text)
                assert len(out) < budget


# -------------------------------------------------- mask precompute -------


def test_golden_first_state_mask_byte_tokenizer(tok, generic):
    """From the grammar start state the ONLY legal bytes are whitespace or
    S/s (leading OWS then SELECT) — the golden test for the tokenizer
    classification pass."""
    row = generic.mask[generic.init_state]
    allowed = {i for i in range(tok.vocab_size) if row[i]}
    expected = {tok.n_special + b for b in b" \t\nSs"}
    assert allowed == expected
    # eos is not allowed before anything was generated (start is not
    # accepting)…
    assert EOS not in allowed
    # …but IS allowed once a complete statement has been walked.
    ids = tok.encode(FIXTURE_SQL[0], add_bos=False)
    end = generic.walk(ids)
    assert end is not None and generic.mask[end, EOS]


def test_hf_bpe_golden_classification():
    """ROADMAP open item: the mask compiler classifies tokens via
    per-token decode([id]); byte-fallback BPE merges that decode
    differently in context deserve a golden against a REAL vocab.
    tests/golden/sql_bpe/ holds a small byte-level BPE tokenizer.json
    (trained with the `tokenizers` library on a SQL corpus — multi-char
    merges, leading-space Ġ tokens) plus the pinned per-token
    classification. Regenerate with scripts/regen_tokenizer_golden.py
    after grammar/compiler changes and review the diff."""
    pytest.importorskip("tokenizers")
    import json
    from pathlib import Path

    from llm_based_apache_spark_optimization_tpu.constrain.masks import (
        compile_token_masks,
    )
    from llm_based_apache_spark_optimization_tpu.tokenizer.hf import (
        HFTokenizer,
    )

    gdir = Path(__file__).parent / "golden" / "sql_bpe"
    golden = json.loads((gdir / "tokenizer_golden.json").read_text())
    hft = HFTokenizer(str(gdir / "tokenizer.json"))
    assert hft.vocab_size == golden["vocab_size"]
    assert hft.eos_id == golden["eos_id"]

    cm = compile_token_masks(spark_sql_dfa(), hft, (hft.eos_id,))
    assert cm.init_state == golden["init_state"]
    assert cm.min_new_tokens == golden["min_new_tokens"]
    for rec in golden["tokens"]:
        tid = rec["id"]
        # The exact string the classification pass consumed…
        assert hft._tok.decode([tid], skip_special_tokens=False) \
            == rec["text"], tid
        # …and both classification bits, token for token.
        assert bool(cm.mask[1:, tid].any()) == rec["classified"], \
            (tid, rec["text"])
        assert bool(cm.mask[cm.init_state, tid]) == rec["init_allowed"], \
            (tid, rec["text"])

    # The real-vocab concern in context: a full statement encoded through
    # LEARNED MERGES (not char-by-char) must walk the FSM to a state where
    # the stop id is legal.
    ids = hft.encode(
        "SELECT VendorID FROM taxi WHERE tip_amount IS NULL;",
        add_bos=False,
    )
    end = cm.walk(ids)
    assert end is not None and cm.mask[end, hft.eos_id]
    # And the vocab genuinely contains classified multi-char merges with a
    # leading space (the ByteLevel Ġ decode path) — the shapes a byte
    # tokenizer never exercises.
    assert any(
        len(r["text"]) > 1 and r["text"].startswith(" ") and r["classified"]
        for r in golden["tokens"]
    )


def test_walk_dies_on_invalid_tokens(tok, generic):
    bad = tok.encode("DROP TABLE", add_bos=False)
    assert generic.walk(bad) is None


def test_compile_happens_once_per_pair(tok):
    before = masks_mod.COMPILE_COUNT
    a = get_constraint("spark_sql", tok, (EOS,))
    b = get_constraint("spark_sql", tok, (EOS,))
    assert a is b
    assert masks_mod.COMPILE_COUNT == before  # module fixtures compiled it
    # A different tokenizer identity compiles its own tables.
    other = ByteTokenizer(n_special=4, pad_id=0, bos_id=1, eos_id=2)
    c = get_constraint("spark_sql", other, (EOS,))
    assert c is not a
    assert masks_mod.COMPILE_COUNT == before + 1


def test_min_new_tokens_and_need_sanity(generic):
    # Shortest parse + stop id: "SELECT * FROM <c>;"-shaped, byte tokens.
    assert generic.min_new_tokens == int(generic.dist[generic.init_state]) + 1
    assert 10 < generic.min_new_tokens < 32
    # Every live masked transition carries a finite finishing cost >= 1.
    live = generic.mask
    assert (generic.need[live] >= 1).all()
    assert (generic.need[live] < masks_mod._INF).all()
    # Sentinel row 0: everything allowed at any budget.
    assert generic.mask[0].all() and (generic.need[0] == 1).all()


def test_device_tables_pad_to_model_vocab(generic, tok):
    tabs = generic.device_tables(320)
    assert tabs["need"].shape == (generic.num_states, 320)
    assert tabs["next"].shape == (generic.num_states, 320)
    # Sentinel row stays all-allowed across the padded width; grammar rows
    # mask everything past the tokenizer vocab (huge need).
    need = np.asarray(tabs["need"])
    assert (need[0] == 1).all()
    assert (need[1:, tok.vocab_size:] > 10**6).all()
    # Cached per width.
    assert generic.device_tables(320) is tabs
    with pytest.raises(ValueError, match="model vocab"):
        generic.device_tables(tok.vocab_size - 1)


def test_constraint_requires_in_vocab_stop_id(tok):
    with pytest.raises(ValueError, match="stop id"):
        get_constraint("spark_sql", tok, (-1,))


def test_reserved_column_names_dropped():
    # A schema column colliding with a keyword is dropped, not compiled in.
    cm_dfa = spark_sql_dfa("t", ("a", "Select"))
    assert cm_dfa.accepts("SELECT a FROM t;")
    assert not cm_dfa.accepts("SELECT Select FROM t;")


def test_non_identifier_column_names_dropped():
    """A CSV header with a space (or punctuation) cannot enter the grammar:
    the decoder could emit it but neither the parser nor a SQL engine
    would accept it — compiling it would break the completions-parse
    guarantee."""
    cm_dfa = spark_sql_dfa("t", ("Trip Distance", "fare"))
    assert cm_dfa.accepts("SELECT fare FROM t;")
    assert not cm_dfa.accepts("SELECT Trip Distance FROM t;")
    with pytest.raises(ValueError, match="no usable identifiers"):
        spark_sql_dfa("t", ("Trip Distance", "a-b"))


def test_constraint_cache_is_lru_bounded(monkeypatch, tok):
    """Schema grammars arrive one per uploaded CSV on a long-running
    server; the compile cache must evict, not grow to OOM — and a
    re-request after eviction recompiles to an EQUAL grammar (same
    fingerprint), which the scheduler's content-based compatibility check
    still serves without a spurious table swap."""
    monkeypatch.setattr(masks_mod, "_CACHE_MAX", 2)
    saved = dict(masks_mod._constraint_cache)
    masks_mod._constraint_cache.clear()
    try:
        a = get_constraint({"table": "t", "columns": ["aa"]}, tok, (EOS,))
        get_constraint({"table": "t", "columns": ["bb"]}, tok, (EOS,))
        get_constraint({"table": "t", "columns": ["cc"]}, tok, (EOS,))
        assert len(masks_mod._constraint_cache) <= 2
        a2 = get_constraint({"table": "t", "columns": ["aa"]}, tok, (EOS,))
        assert a2 is not a  # evicted, recompiled…
        assert a2.fingerprint == a.fingerprint  # …to the same grammar
        assert a2.eos_ids == a.eos_ids
    finally:
        masks_mod._constraint_cache.clear()
        masks_mod._constraint_cache.update(saved)


def test_schema_fingerprints_cannot_collide(tok):
    """('a,b',) and ('a','b') are different schemas and must compile to
    different cached constraints (a separator-join fingerprint collided)."""
    a = get_constraint({"table": "t", "columns": ["ab", "c"]}, tok, (EOS,))
    b = get_constraint({"table": "t", "columns": ["ab_c"]}, tok, (EOS,))
    assert a is not b
    assert a.fingerprint != b.fingerprint


def test_pipeline_falls_back_when_no_column_is_constrainable(tmp_path):
    """LSOT_CONSTRAIN_SQL with a CSV whose headers are all quoted-only
    shapes degrades to an unconstrained run instead of failing."""
    from llm_based_apache_spark_optimization_tpu.app import AppConfig
    from llm_based_apache_spark_optimization_tpu.app.pipeline import Pipeline
    from llm_based_apache_spark_optimization_tpu.serve import (
        FakeBackend,
        GenerationService,
    )
    from llm_based_apache_spark_optimization_tpu.sql import SQLiteBackend

    csv = tmp_path / "odd.csv"
    csv.write_text('"Trip Distance","Total Amount"\n1.0,2.0\n')
    svc = GenerationService()
    svc.register("duckdb-nsql",
                 FakeBackend(lambda p: 'SELECT * FROM temp_view'))
    svc.register("llama3.2", FakeBackend(lambda p: "advice"))
    cfg = AppConfig(input_dir=str(tmp_path), output_dir=str(tmp_path),
                    history_db=":memory:", constrain_sql=True)
    res = Pipeline(svc, SQLiteBackend, None, cfg).run(str(csv), "show all")
    # FakeBackend has no constrain seam: reaching a successful result
    # proves the pipeline dropped the unusable schema constraint.
    assert res.ok


# ------------------------------------------------------ engine decode -----


@pytest.fixture(scope="module")
def tiny_engine():
    import dataclasses

    import jax
    import jax.numpy as jnp

    from llm_based_apache_spark_optimization_tpu.engine import InferenceEngine
    from llm_based_apache_spark_optimization_tpu.models import TINY, init_params

    cfg = dataclasses.replace(TINY, max_seq_len=512)
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    eng = InferenceEngine(cfg, params, stop_ids=(cfg.eos_id,), prompt_bucket=8)
    return cfg, eng


def _detext(tok, cfg, out):
    if out and out[-1] == cfg.eos_id:
        out = out[:-1]
    return tok.decode(out)


def test_engine_constrained_greedy_always_parses(tiny_engine, tok, generic,
                                                 schema):
    cfg, eng = tiny_engine
    prompt = tok.encode("Get all taxis.\nSQL: ", add_bos=True)
    for cm in (generic, schema):
        for budget in (cm.min_new_tokens, 40):
            out = eng.generate([prompt], max_new_tokens=budget,
                               constraint=cm)[0]
            assert len(out) <= budget
            text = _detext(tok, cfg, out)
            assert is_valid_spark_sql(text), text
    # Unconstrained random weights do NOT emit valid SQL — the uplift is
    # real, not a property of the tiny model.
    free = _detext(tok, cfg, eng.generate([prompt], max_new_tokens=40)[0])
    assert not is_valid_spark_sql(free)


def test_engine_rejects_budget_below_shortest_parse(tiny_engine, tok, generic):
    cfg, eng = tiny_engine
    prompt = tok.encode("q", add_bos=True)
    with pytest.raises(ValueError, match="complete constrained parse"):
        eng.generate([prompt], max_new_tokens=4, constraint=generic)


def test_no_vocab_iteration_in_decode_loop(tiny_engine, tok, generic):
    """The hot loop must never re-classify the vocabulary: generating twice
    more compiles nothing (COMPILE_COUNT frozen) and reuses the same
    cached device tables object."""
    cfg, eng = tiny_engine
    prompt = tok.encode("q2", add_bos=True)
    tabs = generic.device_tables(cfg.vocab_size)
    before = masks_mod.COMPILE_COUNT
    for seed in (0, 1):
        eng.generate([prompt], max_new_tokens=24, constraint=generic,
                     seed=seed)
    assert masks_mod.COMPILE_COUNT == before
    assert generic.device_tables(cfg.vocab_size) is tabs


# --------------------------------------------------- scheduler decode -----


def test_scheduler_mixed_constrained_batch(tiny_engine, tok, generic):
    """Constrained and unconstrained requests interleave in ONE slot batch:
    constrained outputs are grammar-valid, the unconstrained neighbour is
    token-for-token what the engine produces alone, and nothing compiles
    per request."""
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )

    cfg, eng = tiny_engine
    con_prompt = tok.encode("Total fare per vendor.\nSQL: ", add_bos=True)
    free_prompt = tok.encode("hello", add_bos=True)
    golden_free = eng.generate([free_prompt], max_new_tokens=6)[0]
    golden_con = eng.generate([con_prompt], max_new_tokens=40,
                              constraint=generic)[0]

    sched = ContinuousBatchingScheduler(
        cfg, eng.params, num_slots=3, decode_chunk=4, prompt_bucket=8,
        stop_ids=(cfg.eos_id,),
    )
    before = masks_mod.COMPILE_COUNT
    decode_fn = sched._decode_fn
    with sched:
        f1 = sched.submit(con_prompt, max_new_tokens=40, constraint=generic)
        f2 = sched.submit(free_prompt, max_new_tokens=6)
        f3 = sched.submit(con_prompt, max_new_tokens=40, constraint=generic)
        o1, o2, o3 = (f.result(timeout=180) for f in (f1, f2, f3))
    for o in (o1, o3):
        assert is_valid_spark_sql(_detext(tok, cfg, o))
    # Greedy constrained decode is deterministic and engine-exact (the
    # engine keeps its stop token, the scheduler strips it).
    stripped = (golden_con[:-1] if golden_con[-1] == cfg.eos_id
                else golden_con)
    assert o1 == stripped and o3 == stripped
    assert o2 == golden_free
    assert masks_mod.COMPILE_COUNT == before  # zero compiles while serving
    assert sched._decode_fn is decode_fn      # one decode program, reused


def test_scheduler_grammar_swap_between_requests(tiny_engine, tok, generic,
                                                 schema):
    """A request with a DIFFERENT grammar waits for constrained slots to
    drain, then installs its tables — both complete, both valid."""
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )

    cfg, eng = tiny_engine
    prompt = tok.encode("List vendors.\nSQL: ", add_bos=True)
    sched = ContinuousBatchingScheduler(
        cfg, eng.params, num_slots=2, decode_chunk=4, prompt_bucket=8,
        stop_ids=(cfg.eos_id,),
    )
    with sched:
        f1 = sched.submit(prompt, max_new_tokens=40, constraint=generic)
        f2 = sched.submit(prompt, max_new_tokens=40, constraint=schema)
        o1, o2 = f1.result(timeout=180), f2.result(timeout=180)
    t1, t2 = _detext(tok, cfg, o1), _detext(tok, cfg, o2)
    assert is_valid_spark_sql(t1)
    assert is_valid_spark_sql(t2)
    # The schema-constrained completion can only name the fixture table.
    assert "taxi" in t2.lower()


def test_scheduler_constraint_guards(tiny_engine, tok, generic):
    from llm_based_apache_spark_optimization_tpu.constrain import CompiledMask
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
        SchedulerBackend,
    )

    cfg, eng = tiny_engine
    prompt = tok.encode("q", add_bos=True)
    plain = ContinuousBatchingScheduler(
        cfg, eng.params, num_slots=2, prompt_bucket=8,
        stop_ids=(cfg.eos_id,),
    )
    with pytest.raises(ValueError, match="complete constrained parse"):
        plain.submit(prompt, max_new_tokens=4, constraint=generic)
    # The speculative scheduler ACCEPTS constrained submits now (the
    # grammar mask is evaluated at every draft position — the old
    # rejection guard is gone), and its budget guard matches the plain
    # scheduler's.
    spec = ContinuousBatchingScheduler(
        cfg, eng.params, num_slots=2, prompt_bucket=8,
        stop_ids=(cfg.eos_id,), speculative_draft=4,
    )
    with pytest.raises(ValueError, match="complete constrained parse"):
        spec.submit(prompt, max_new_tokens=4, constraint=generic)
    with spec:
        out = spec.submit(prompt, max_new_tokens=40,
                          constraint=generic).result(timeout=180)
    assert is_valid_spark_sql(_detext(tok, cfg, out))
    # The backend resolver over a speculative scheduler compiles the spec
    # instead of raising, so validate()/submit() accept constrain=.
    backend = SchedulerBackend.__new__(SchedulerBackend)
    backend.scheduler, backend.tokenizer = spec, tok
    assert isinstance(backend._resolve_constraint("spark_sql"),
                      CompiledMask)


# ------------------------------------ constrained + speculative decode ----


def test_engine_constrained_speculative_parity(tiny_engine, tok, generic,
                                               schema):
    """The composition's correctness contract: constrained+speculative
    greedy output is TOKEN-IDENTICAL to constrained-vanilla decode — the
    grammar mask is evaluated at every draft position, so drafts only
    change how many verify forwards it takes, never what gets emitted.
    Both fixture grammars (generic + schema-locked), both the shortest
    parseable budget and a roomy one; grammar-valid stays 100%."""
    from llm_based_apache_spark_optimization_tpu.engine import (
        InferenceEngine,
    )

    cfg, eng = tiny_engine
    spec = InferenceEngine(cfg, eng.params, stop_ids=(cfg.eos_id,),
                           prompt_bucket=8, speculative_draft=4)
    prompt = tok.encode("Get all taxis.\nSQL: ", add_bos=True)
    for cm in (generic, schema):
        for budget in (cm.min_new_tokens, 40):
            golden = eng.generate([prompt], max_new_tokens=budget,
                                  constraint=cm)[0]
            out = spec.generate([prompt], max_new_tokens=budget,
                                constraint=cm)[0]
            assert out == golden, (budget, golden, out)
            assert spec.last_spec_rounds is not None
            assert 1 <= spec.last_spec_rounds <= len(out)
            assert is_valid_spark_sql(_detext(tok, cfg, out))


@pytest.mark.slow
def test_bpe_vocab_constrained_speculative_parity():
    """Same parity contract over the committed tests/golden/sql_bpe/ BPE
    vocab (multi-char merges, leading-space Ġ tokens — the token shapes a
    byte tokenizer never exercises): one engine pair at the golden
    tokenizer's vocab width, constrained+speculative == constrained
    vanilla, and the output walks the FSM to an accepting state."""
    pytest.importorskip("tokenizers")
    import dataclasses
    from pathlib import Path

    import jax
    import jax.numpy as jnp

    from llm_based_apache_spark_optimization_tpu.engine import (
        InferenceEngine,
    )
    from llm_based_apache_spark_optimization_tpu.models import (
        TINY,
        init_params,
    )
    from llm_based_apache_spark_optimization_tpu.tokenizer.hf import (
        HFTokenizer,
    )

    gdir = Path(__file__).parent / "golden" / "sql_bpe"
    hft = HFTokenizer(str(gdir / "tokenizer.json"))
    cfg = dataclasses.replace(
        TINY, name="tiny-sqlbpe", max_seq_len=512,
        vocab_size=max(TINY.vocab_size, hft.vocab_size),
        eos_id=hft.eos_id,
    )
    params = init_params(cfg, jax.random.key(3), dtype=jnp.float32)
    cm = get_constraint("spark_sql", hft, (hft.eos_id,))
    ref = InferenceEngine(cfg, params, stop_ids=(hft.eos_id,),
                          prompt_bucket=8)
    spec = InferenceEngine(cfg, params, stop_ids=(hft.eos_id,),
                           prompt_bucket=8, speculative_draft=4)
    prompt = hft.encode("SQL: SELECT VendorID FROM taxi; SQL:",
                        add_bos=False)
    golden = ref.generate([prompt], max_new_tokens=40, constraint=cm)[0]
    out = spec.generate([prompt], max_new_tokens=40, constraint=cm)[0]
    assert out == golden
    body = out[:-1] if out and out[-1] == hft.eos_id else out
    end = cm.walk(body)
    assert end is not None and cm.mask[end, hft.eos_id]


def test_scheduler_speculative_mixed_constrained_batch(tiny_engine, tok,
                                                       generic):
    """Mixed constrained/unconstrained requests share ONE speculative
    decode program: constrained outputs are token-identical to
    constrained-vanilla engine decode, the unconstrained neighbour keeps
    plain engine parity, nothing compiles per request, and the acceptance
    counters split by class."""
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )

    cfg, eng = tiny_engine
    con_prompt = tok.encode("Total fare per vendor.\nSQL: ", add_bos=True)
    free_prompt = tok.encode("hello", add_bos=True)
    golden_free = eng.generate([free_prompt], max_new_tokens=6)[0]
    golden_con = eng.generate([con_prompt], max_new_tokens=40,
                              constraint=generic)[0]
    stripped = (golden_con[:-1] if golden_con[-1] == cfg.eos_id
                else golden_con)

    sched = ContinuousBatchingScheduler(
        cfg, eng.params, num_slots=3, prompt_bucket=8,
        stop_ids=(cfg.eos_id,), speculative_draft=4,
    )
    before = masks_mod.COMPILE_COUNT
    decode_fn = sched._decode_fn
    with sched:
        f1 = sched.submit(con_prompt, max_new_tokens=40, constraint=generic)
        f2 = sched.submit(free_prompt, max_new_tokens=6)
        f3 = sched.submit(con_prompt, max_new_tokens=40, constraint=generic)
        o1, o2, o3 = (f.result(timeout=180) for f in (f1, f2, f3))
    assert o1 == stripped and o3 == stripped
    assert o2 == golden_free
    assert is_valid_spark_sql(_detext(tok, cfg, o1))
    assert masks_mod.COMPILE_COUNT == before  # zero compiles while serving
    assert sched._decode_fn is decode_fn      # one decode program, reused
    stats = sched.speculation_stats
    by = stats["by_class"]
    assert by["constrained"]["verify_rounds"] >= 1
    assert by["unconstrained"]["verify_rounds"] >= 1
    # The split partitions the totals exactly.
    for k in ("verify_rounds", "tokens_emitted"):
        assert by["constrained"][k] + by["unconstrained"][k] == stats[k]


def test_constrained_speculation_accepts_drafts(tiny_engine, tok):
    """The speedup exists on constrained fixture traffic, not just in
    principle: a schema-locked grammar forces long identifier/keyword
    runs, the prompt (the DDL, as in real NL→SQL serving) contains those
    identifiers, so prompt-lookup drafts land and constrained
    tokens/round exceeds 1."""
    from llm_based_apache_spark_optimization_tpu.evalh.fixtures import (
        TAXI_COLUMNS,
        TAXI_DDL_SYSTEM,
    )
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )

    cfg, eng = tiny_engine
    cm = get_constraint({"table": "taxi", "columns": list(TAXI_COLUMNS)},
                        tok, (cfg.eos_id,))
    prompt = tok.encode(TAXI_DDL_SYSTEM[:180] + "\nSQL: ", add_bos=True)
    sched = ContinuousBatchingScheduler(
        cfg, eng.params, num_slots=2, prompt_bucket=256,
        stop_ids=(cfg.eos_id,), speculative_draft=4,
    )
    with sched:
        out = sched.submit(prompt, max_new_tokens=64,
                           constraint=cm).result(timeout=300)
    assert is_valid_spark_sql(_detext(tok, cfg, out))
    con = sched.speculation_stats["by_class"]["constrained"]
    assert con["verify_rounds"] >= 1
    assert con["tokens_per_round"] > 1.0, con


def test_speculation_stats_split_partitions_totals(tiny_engine):
    """Host-level contract of the per-class counters: constrained counts
    are a subset of the totals, and by_class reconstructs both classes
    without double counting."""
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )

    cfg, eng = tiny_engine
    sched = ContinuousBatchingScheduler(
        cfg, eng.params, num_slots=2, prompt_bucket=8,
        stop_ids=(cfg.eos_id,), speculative_draft=4,
    )
    with sched._submit_lock:
        sched._spec_rounds, sched._spec_tokens = 10, 25
        sched._spec_rounds_con, sched._spec_tokens_con = 4, 16
    stats = sched.speculation_stats
    assert stats["tokens_per_round"] == 2.5
    assert stats["by_class"]["constrained"] == {
        "verify_rounds": 4, "tokens_emitted": 16,
        "tokens_per_round": 4.0,
        "est_speedup_vs_vanilla": round(4.0 / stats["verify_cost_ratio"], 3),
    }
    assert stats["by_class"]["unconstrained"]["verify_rounds"] == 6
    assert stats["by_class"]["unconstrained"]["tokens_emitted"] == 9


# ------------------------------------------------- service / api seam -----


def test_service_rejects_constrain_on_fake_backend():
    from llm_based_apache_spark_optimization_tpu.serve import (
        FakeBackend,
        GenerationService,
    )

    svc = GenerationService()
    svc.register("fake", FakeBackend(lambda p: "SELECT 1"))
    with pytest.raises(ValueError, match="constrained decoding"):
        svc.generate("fake", "q", constrain="spark_sql")


def test_api_validates_constrain_field(tmp_path):
    from llm_based_apache_spark_optimization_tpu.app import (
        AppConfig,
        create_api_app,
    )
    from llm_based_apache_spark_optimization_tpu.serve import (
        FakeBackend,
        GenerationService,
    )
    from llm_based_apache_spark_optimization_tpu.sql import SQLiteBackend

    svc = GenerationService()
    svc.register("m", FakeBackend(lambda p: "SELECT 1"))
    cfg = AppConfig(input_dir=str(tmp_path / "i"),
                    output_dir=str(tmp_path / "o"), history_db=":memory:")
    client = create_api_app(svc, SQLiteBackend, None, cfg).test_client()
    res = client.post_json("/api/generate", {
        "model": "m", "prompt": "q", "constrain": 42,
    })
    assert res.status == 400
    # Non-string column entries must be the same 400, not a deep TypeError.
    res = client.post_json("/api/generate", {
        "model": "m", "prompt": "q",
        "constrain": {"table": "t", "columns": [1]},
    })
    assert res.status == 400
    # Typo'd keys / empty column lists must not silently degrade to the
    # GENERIC grammar.
    for bad in ({"Table": "t", "Columns": ["a"]}, {},
                {"table": "t", "columns": []}):
        res = client.post_json("/api/generate", {
            "model": "m", "prompt": "q", "constrain": bad,
        })
        assert res.status == 400, bad


    # A well-formed spec against a backend without the seam is the
    # service's ValueError -> 400, not a 500.
    res = client.post_json("/api/generate", {
        "model": "m", "prompt": "q", "constrain": "spark_sql",
    })
    assert res.status == 400
    assert "constrained decoding" in res.json()["error"]
    # Streaming requests hit the same pre-validation (service.validate
    # checks constrain) — a 400, never a mid-stream error line after 200.
    res = client.post_json("/api/generate", {
        "model": "m", "prompt": "q", "constrain": "spark_sql",
        "stream": True,
    })
    assert res.status == 400


def test_normalize_spec_rejects_empty_columns(tok):
    """An explicitly-empty 'columns' must error, not silently fall back to
    the generic grammar."""
    with pytest.raises(ValueError, match="non-empty"):
        get_constraint({"table": "t", "columns": []}, tok, (EOS,))


# ----------------------------------------------------------- evalh e2e ----


def test_evalh_constrained_run_is_100_percent_grammar_valid(tiny_engine, tok):
    """The acceptance criterion end to end: with constrain="spark_sql" and
    greedy decode, EVERY completion in the fixture suite parses under the
    in-tree grammar — on random weights, where unconstrained output is 0%
    valid — and the schema-aware grammar also executes on the sqlite
    fixture oracle."""
    from llm_based_apache_spark_optimization_tpu.evalh.fixtures import (
        TAXI_DDL_SYSTEM,
    )
    from llm_based_apache_spark_optimization_tpu.evalh.harness import (
        evaluate_models,
    )
    from llm_based_apache_spark_optimization_tpu.evalh.report import (
        make_taxi_exec_backend,
    )
    from llm_based_apache_spark_optimization_tpu.serve import GenerationService
    from llm_based_apache_spark_optimization_tpu.serve.backends import (
        EngineBackend,
    )

    cfg, eng = tiny_engine
    svc = GenerationService()
    svc.register("duckdb-nsql",
                 EngineBackend(eng, tok, max_new_tokens=48))
    exec_backend = make_taxi_exec_backend()

    constrained = evaluate_models(
        svc, ["duckdb-nsql"], FOUR_QUERY_SUITE, TAXI_DDL_SYSTEM,
        max_new_tokens=48, exec_backend=exec_backend,
        constrain="spark_sql",
    )["duckdb-nsql"]
    assert constrained.grammar_valid_rate == 100.0
    assert all(c.grammar_valid == 1 for c in constrained.cases)

    unconstrained = evaluate_models(
        svc, ["duckdb-nsql"], FOUR_QUERY_SUITE, TAXI_DDL_SYSTEM,
        max_new_tokens=48, exec_backend=exec_backend,
    )["duckdb-nsql"]
    assert unconstrained.grammar_valid_rate == 0.0

    schema_rep = evaluate_models(
        svc, ["duckdb-nsql"], FOUR_QUERY_SUITE, TAXI_DDL_SYSTEM,
        max_new_tokens=48, exec_backend=exec_backend,
        constrain={"table": "taxi", "columns": list(TAXI_COLUMNS)},
    )["duckdb-nsql"]
    assert schema_rep.grammar_valid_rate == 100.0
    assert schema_rep.executable_rate == 100.0
