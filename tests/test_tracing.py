"""Request-scoped tracing (utils/tracing.py): span trees, head sampling,
Chrome-trace export round-trip, and the scheduler/service integration."""

import json
import threading
import time

import pytest

from llm_based_apache_spark_optimization_tpu.utils.tracing import (
    RequestTrace,
    Tracer,
    new_request_id,
)
from llm_based_apache_spark_optimization_tpu.utils import tracing


def test_request_ids_unique_and_prefixed():
    ids = {new_request_id() for _ in range(100)}
    assert len(ids) == 100
    assert all(i.startswith("req-") for i in ids)


def test_span_tree_records_and_sorts():
    t = RequestTrace("req-x", model="m")
    with t.span("service.generate", model="m"):
        t.add_span("sched.decode", time.perf_counter() - 0.5,
                   time.perf_counter(), output_tokens=3)
    t.event("sched.error", error="Boom")
    doc = t.to_dict()
    assert doc["request_id"] == "req-x" and doc["model"] == "m"
    names = [s["name"] for s in doc["spans"]]
    # Sorted by start: the decode span started before the enclosing
    # service span's END-time recording order.
    assert set(names) == {"service.generate", "sched.decode", "sched.error"}
    decode = next(s for s in doc["spans"] if s["name"] == "sched.decode")
    assert decode["dur_s"] == pytest.approx(0.5, abs=0.05)
    assert decode["attrs"]["output_tokens"] == 3
    assert json.dumps(doc)  # JSONL-exportable


def test_spans_threadsafe_across_threads():
    t = RequestTrace("req-t")

    def worker(i):
        for j in range(50):
            t.add_span(f"lane{i}.s", 0.0, 1.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    [th.start() for th in threads]
    [th.join() for th in threads]
    assert len(t.to_dict()["spans"]) == 200


def test_tracer_head_sampling():
    t0 = Tracer(sample=0.0)
    assert all(t0.begin() is None for _ in range(20))
    t1 = Tracer(sample=1.0)
    assert all(t1.begin() is not None for _ in range(5))
    th = Tracer(sample=0.5, seed=0)
    picks = [th.begin() is not None for _ in range(400)]
    assert 100 < sum(picks) < 300  # genuinely sampled, not all/none


def test_tracer_finish_none_safe_and_ring():
    tr = Tracer(sample=1.0, ring=2)
    assert tr.finish(None) is None
    for i in range(4):
        t = tr.begin(model=f"m{i}")
        tr.finish(t)
    recent = tr.recent()
    assert len(recent) == 2  # ring bounded
    assert tr.stats()["exported"] == 4


def test_chrome_export_roundtrips_traceprof(tmp_path):
    """Acceptance: the exported Chrome trace loads in utils/traceprof.Trace
    (the same parser that reads jax.profiler device traces) — op time
    positive, span names preserved, device_time bounded by wall."""
    from llm_based_apache_spark_optimization_tpu.utils.traceprof import (
        Trace,
    )

    tr = Tracer(sample=1.0, export_dir=str(tmp_path))
    t = tr.begin(model="m")
    with t.span("service.generate"):
        time.sleep(0.01)
    t.add_span("sql.exec", time.perf_counter() - 0.004, time.perf_counter())
    tr.finish(t)
    # Per-request gzipped chrome file + the JSONL append both exist.
    assert (tmp_path / "requests.jsonl").exists()
    assert list(tmp_path.glob("*.trace.json.gz"))
    pt = Trace().load_dir(str(tmp_path))
    assert pt.op_time_s() > 0.0
    assert 0.0 < pt.device_time_s() <= pt.op_time_s() + 1e-9
    names = {n for n, _, _ in pt.top_ops(10)}
    assert {"service.generate", "sql.exec"} <= names


def test_span_helper_noop_without_current_trace():
    # No ambient trace: the span contextmanager must be a free no-op.
    with tracing.span("anything", attr=1):
        pass
    assert tracing.current() is None


def test_use_installs_and_restores():
    t = RequestTrace("req-ctx")
    assert tracing.current() is None
    with tracing.use(t):
        assert tracing.current() is t
        with tracing.span("sql.exec"):
            pass
    assert tracing.current() is None
    assert [s["name"] for s in t.to_dict()["spans"]] == ["sql.exec"]


def test_use_none_marks_decision_no_redraw(monkeypatch):
    """`use(None)` records made-but-UNSAMPLED: a downstream entry point
    (the service under the HTTP layer) must honor it instead of drawing
    a second sample — re-drawing would double the effective rate."""
    from llm_based_apache_spark_optimization_tpu.serve import (
        FakeBackend,
        GenerationService,
    )
    from llm_based_apache_spark_optimization_tpu.utils.tracing import TRACER

    assert not tracing.decided()
    with tracing.use(None):
        assert tracing.decided()
        assert tracing.current() is None
        with tracing.span("never.recorded"):  # still a free no-op
            pass
    assert not tracing.decided()

    svc = GenerationService()
    svc.register("m", FakeBackend(lambda p: "SELECT 1"))
    calls = []
    monkeypatch.setattr(
        TRACER, "begin",
        lambda *a, **k: calls.append(1) or None)
    # HTTP layer drew (unsampled) -> the service must NOT draw again...
    with tracing.use(None):
        svc.generate("m", "q")
    assert calls == []
    # ...but with no upstream decision, the service draws exactly once.
    svc.generate("m", "q")
    assert calls == [1]


def test_stream_context_never_leaks_between_yields(monkeypatch):
    """A library caller's sampled generate_stream must not leave its
    trace installed in the CALLER's context while suspended at a yield —
    generators share the thread's context, so a leaked set would record
    a second, interleaved request's spans into the first one's tree."""
    from llm_based_apache_spark_optimization_tpu.serve import (
        FakeBackend,
        GenerationService,
    )
    from llm_based_apache_spark_optimization_tpu.utils.tracing import TRACER

    svc = GenerationService()
    svc.register("m", FakeBackend(lambda p: "SELECT 1"))
    monkeypatch.setattr(TRACER, "sample", 1.0)  # library path draws
    g1 = svc.generate_stream("m", "one")
    next(g1)
    # Suspended mid-stream: the caller's context must be clean.
    assert tracing.current() is None
    assert not tracing.decided()
    g1.close()


def test_service_records_spans_and_request_id():
    """Driving the service directly under an ambient trace records the
    service span into it, and the GenerateResult echoes the id."""
    from llm_based_apache_spark_optimization_tpu.serve import (
        FakeBackend,
        GenerationService,
    )

    svc = GenerationService()
    svc.register("m", FakeBackend(lambda p: "SELECT 1"))
    t = RequestTrace("req-svc")
    with tracing.use(t):
        res = svc.generate("m", "q", request_id="req-svc")
    assert res.request_id == "req-svc"
    assert "service.generate" in [s["name"] for s in t.to_dict()["spans"]]


@pytest.fixture(scope="module")
def tiny_model_module():
    import jax
    import jax.numpy as jnp

    from llm_based_apache_spark_optimization_tpu.models import TINY, init_params

    return TINY, init_params(TINY, jax.random.key(0), dtype=jnp.float32)


def test_scheduler_records_request_spans(tiny_model_module):
    """The worker thread records queue-wait / prefill / decode / per-round
    spans into a submitted trace, and stamps the measured queue wait on
    the future (the Completion/metrics seam)."""
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )

    cfg, params = tiny_model_module
    t = RequestTrace("req-sched")
    with ContinuousBatchingScheduler(
        cfg, params, num_slots=2, prompt_bucket=8, decode_chunk=4,
        stop_ids=(-1,),
    ) as sched:
        fut = sched.submit([1, 2, 3], max_new_tokens=6, trace=t)
        out = fut.result(timeout=120)
    assert len(out) == 6
    names = [s["name"] for s in t.to_dict()["spans"]]
    assert "sched.queue_wait" in names
    assert "sched.prefill" in names
    assert "sched.decode" in names
    assert "sched.round" in names
    assert getattr(fut, "_lsot_queue_wait") >= 0.0
    assert getattr(fut, "_lsot_replica") == "r0"
    decode = next(s for s in t.to_dict()["spans"]
                  if s["name"] == "sched.decode")
    assert decode["attrs"]["output_tokens"] == 6


def test_supervised_scheduler_forwards_trace(tiny_model_module):
    """The supervisor forwards a sampled trace to the inner attempt and
    copies the measured queue wait onto its own client-facing future."""
    from llm_based_apache_spark_optimization_tpu.serve.scheduler import (
        ContinuousBatchingScheduler,
    )
    from llm_based_apache_spark_optimization_tpu.serve.supervisor import (
        SupervisedScheduler,
    )

    cfg, params = tiny_model_module

    def make():
        return ContinuousBatchingScheduler(
            cfg, params, num_slots=2, prompt_bucket=8, decode_chunk=4,
            stop_ids=(-1,),
        )

    sup = SupervisedScheduler(make, stall_min_s=0).start()
    try:
        t = RequestTrace("req-sup")
        fut = sup.submit([1, 2, 3], max_new_tokens=4, trace=t)
        fut.result(timeout=120)
        assert "sched.decode" in [s["name"] for s in t.to_dict()["spans"]]
        assert getattr(fut, "_lsot_queue_wait") >= 0.0
    finally:
        sup.shutdown()
